"""Capacity-based sparse MoE dispatch (GShard/Switch-style) for TPU.

The dense-compute MoE in ``models/llama.py:_moe_mlp`` evaluates every
expert on every token — fine at test scale, E/k-times wasted FLOPs at
Mixtral scale. This module is the expert-parallel execution path
(SURVEY.md §2.3: EP "No" in the reference; north star Mixtral-8x7B EP on
v5e-16): tokens are routed into fixed-capacity per-expert buffers with
one-hot dispatch/combine tensors, so the whole layer is einsums with
static shapes — exactly the form GSPMD partitions well. With expert
weights sharded on the ``expert`` mesh axis (parallel/tp.py:
``llama_param_specs``) and the dispatched buffer constrained to
``P('expert', None, None)``, XLA inserts the all-to-all dispatch/combine
over ICI; no manual collectives.

Capacity semantics: each expert processes at most C tokens per step;
assignments beyond C are dropped (the token keeps its residual stream,
standard GShard behavior). Choice-major priority — every token's first
choice is buffered before any token's second choice.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


def expert_capacity(
    num_tokens: int, num_experts: int, k: int, capacity_factor: float
) -> int:
    """Static per-expert buffer size: ceil(tokens*k/E) * factor, floored at
    k so a single-token batch always fits."""
    base = -(-num_tokens * k // num_experts)
    return max(k, int(base * capacity_factor))


def moe_mlp_ep(
    x: jnp.ndarray,
    layer: Dict[str, jnp.ndarray],
    num_experts: int,
    num_experts_per_tok: int,
    *,
    capacity: int,
    shard_experts: bool = False,
    valid_tokens: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Sparse-dispatch SwiGLU MoE over ``layer``'s stacked expert weights.

    Args:
      x: [B, T, H] activations.
      layer: dict with ``router`` [H, E], ``w_gate``/``w_up`` [E, H, I],
        ``w_down`` [E, I, H] (one scan layer of ``llama.init_params``).
      capacity: per-expert token buffer size (see ``expert_capacity``).
      shard_experts: add a ``P('expert', ...)`` sharding constraint on the
        dispatched buffer so GSPMD materializes the all-to-all when running
        inside a mesh context (no-op semantics otherwise).
      valid_tokens: optional [B, T] bool; False rows (bucket padding,
        inactive decode slots) are excluded from routing so garbage tokens
        never consume expert capacity and crowd out live ones. Their
        output rows are zero (callers already discard them).

    Returns [B, T, H], same routing math as the dense path (softmax over
    the top-k logits), so the two agree exactly when nothing is dropped.
    """
    B, T, H = x.shape
    E, k, C = num_experts, num_experts_per_tok, capacity
    N = B * T
    xf = x.reshape(N, H)

    router_logits = (xf @ layer["router"]).astype(jnp.float32)  # [N, E]
    top_logits, top_idx = lax.top_k(router_logits, k)
    gates = jax.nn.softmax(top_logits, axis=-1)  # [N, k]

    # Choice-major queue positions: all first choices rank before any
    # second choice, FIFO within a choice.
    onehot = jax.nn.one_hot(top_idx, E, dtype=jnp.int32)  # [N, k, E]
    if valid_tokens is not None:
        onehot = onehot * valid_tokens.reshape(N, 1, 1).astype(jnp.int32)
    flat = onehot.transpose(1, 0, 2).reshape(k * N, E)  # [kN, E]
    pos = jnp.cumsum(flat, axis=0) - flat  # rank within expert queue
    keep = (pos < C) & (flat > 0)  # [kN, E]

    slot = jax.nn.one_hot(jnp.clip(pos, 0, C - 1), C, dtype=jnp.float32)
    d_flat = keep[..., None] * slot  # [kN, E, C]
    dispatch = d_flat.reshape(k, N, E, C).sum(0)  # [N, E, C] 0/1
    combine = (
        (gates.T.reshape(k * N, 1, 1) * d_flat).reshape(k, N, E, C).sum(0)
    )  # [N, E, C]

    # dispatch → expert buffers (the all-to-all boundary under EP)
    expert_in = jnp.einsum(
        "nec,nh->ech", dispatch.astype(x.dtype), xf
    )  # [E, C, H]
    if shard_experts:
        expert_in = lax.with_sharding_constraint(
            expert_in, P("expert", None, None)
        )
    from distributed_inference_server_tpu.ops.quant import dense_view

    gate = jax.nn.silu(
        jnp.einsum(
            "ech,ehi->eci", expert_in, dense_view(layer["w_gate"], x.dtype)
        )
    )
    up = jnp.einsum(
        "ech,ehi->eci", expert_in, dense_view(layer["w_up"], x.dtype)
    )
    expert_out = jnp.einsum(
        "eci,eih->ech", gate * up, dense_view(layer["w_down"], x.dtype)
    )
    if shard_experts:
        expert_out = lax.with_sharding_constraint(
            expert_out, P("expert", None, None)
        )

    out = jnp.einsum(
        "ech,nec->nh", expert_out, combine.astype(expert_out.dtype)
    )
    return out.reshape(B, T, H)
