"""Pallas/Mosaic TPU kernels — the framework's native-kernel tier.

The reference planned to reach native compute through llama.cpp's C++
kernels over FFI (``design.md:7``, ``tasks.md:196-200`` [spec]); on TPU the
equivalent tier is Pallas kernels lowered through Mosaic. Every kernel here
has a pure-XLA reference implementation (ops/attention.py et al.) it is
tested against, and runs in interpret mode on the CPU backend.
"""

from distributed_inference_server_tpu.ops.pallas.paged_attention import (
    paged_attention_decode,
    paged_attention_prefill,
)

__all__ = ["paged_attention_decode", "paged_attention_prefill"]
