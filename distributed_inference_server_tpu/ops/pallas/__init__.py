"""Pallas/Mosaic TPU kernels — the framework's native-kernel tier.

The reference planned to reach native compute through llama.cpp's C++
kernels over FFI (``design.md:7``, ``tasks.md:196-200`` [spec]); on TPU the
equivalent tier is Pallas kernels lowered through Mosaic. Every kernel here
has a pure-XLA reference implementation (ops/attention.py et al.) it is
tested against, and runs in interpret mode on the CPU backend.

Scope is deliberate: kernels exist where XLA's compilation model cannot
express the access pattern — paged attention reads scattered KV pages
straight from the HBM pool with manual double-buffered DMA, which the
XLA alternative can only approximate by materializing a dense
``[B, S_max]`` gather per layer per step. RMSNorm, RoPE, sampling, and
on-the-fly dequantization intentionally stay XLA: they are elementwise
chains adjacent to matmuls, exactly what XLA fuses into operand
reads/writes on its own, and a hand kernel there starts from parity at
best (SURVEY §7.1 planned four kernels; measurement on the chip — the
r1 lesson that an unproven kernel can ship slower than the fusion it
replaces — set this boundary instead).
"""

from distributed_inference_server_tpu.ops.pallas.paged_attention import (
    paged_attention_decode,
    paged_attention_prefill,
)

__all__ = ["paged_attention_decode", "paged_attention_prefill"]
