"""Pallas/Mosaic TPU kernels — the framework's native-kernel tier.

The reference planned to reach native compute through llama.cpp's C++
kernels over FFI (``design.md:7``, ``tasks.md:196-200`` [spec]); on TPU the
equivalent tier is Pallas kernels lowered through Mosaic. Every kernel here
has a pure-XLA reference implementation (ops/attention.py et al.) it is
tested against, and runs in interpret mode on the CPU backend.

Two classes of kernel, with different defaults:

- **Paged attention (default-on via the engine's "auto" probe)** — XLA's
  compilation model cannot express the access pattern: the kernels read
  scattered KV pages straight from the HBM pool with manual
  double-buffered DMA, where the XLA alternative materializes a dense
  ``[B, S_max]`` gather per layer per step.
- **Fused RMSNorm / RoPE / group-dequant matmul (opt-in,
  DIS_TPU_PALLAS_FUSED=1)** — these sit where XLA's own fusion usually
  already wins (elementwise chains welded to matmul operand reads), so
  the default stays XLA; the kernels complete SURVEY §2.3's native-tier
  inventory and exist for the geometries where
  ``tools/kernel_probe.py``'s on-chip comparison says they pay — the
  dequant matmul in particular guards against XLA fusion misses that
  materialize dense bf16 tiles at 2-4x the quantized HBM bytes. The r1
  lesson stands: none of these flips on without a measured number.
"""

from distributed_inference_server_tpu.ops.pallas.fused import (
    apply_rope_pallas,
    fused_mode,
    quant_matmul_pallas,
    quant_matmul_supported,
    rms_norm_pallas,
)
from distributed_inference_server_tpu.ops.pallas.paged_attention import (
    paged_attention_decode,
    paged_attention_prefill,
    paged_attention_ragged,
)

__all__ = [
    "paged_attention_decode",
    "paged_attention_prefill",
    "paged_attention_ragged",
    "rms_norm_pallas",
    "apply_rope_pallas",
    "quant_matmul_pallas",
    "quant_matmul_supported",
    "fused_mode",
]
