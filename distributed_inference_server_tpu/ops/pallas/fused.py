"""Pallas TPU kernels for the non-attention hot ops: RMSNorm, RoPE, and
group-dequant matmul (int8 / packed-int4).

These complete the native-kernel tier SURVEY.md §2.3 commits to ("Pallas
kernels: paged/ragged attention, RMSNorm, RoPE application, dequant-matmul
(int8/int4)" — the TPU equivalents of the llama.cpp C++ kernels the
reference planned to reach over FFI, design.md:7 [spec]). They are
**opt-in** (`DIS_TPU_PALLAS_FUSED=1`): XLA already fuses RMSNorm / RoPE /
dequant into neighbouring ops, so the honest default is the fused XLA
path; these kernels exist for (a) geometries where the measured number
says otherwise — `tools/kernel_probe.py` compares both on the real chip —
and (b) single-device quantized decode, where a fusion miss in XLA's
dequant (materializing the dense tile in HBM) costs 2-4x the weight
bytes. All three are single-device kernels: GSPMD cannot partition an
opaque `pallas_call`, so under a tensor mesh callers must keep the XLA
path (the paged-attention kernels solve this with an explicit shard_map
wrap; these ops are cheap enough that the wrap has no payoff).

Every kernel keeps Mosaic's tiling rules in mind the same way
paged_attention.py does: last dim a multiple of 128 where it matters,
no sub-128 lane slicing (the RoPE kernel takes the two head-dim halves
as separate refs instead of slicing 32-lane windows), leading-dim-only
reshapes inside kernel bodies.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def fused_mode() -> str | None:
    """Trace-time switch for the opt-in fused kernels.

    DIS_TPU_PALLAS_FUSED=1        -> "compiled" on a SINGLE-device TPU
                                      backend (GSPMD cannot partition an
                                      opaque pallas_call, so the flag is
                                      ignored — XLA path — the moment
                                      more than one device is visible)
    DIS_TPU_PALLAS_FUSED=interpret -> "interpret" on any backend (tests:
                                      exercises the exact dispatch path
                                      off-TPU)
    unset/0                        -> None (XLA fused path)
    """
    v = os.environ.get("DIS_TPU_PALLAS_FUSED", "0")
    if v == "interpret":
        return "interpret"
    if (
        v == "1"
        and jax.default_backend() == "tpu"
        and jax.device_count() == 1
    ):
        return "compiled"
    return None


# ----------------------------------------------------------------------
# RMSNorm
# ----------------------------------------------------------------------


def _rms_norm_kernel(x_ref, w_ref, o_ref, *, eps: float):
    x = x_ref[...].astype(jnp.float32)  # [BM, H]
    ms = jnp.mean(x * x, axis=-1, keepdims=True)
    o_ref[...] = (x * lax.rsqrt(ms + eps) * w_ref[...].astype(jnp.float32)
                  ).astype(o_ref.dtype)


def _row_block(m: int, cap: int = 256) -> int:
    """Largest divisor of ``m`` that is <= cap and a multiple of 8 (or
    ``m`` itself when m < 8 — Mosaic pads sublanes)."""
    if m <= 8:
        return m
    best = 8 if m % 8 == 0 else 0
    b = 8
    while b < cap:
        b += 8
        if m % b == 0:
            best = b
    return best or m


@functools.partial(jax.jit, static_argnames=("eps", "interpret"))
def rms_norm_pallas(
    x: jnp.ndarray, weight: jnp.ndarray, eps: float = 1e-6,
    interpret: bool = False,
) -> jnp.ndarray:
    """RMSNorm over the last axis. x: [..., H]; weight: [H]."""
    orig_shape = x.shape
    H = orig_shape[-1]
    x2 = x.reshape(-1, H)
    M = x2.shape[0]
    BM = _row_block(M)
    out = pl.pallas_call(
        functools.partial(_rms_norm_kernel, eps=eps),
        grid=(M // BM,),
        in_specs=[
            pl.BlockSpec((BM, H), lambda m: (m, 0)),
            pl.BlockSpec((1, H), lambda m: (0, 0)),
        ],
        out_specs=pl.BlockSpec((BM, H), lambda m: (m, 0)),
        out_shape=jax.ShapeDtypeStruct((M, H), x.dtype),
        interpret=interpret,
    )(x2, weight.reshape(1, H))
    return out.reshape(orig_shape)


# ----------------------------------------------------------------------
# RoPE (half-split convention, matching ops/rotary.apply_rope)
# ----------------------------------------------------------------------


def _rope_kernel(pos_ref, x1_ref, x2_ref, inv_ref, o1_ref, o2_ref):
    # rows = flattened (seq, head); each row rotates by its position
    pos = pos_ref[...].astype(jnp.float32)  # [BM, 1]
    inv = inv_ref[...].astype(jnp.float32)  # [1, half]
    ang = pos * inv  # [BM, half]
    c, s = jnp.cos(ang), jnp.sin(ang)
    x1 = x1_ref[...].astype(jnp.float32)
    x2 = x2_ref[...].astype(jnp.float32)
    o1_ref[...] = (x1 * c - x2 * s).astype(o1_ref.dtype)
    o2_ref[...] = (x2 * c + x1 * s).astype(o2_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def apply_rope_pallas(
    x: jnp.ndarray, positions: jnp.ndarray, inv_freq: jnp.ndarray,
    interpret: bool = False,
) -> jnp.ndarray:
    """Half-split RoPE: x [..., seq, heads, D], positions [..., seq],
    inv_freq [D/2]. Sin/cos are computed in VMEM per row block — nothing
    position-dependent is materialized in HBM. The two head-dim halves
    travel as separate refs (Mosaic rejects sub-128 lane slicing for the
    D=64 models; two D/2-lane refs sidestep it the same way the
    attention kernels' block-diagonal trick does)."""
    *lead, T, nh, D = x.shape
    half = D // 2
    pos = jnp.broadcast_to(
        positions[..., None], (*lead, T, nh)
    ).reshape(-1, 1)
    x2d = x.reshape(-1, D)
    M = x2d.shape[0]
    BM = _row_block(M)
    o1, o2 = pl.pallas_call(
        _rope_kernel,
        grid=(M // BM,),
        in_specs=[
            pl.BlockSpec((BM, 1), lambda m: (m, 0)),
            pl.BlockSpec((BM, half), lambda m: (m, 0)),
            pl.BlockSpec((BM, half), lambda m: (m, 0)),
            pl.BlockSpec((1, half), lambda m: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((BM, half), lambda m: (m, 0)),
            pl.BlockSpec((BM, half), lambda m: (m, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((M, half), x.dtype),
            jax.ShapeDtypeStruct((M, half), x.dtype),
        ],
        interpret=interpret,
    )(pos.astype(jnp.int32), x2d[:, :half], x2d[:, half:],
      inv_freq.reshape(1, half))
    return jnp.concatenate([o1, o2], axis=-1).reshape(*lead, T, nh, D)


# ----------------------------------------------------------------------
# Group-dequant matmul: x @ dequant(Wq)
# ----------------------------------------------------------------------


def _q8_matmul_kernel(x_ref, q_ref, s_ref, o_ref, acc_ref, *, n_k: int):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _zero():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    qt = q_ref[...]  # [BK, BN] int8
    st = s_ref[...].astype(jnp.float32)  # [BK//G, BN]
    groups, BN = st.shape
    BK = qt.shape[0]
    deq = (
        qt.astype(jnp.float32).reshape(groups, BK // groups, BN)
        * st[:, None, :]
    ).reshape(BK, BN)
    acc_ref[...] += lax.dot(
        x_ref[...].astype(jnp.bfloat16), deq.astype(jnp.bfloat16),
        preferred_element_type=jnp.float32,
    )

    @pl.when(k == n_k - 1)
    def _emit():
        o_ref[...] = acc_ref[...].astype(o_ref.dtype)


def _q4_matmul_kernel(x_ref, q_ref, s_ref, o_ref, acc_ref, *, n_k: int):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _zero():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    packed = q_ref[...]  # [BK//2, BN] uint8: low nibble=even k, high=odd
    st = s_ref[...].astype(jnp.float32)  # [BK//G, BN]
    groups, BN = st.shape
    halfk = packed.shape[0]
    low = (packed & 0xF).astype(jnp.int8)
    high = (packed >> 4).astype(jnp.int8)
    low = jnp.where(low > 7, low - 16, low)
    high = jnp.where(high > 7, high - 16, high)
    # interleave to k order: row 2i = low_i, 2i+1 = high_i (quant.py pack)
    q = jnp.stack([low, high], axis=1).reshape(halfk * 2, BN)
    BK = halfk * 2
    deq = (
        q.astype(jnp.float32).reshape(groups, BK // groups, BN)
        * st[:, None, :]
    ).reshape(BK, BN)
    acc_ref[...] += lax.dot(
        x_ref[...].astype(jnp.bfloat16), deq.astype(jnp.bfloat16),
        preferred_element_type=jnp.float32,
    )

    @pl.when(k == n_k - 1)
    def _emit():
        o_ref[...] = acc_ref[...].astype(o_ref.dtype)


def _tile(n: int, cap: int, mult: int) -> int:
    """Largest divisor of n that is <= cap and a multiple of ``mult``;
    0 when none exists (caller falls back to XLA)."""
    best = 0
    b = mult
    while b <= min(n, cap):
        if n % b == 0:
            best = b
        b += mult
    return best


def quant_matmul_supported(M: int, K: int, N: int, group: int,
                           packed: bool) -> bool:
    """Static dispatch check: every dim must admit an aligned tiling."""
    if _row_block(M) % 8 and M > 8:
        return False
    kmult = max(group, 256 if packed else 128)
    return (_tile(K, 2048, kmult) > 0 and _tile(N, 512, 128) > 0
            and K % group == 0)


@functools.partial(
    jax.jit, static_argnames=("group", "packed", "interpret")
)
def quant_matmul_pallas(
    x: jnp.ndarray, q: jnp.ndarray, s: jnp.ndarray, group: int,
    packed: bool = False, interpret: bool = False,
) -> jnp.ndarray:
    """x [M, K] @ dequant(q, s) -> [M, N] in x.dtype.

    q: [K, N] int8, or [K/2, N] uint8 when ``packed`` (two int4 along K,
    quantize_int4's layout). s: [K/group, N] scales. Dequant happens in
    VMEM after the int tile's DMA — HBM traffic stays at the quantized
    byte count even if XLA would have failed to fuse (its failure mode
    materializes dense bf16 tiles, 2-4x the bytes of the int read)."""
    M, K = x.shape
    N = s.shape[-1]
    BM = _row_block(M)
    BK = _tile(K, 2048, max(group, 256 if packed else 128))
    BN = _tile(N, 512, 128)
    n_k = K // BK
    kern = _q4_matmul_kernel if packed else _q8_matmul_kernel
    qspec = (
        pl.BlockSpec((BK // 2, BN), lambda m, n, k: (k, n)) if packed
        else pl.BlockSpec((BK, BN), lambda m, n, k: (k, n))
    )
    return pl.pallas_call(
        functools.partial(kern, n_k=n_k),
        grid=(M // BM, N // BN, n_k),
        in_specs=[
            pl.BlockSpec((BM, BK), lambda m, n, k: (m, k)),
            qspec,
            pl.BlockSpec((BK // group, BN), lambda m, n, k: (k, n)),
        ],
        out_specs=pl.BlockSpec((BM, BN), lambda m, n, k: (m, n)),
        out_shape=jax.ShapeDtypeStruct((M, N), x.dtype),
        scratch_shapes=[pltpu.VMEM((BM, BN), jnp.float32)],
        interpret=interpret,
    )(x, q, s)
