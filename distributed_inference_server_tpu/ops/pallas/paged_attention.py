"""Ragged paged-attention decode kernel (Pallas / Mosaic TPU), v2.

The serving hot loop's attention: one new query token per sequence attends
to that sequence's KV pages scattered through the HBM page pool. The
pure-XLA path (``models/llama.py:paged_forward``) first gathers every
sequence's pages into a dense ``[B, S_max, KV, D]`` buffer and then runs
dense attention — materializing S_max slots per row in HBM each step and
paying the write+read round trip. This kernel reads pages straight from
the pool instead.

Decode kernel v3 design (v1 drowned in grid overhead — B x P grid steps
of one page each; v2 blocked the DMA but sliced 64-wide per-head lane
windows, which Mosaic rejects for head_dim-64 models — "slice shape must
be aligned to tiling (128)"):

- **Grid = (B,)**: one grid step per sequence; the page loop runs inside
  the kernel as a ``fori_loop`` with a *dynamic* trip count covering only
  the row's valid pages — rows attend exactly as far as they are long
  (the ragged contract), and short rows cost proportionally less.
- **Manual double-buffered DMA**: the page pools stay in HBM
  (``memory_space=ANY``); each loop iteration copies a *block* of
  ``pages_per_block`` pages (chosen by the scalar-prefetched block table)
  into one of two VMEM buffers with ``make_async_copy`` while the MXU
  works on the previous block — the classic overlap pattern, with
  per-page semaphores because the pages are scattered.
- **Block-diagonal GQA**: pages are DMA'd with heads folded into lanes
  ([page_size, KV*D] — always 128-aligned for serving geometries), and
  the query enters pre-expanded to a block-diagonal [H, KV*D] so the
  whole batch of heads is TWO aligned MXU dots per KV block: scores
  [H,KV*D]x[T,KV*D]^T and values [H,T]x[T,KV*D]. No per-head slicing
  anywhere in the kernel; the wrapper extracts each head's diagonal
  lane block afterwards. The KV-fold multiplies attention FLOPs by KV,
  which is free in practice: decode attention is HBM-DMA-bound and the
  tiny per-head matmuls of v2 were far below MXU tile size anyway.
- **bf16 on the MXU**: q/k/v enter the dots in their native dtype with
  ``preferred_element_type=f32`` accumulation.
- Online-softmax accumulation (flash-attention style) across blocks in
  f32 VMEM scratch; causal masking implied by the ragged ``kv_valid_len``
  (the query IS the last valid token — decode only).

Replaces the reference's planned llama.cpp attention (design.md:7 [spec])
as the native tier; same contract as ops/attention.py:gqa_attention.
Kernel shape follows the ragged-paged-attention recipe (PAPERS.md).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from distributed_inference_server_tpu.utils.compat import tpu_compiler_params

_NEG_INF = -1e30
_LANES = 128  # VPU lane width; scratch statistics are broadcast across lanes


def _decode_kernel(
    # scalar-prefetch refs (SMEM)
    tables_ref,  # [B, P] page id per (row, page-slot)
    valid_ref,  # [B] valid token count per row
    window_ref,  # [1] sliding window (0 = full causal; runtime so Gemma-2
    #              per-layer windows flow through one compiled program)
    # tensor refs, then scratch — layout depends on `quantized`:
    #   dense:  qbd, k_hbm, v_hbm, out,
    #           k_buf, v_buf, sem_k, sem_v, m, l, acc
    #   int8:   qbd, k_hbm, v_hbm, ks_hbm, vs_hbm, out,
    #           k_buf, v_buf, ks_buf, vs_buf,
    #           sem_k, sem_v, sem_ks, sem_vs, m, l, acc
    # where ks/vs are the QuantPool scale pages [num_pages, ps, KV] f32
    # and k/v carry int8 codes (engine/kv_cache.py QuantPool layout)
    *refs,
    page_size: int,
    pages_per_block: int,
    num_page_slots: int,
    head_dim: int,
    attn_softcap: float = 0.0,
    quantized: bool = False,
):
    """v3 body: block-diagonal GQA — every shape Mosaic-tile-aligned.

    The query arrives pre-expanded (host XLA) to [H, KV*D], row h = kv*G+g
    holding q_h in lanes [kv*D, (kv+1)*D) and zeros elsewhere. One
    [H, KV*D] x [KV*D, T] MXU dot then yields exactly the per-head scores
    (zero lanes null the cross-head terms) without slicing the KV/head
    dimension anywhere — the per-head lane slices of v2 were 64-wide for
    head_dim-64 models, which Mosaic rejects (tiling is 128). The extra
    FLOPs (contraction over KV*D instead of D) are irrelevant: decode
    attention is DMA-bound, the MXU idles either way.

    Int8 mode (``quantized``): K/V pages carry int8 codes and separate
    per-(token, head) f32 scale pages ride their own (much smaller) DMAs —
    HALF the attention DMA bytes, the bound this kernel lives under. The
    codes are cast to bf16 for the MXU and the scales are folded in
    WITHOUT any lane-crossing reshape: score[h, t] needs k_scale[t, kv(h)]
    and the PV accumulation needs probs[h, t] * v_scale[t, kv(h)], both
    of which are one [H, KV] x [KV, T] one-hot MXU dot per block (the
    head->kv map) multiplied elementwise into the score/prob matrix.
    Cross-head lanes of the accumulator pick up wrongly-scaled garbage —
    exactly the lanes the wrapper already discards."""
    if quantized:
        (qbd_ref, k_hbm, v_hbm, ks_hbm, vs_hbm, out_ref,
         k_buf, v_buf, ks_buf, vs_buf, sem_k, sem_v, sem_ks, sem_vs,
         m_ref, l_ref, acc_ref) = refs
    else:
        (qbd_ref, k_hbm, v_hbm, out_ref,
         k_buf, v_buf, sem_k, sem_v, m_ref, l_ref, acc_ref) = refs
    b = pl.program_id(0)
    PB = pages_per_block
    blk_tokens = PB * page_size

    valid = valid_ref[b]
    num_blocks = lax.div(valid + blk_tokens - 1, blk_tokens)
    # sliding window: the decode query sits at position valid-1, so only
    # tokens >= valid - window are attended; skip whole blocks below it.
    # win_lo stays 0 for full-causal layers, making the mask a no-op.
    w = window_ref[0]
    win_lo = jnp.where(w > 0, jnp.maximum(valid - w, 0), 0)
    first_block = lax.div(win_lo, blk_tokens)

    m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
    l_ref[:] = jnp.zeros_like(l_ref)
    acc_ref[:] = jnp.zeros_like(acc_ref)

    def start_block(slot, blk):
        # PB scattered pages -> PB independent DMAs into adjacent buffer
        # rows; page ids come from the scalar-prefetched table (clamped by
        # the driver, so entries past the row's last page are in-range and
        # merely masked at compute time)
        for i in range(PB):
            page = tables_ref[b, jnp.minimum(blk * PB + i,
                                             num_page_slots - 1)]
            pltpu.make_async_copy(
                k_hbm.at[page], k_buf.at[slot, i], sem_k.at[slot, i]
            ).start()
            pltpu.make_async_copy(
                v_hbm.at[page], v_buf.at[slot, i], sem_v.at[slot, i]
            ).start()
            if quantized:
                pltpu.make_async_copy(
                    ks_hbm.at[page], ks_buf.at[slot, i], sem_ks.at[slot, i]
                ).start()
                pltpu.make_async_copy(
                    vs_hbm.at[page], vs_buf.at[slot, i], sem_vs.at[slot, i]
                ).start()

    def wait_block(slot, blk):
        for i in range(PB):
            page = tables_ref[b, jnp.minimum(blk * PB + i,
                                             num_page_slots - 1)]
            pltpu.make_async_copy(
                k_hbm.at[page], k_buf.at[slot, i], sem_k.at[slot, i]
            ).wait()
            pltpu.make_async_copy(
                v_hbm.at[page], v_buf.at[slot, i], sem_v.at[slot, i]
            ).wait()
            if quantized:
                pltpu.make_async_copy(
                    ks_hbm.at[page], ks_buf.at[slot, i], sem_ks.at[slot, i]
                ).wait()
                pltpu.make_async_copy(
                    vs_hbm.at[page], vs_buf.at[slot, i], sem_vs.at[slot, i]
                ).wait()

    @pl.when(num_blocks > first_block)
    def _run():
        qbd = qbd_ref[0] * (1.0 / (head_dim**0.5))  # [H, KV*D]
        if quantized:
            # head -> kv-head map as a one-hot [H, KV] (static iota
            # compare): row h = kv*G + g selects column kv
            H, CD = qbd_ref.shape[1], qbd_ref.shape[2]
            KV = CD // head_dim
            G = H // KV
            head_onehot = (
                lax.broadcasted_iota(jnp.int32, (H, KV), 0) // G
                == lax.broadcasted_iota(jnp.int32, (H, KV), 1)
            ).astype(jnp.float32)
        start_block(lax.rem(first_block, 2), first_block)

        def loop(blk, _):
            slot = lax.rem(blk, 2)

            @pl.when(blk + 1 < num_blocks)
            def _prefetch():
                start_block(lax.rem(blk + 1, 2), blk + 1)

            wait_block(slot, blk)
            start = blk * blk_tokens

            k = k_buf[slot].reshape(blk_tokens, -1)  # [T, KV*D]
            v = v_buf[slot].reshape(blk_tokens, -1)
            if quantized:
                k = k.astype(jnp.bfloat16)
                v = v.astype(jnp.bfloat16)

            # [H, T] scores in ONE MXU dot; block-diagonal q rows contract
            # only their own head's lanes
            s = lax.dot_general(
                qbd.astype(k.dtype), k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            if quantized:
                # fold k scales in: score[h, t] *= k_scale[t, kv(h)],
                # realized as onehot[H, KV] @ kscale[T, KV]^T — one tiny
                # MXU dot, no lane-crossing reshape
                ksc = ks_buf[slot].reshape(blk_tokens, -1)  # [T, KV]
                s = s * lax.dot_general(
                    head_onehot, ksc, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
            if attn_softcap:
                s = jnp.tanh(s * (1.0 / attn_softcap)) * attn_softcap
            token_ids = start + lax.broadcasted_iota(jnp.int32, s.shape, 1)
            ok = (token_ids < valid) & (token_ids >= win_lo)
            s = jnp.where(ok, s, _NEG_INF)

            m_prev = m_ref[:, :1]  # [H, 1]
            l_prev = l_ref[:, :1]
            m_cur = jnp.max(s, axis=-1, keepdims=True)
            m_new = jnp.maximum(m_prev, m_cur)
            alpha = jnp.exp(m_prev - m_new)
            probs = jnp.exp(s - m_new)  # [H, T] f32
            l_new = l_prev * alpha + jnp.sum(probs, -1, keepdims=True)
            if quantized:
                # fold v scales into the probabilities: row h's own-head
                # lanes then accumulate sum(p * v_scale * codes) exactly;
                # cross-head lanes get wrongly-scaled garbage the wrapper
                # discards anyway
                vsc = vs_buf[slot].reshape(blk_tokens, -1)  # [T, KV]
                probs = probs * lax.dot_general(
                    head_onehot, vsc, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
            # [H, KV*D]: row h accumulates its own head's V in the diagonal
            # lane block (other lanes carry cross-head garbage the wrapper
            # discards)
            acc_ref[:] = acc_ref[:] * alpha + lax.dot_general(
                probs.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
            l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)
            return 0

        lax.fori_loop(first_block, num_blocks, loop, 0)

    l = jnp.maximum(l_ref[:, :1], 1e-30)  # rows with valid=0 emit zeros
    out_ref[0] = (acc_ref[:] / l).astype(out_ref.dtype)


def _prefill_kernel(
    # scalar-prefetch refs (SMEM)
    tables_ref,  # [B, P] page id per (row, page-slot)
    valid_ref,  # [B] valid token count per row (incl. this chunk)
    qstart_ref,  # [B] global position of the chunk's first query
    window_ref,  # [1] sliding window (0 = full causal; runtime scalar)
    # tensor refs
    qbd_ref,  # [1, 1, R, CD] this (row, head-chunk, q-block)'s
    #           block-diagonal query tile (VMEM); R = TQ*C*G
    k_hbm,  # [num_pages, page_size, KV*D] full K pool (HBM)
    v_hbm,  # [num_pages, page_size, KV*D] full V pool (HBM)
    out_ref,  # [1, 1, R, CD] (VMEM; per-head diagonal lanes valid)
    # scratch
    k_buf,  # [2, PB, page_size, CD] double-buffered K page lane-chunks
    v_buf,
    sem_k,  # DMA semaphores [2, PB]
    sem_v,
    *,
    page_size: int,
    pages_per_block: int,
    num_page_slots: int,
    heads_per_chunk: int,
    groups: int,
    head_dim: int,
    attn_softcap: float = 0.0,
):
    """v3 body: like the decode kernel, every shape is tile-aligned by
    folding heads into 128-lane chunks (C = 128/D heads per chunk; C = 1
    for head_dim >= 128). Grid = (B, KV/C, T/TQ); each step DMAs only its
    chunk's lane window of each page (128-aligned dynamic lane slice) and
    runs the whole chunk as two MXU dots over block-diagonal queries —
    the per-head 64-wide lane slices Mosaic rejects never appear."""
    b = pl.program_id(0)
    c = pl.program_id(1)
    qb = pl.program_id(2)
    R, CD = qbd_ref.shape[2], qbd_ref.shape[3]
    C, G, D = heads_per_chunk, groups, head_dim
    TQ = R // (C * G)
    PB = pages_per_block
    blk_tokens = PB * page_size

    valid = valid_ref[b]
    qstart = qstart_ref[b]
    q_base = qstart + qb * TQ  # global position of this tile's first query
    # causal upper bound for the whole tile: the last query's position + 1,
    # clamped by the row's valid length — the KV loop never reads past it
    kv_upper = jnp.minimum(valid, q_base + TQ)
    num_blocks = lax.div(kv_upper + blk_tokens - 1, blk_tokens)
    # sliding window: no query in this tile sees anything before
    # q_base - window + 1, so whole blocks below it are skipped. The
    # window is a runtime scalar (0 = full causal -> first_block 0 and an
    # effectively-infinite mask window).
    w = window_ref[0]
    first_block = lax.div(
        jnp.where(w > 0, jnp.maximum(q_base - w + 1, 0), 0), blk_tokens
    )
    eff_w = jnp.where(w > 0, w, jnp.int32(2**30))

    lane_lo = c * CD  # this head-chunk's 128-aligned lane window

    def start_block(slot, blk):
        for i in range(PB):
            page = tables_ref[b, jnp.minimum(blk * PB + i,
                                             num_page_slots - 1)]
            pltpu.make_async_copy(
                k_hbm.at[page, :, pl.ds(lane_lo, CD)],
                k_buf.at[slot, i], sem_k.at[slot, i]
            ).start()
            pltpu.make_async_copy(
                v_hbm.at[page, :, pl.ds(lane_lo, CD)],
                v_buf.at[slot, i], sem_v.at[slot, i]
            ).start()

    def wait_block(slot, blk):
        for i in range(PB):
            page = tables_ref[b, jnp.minimum(blk * PB + i,
                                             num_page_slots - 1)]
            pltpu.make_async_copy(
                k_hbm.at[page, :, pl.ds(lane_lo, CD)],
                k_buf.at[slot, i], sem_k.at[slot, i]
            ).wait()
            pltpu.make_async_copy(
                v_hbm.at[page, :, pl.ds(lane_lo, CD)],
                v_buf.at[slot, i], sem_v.at[slot, i]
            ).wait()

    # per-row global query position: row r = (t*C + cl)*G + g
    q_pos = q_base + lax.broadcasted_iota(
        jnp.int32, (R, 1), 0
    ) // (C * G)

    m0 = jnp.full((R, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((R, 1), jnp.float32)
    acc0 = jnp.zeros((R, CD), jnp.float32)
    qbd = qbd_ref[0, 0] * (1.0 / (D**0.5))  # [R, CD]

    def loop(blk, carry):
        m, l, acc = carry
        slot = lax.rem(blk, 2)

        @pl.when(blk + 1 < num_blocks)
        def _prefetch():
            start_block(lax.rem(blk + 1, 2), blk + 1)

        wait_block(slot, blk)
        start = blk * blk_tokens
        kv_idx = start + lax.broadcasted_iota(
            jnp.int32, (R, blk_tokens), 1
        )
        mask = (kv_idx <= q_pos) & (kv_idx < valid)
        mask &= kv_idx > q_pos - eff_w

        k = k_buf[slot].reshape(blk_tokens, CD)
        v = v_buf[slot].reshape(blk_tokens, CD)
        # [R, T] scores in ONE MXU dot; block-diagonal q rows contract
        # only their own head's lanes
        s = lax.dot_general(
            qbd.astype(k.dtype), k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if attn_softcap:
            s = jnp.tanh(s * (1.0 / attn_softcap)) * attn_softcap
        s = jnp.where(mask, s, _NEG_INF)

        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_cur)
        alpha = jnp.exp(m - m_new)
        # masked-everything rows: exp(s - m_new) with m_new still -inf
        # would be exp(0); force explicit zeros
        probs = jnp.where(s > _NEG_INF * 0.5, jnp.exp(s - m_new), 0.0)
        l_new = l * alpha + jnp.sum(probs, -1, keepdims=True)
        acc_new = acc * alpha + lax.dot_general(
            probs.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return (m_new, l_new, acc_new)

    def run():
        start_block(lax.rem(first_block, 2), first_block)
        return lax.fori_loop(first_block, num_blocks, loop, (m0, l0, acc0))

    _, l, acc = lax.cond(
        num_blocks > first_block, run, lambda: (m0, l0, acc0)
    )
    out_ref[0, 0] = (acc / jnp.maximum(l, 1e-30)).astype(out_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("page_size", "q_block", "pages_per_block", "interpret",
                     "attn_softcap"),
)
def paged_attention_prefill(
    q: jnp.ndarray,
    pool_k: jnp.ndarray,
    pool_v: jnp.ndarray,
    page_tables: jnp.ndarray,
    q_start: jnp.ndarray,
    kv_valid_len: jnp.ndarray,
    *,
    page_size: int,
    q_block: int = 128,
    pages_per_block: int = 8,
    interpret: bool | None = None,
    sliding_window=0,
    attn_softcap: float = 0.0,
) -> jnp.ndarray:
    """Chunked-prefill paged GQA attention against the flat page pool.

    The XLA prefill path gathers every row's pages into a dense
    ``[B, S_max, KV, D]`` buffer per layer (``models/llama.py``
    ``paged_forward``) — S_max slots materialized in HBM per row however
    short the row. This kernel reads only the pages a query tile can
    causally see, with the same double-buffered scattered-page DMA as the
    decode kernel (VERDICT r1: "no prefill/chunked-prefill kernel").

    Contract: queries are a CONTIGUOUS chunk of positions per row —
    query t of row b sits at global position ``q_start[b] + t`` (the
    engine's chunked/batched prefill layout). K/V for the chunk must
    already be written to the pool (same ordering as ops/attention.py).

    Args:
      q: [B, T, H, D] query chunk (T >= 1, bucket-padded; padding rows'
        outputs are garbage and discarded by the caller).
      pool_k, pool_v: [num_slots, KV, D] one layer's flat page pool.
      page_tables: [B, P] page ids per row.
      q_start: [B] global position of each row's first query.
      kv_valid_len: [B] valid tokens per row INCLUDING this chunk.
      page_size: tokens per page.
      q_block: queries per grid tile (VMEM residency unit).
      pages_per_block: pages DMA'd per inner-loop step.
      interpret: force Pallas interpret mode; defaults to True off-TPU.

    Returns: [B, T, H, D] attention outputs in q.dtype.
    """
    B, T, H, D = q.shape
    num_slots, KV, _ = pool_k.shape
    G = H // KV
    num_pages = num_slots // page_size
    P = page_tables.shape[1]
    PB = min(pages_per_block, P)
    TQ = min(q_block, T)
    while T % TQ:
        TQ //= 2  # buckets are powers of two; degenerate T still divides
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    # heads per 128-lane chunk: pack small heads (D=64) in pairs so every
    # DMA lane window and MXU operand is tile-aligned; D >= 128 chunks are
    # a single head (no block-diagonal FLOP overhead at all). For
    # geometries that cannot align (tiny test models, odd head counts) we
    # still build the kernel — interpret mode runs anything, and on real
    # TPU the engine's "auto" probe rejects what Mosaic rejects.
    C = max(1, min(_LANES // D, KV))
    while KV % C:
        C -= 1
    KVc = KV // C
    CD = C * D
    R = TQ * C * G  # rows per tile: (query t, chunk-local head cl, group g)

    # Block-diagonal query expansion within each head chunk (plain XLA):
    # row (t, cl, g) carries q[t, c*C+cl, g] in lanes [cl*D, (cl+1)*D).
    eye = jnp.eye(C, dtype=q.dtype)
    qbd = jnp.einsum(
        "btkugd,uj->btkugjd",
        q.reshape(B, T, KVc, C, G, D), eye,
    )  # [B, T, KVc, C, G, C, D]
    qbd = qbd.transpose(0, 2, 1, 3, 4, 5, 6).reshape(B, KVc, T * C * G, CD)
    k_pages = pool_k.reshape(num_pages, page_size, KV * D)
    v_pages = pool_v.reshape(num_pages, page_size, KV * D)
    tables = jnp.clip(page_tables.astype(jnp.int32), 0, num_pages - 1)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(B, KVc, T // TQ),
        in_specs=[
            pl.BlockSpec((1, 1, R, CD),
                         lambda b, c, qb, t, vl, qs, w: (b, c, qb, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((1, 1, R, CD),
                               lambda b, c, qb, t, vl, qs, w: (b, c, qb, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, PB, page_size, CD), pool_k.dtype),
            pltpu.VMEM((2, PB, page_size, CD), pool_v.dtype),
            pltpu.SemaphoreType.DMA((2, PB)),
            pltpu.SemaphoreType.DMA((2, PB)),
        ],
    )

    out_big = pl.pallas_call(
        functools.partial(
            _prefill_kernel,
            page_size=page_size,
            pages_per_block=PB,
            num_page_slots=P,
            heads_per_chunk=C,
            groups=G,
            head_dim=D,
            attn_softcap=attn_softcap,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, KVc, T * C * G, CD), q.dtype),
        interpret=interpret,
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=2 * B * H * T * P * page_size * CD,
            bytes_accessed=2 * B * KV * P * page_size * D
            * pool_k.dtype.itemsize,
            transcendentals=B * H * T * P * page_size,
        ),
    )(
        tables, kv_valid_len.astype(jnp.int32), q_start.astype(jnp.int32),
        jnp.asarray(sliding_window, jnp.int32).reshape(1),
        qbd, k_pages, v_pages,
    )
    # extract each head's diagonal lane block
    out = jnp.einsum(
        "bktugjd,uj->btkugd",
        out_big.reshape(B, KVc, T, C, G, C, D), eye,
    )
    return out.reshape(B, T, H, D)


@functools.partial(
    jax.jit,
    static_argnames=("page_size", "pages_per_block", "interpret",
                     "attn_softcap"),
)
def paged_attention_decode(
    q: jnp.ndarray,
    pool_k: jnp.ndarray,
    pool_v: jnp.ndarray,
    page_tables: jnp.ndarray,
    kv_valid_len: jnp.ndarray,
    *,
    page_size: int,
    pages_per_block: int = 8,
    interpret: bool | None = None,
    sliding_window=0,
    attn_softcap: float = 0.0,
) -> jnp.ndarray:
    """Decode-step paged GQA attention against the flat page pool.

    Args:
      q: [B, H, D] one query per row (the token being decoded).
      pool_k, pool_v: [num_slots, KV, D] one layer's flat page pool
        (num_slots = num_pages * page_size — engine/kv_cache.py layout),
        or ``ops.quant.QuantPool`` (int8 codes + f32 per-vector scales):
        the kernel then DMAs HALF the attention bytes and folds the
        scales into the score/probability matrices on the fly.
        CAVEAT (quantized mode): the scale VMEM scratch and DMA tiles are
        [page_size, KV] with KV typically far below the 128-lane Mosaic
        tile — this lane width is the expected Mosaic rejection point on
        real silicon (all CI runs use interpret=True). Serving gates the
        kernel behind DIS_TPU_KV_QUANT_PALLAS=1 plus an AOT probe with
        XLA fallback; land the KP_KV_QUANT=1 silicon probe before
        widening the opt-in.
      page_tables: [B, P] page ids per row (entries past the row's last
        page may be any value; they are clamped to the pool and masked).
      kv_valid_len: [B] valid tokens per row, INCLUDING the just-written
        query token (the query is causal-last by construction).
      page_size: tokens per page.
      pages_per_block: pages DMA'd and processed per inner-loop step (the
        double-buffered block size; tune for DMA/compute overlap).
      interpret: force Pallas interpret mode; defaults to True off-TPU so
        tests run on the CPU backend.
      sliding_window: attend only the last N positions (0 = full causal).
        May be a TRACED scalar — Gemma-2's per-layer windows flow through
        one compiled program via scalar prefetch.
      attn_softcap: Gemma-2 score soft-capping tanh(s/cap)*cap (0 = off).

    Returns: [B, H, D] attention outputs in q.dtype.
    """
    from distributed_inference_server_tpu.ops.quant import QuantPool

    quantized = isinstance(pool_k, QuantPool)
    k_arr = pool_k.data if quantized else pool_k
    v_arr = pool_v.data if quantized else pool_v
    B, H, D = q.shape
    num_slots, KV, _ = k_arr.shape
    G = H // KV
    CD = KV * D
    num_pages = num_slots // page_size
    P = page_tables.shape[1]
    PB = min(pages_per_block, P)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    # Block-diagonal query expansion (plain XLA — no Mosaic layout rules):
    # qbd[b, kv*G+g, kv*D+d] = q[b, kv*G+g, d], zeros off the diagonal.
    # This is what lets the kernel contract [H, KV*D] x [T, KV*D] in one
    # aligned MXU dot instead of slicing 64-wide per-head lane windows.
    eye = jnp.eye(KV, dtype=q.dtype)
    qbd = jnp.einsum(
        "bkgd,kj->bkgjd", q.reshape(B, KV, G, D), eye
    ).reshape(B, H, CD)
    if quantized:
        k_pages = pool_k.data.reshape(num_pages, page_size, CD)
        v_pages = pool_v.data.reshape(num_pages, page_size, CD)
        ks_pages = pool_k.scale.reshape(num_pages, page_size, KV)
        vs_pages = pool_v.scale.reshape(num_pages, page_size, KV)
        extra_in = [ks_pages, vs_pages]
        extra_in_specs = [
            pl.BlockSpec(memory_space=pl.ANY),  # K scales stay in HBM
            pl.BlockSpec(memory_space=pl.ANY),  # V scales stay in HBM
        ]
        extra_scratch = [
            pltpu.VMEM((2, PB, page_size, KV), jnp.float32),
            pltpu.VMEM((2, PB, page_size, KV), jnp.float32),
        ]
        extra_sems = [
            pltpu.SemaphoreType.DMA((2, PB)),
            pltpu.SemaphoreType.DMA((2, PB)),
        ]
    else:
        k_pages = pool_k.reshape(num_pages, page_size, CD)
        v_pages = pool_v.reshape(num_pages, page_size, CD)
        extra_in, extra_in_specs, extra_scratch, extra_sems = [], [], [], []
    tables = jnp.clip(page_tables.astype(jnp.int32), 0, num_pages - 1)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, H, CD), lambda b, t, vl, w: (b, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),  # K pool stays in HBM
            pl.BlockSpec(memory_space=pl.ANY),  # V pool stays in HBM
            *extra_in_specs,
        ],
        out_specs=pl.BlockSpec((1, H, CD), lambda b, t, vl, w: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, PB, page_size, CD), k_arr.dtype),
            pltpu.VMEM((2, PB, page_size, CD), v_arr.dtype),
            *extra_scratch,
            pltpu.SemaphoreType.DMA((2, PB)),
            pltpu.SemaphoreType.DMA((2, PB)),
            *extra_sems,
            pltpu.VMEM((H, _LANES), jnp.float32),
            pltpu.VMEM((H, _LANES), jnp.float32),
            pltpu.VMEM((H, CD), jnp.float32),
        ],
    )

    out_big = pl.pallas_call(
        functools.partial(
            _decode_kernel,
            page_size=page_size,
            pages_per_block=PB,
            num_page_slots=P,
            head_dim=D,
            attn_softcap=attn_softcap,
            quantized=quantized,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, CD), q.dtype),
        interpret=interpret,
        compiler_params=tpu_compiler_params(
            # rows are independent — scratch state is reset per grid step
            # — so let megacore split the batch
            dimension_semantics=("parallel",),
        ),
        cost_estimate=pl.CostEstimate(
            flops=4 * B * H * P * page_size * CD,
            bytes_accessed=2 * B * KV * P * page_size * D
            * k_arr.dtype.itemsize,
            transcendentals=B * H * P * page_size,
        ),
    )(tables, kv_valid_len.astype(jnp.int32),
      jnp.asarray(sliding_window, jnp.int32).reshape(1),
      qbd, k_pages, v_pages, *extra_in)
    # extract each head's diagonal lane block (the rest is cross-head
    # garbage by construction)
    out = jnp.einsum(
        "bkgjd,kj->bkgd", out_big.reshape(B, KV, G, KV, D), eye
    )
    return out.reshape(B, H, D)


def _ragged_kernel(
    # scalar-prefetch refs (SMEM)
    tables_ref,  # [Bm, P] page id per (row, page-slot)
    valid_ref,  # [Bm] valid token count per row (incl. its new tokens)
    wrow_ref,  # [W] work-item row (-1 = padding item)
    wwin_ref,  # [W] work-item packed-query window
    wfirst_ref,  # [W] 1 = first work item of its window (init the out block)
    window_ref,  # [1] sliding window (0 = full causal)
    # tensor refs
    qbd_ref,  # [1, 1, R, CD] this (window, head-chunk)'s block-diagonal
    #           query tile; R = TQ*C*G
    posr_ref,  # [1, R] per-q-row absolute position (token-expanded)
    rowr_ref,  # [1, R] per-q-row owning batch row (-1 = padding token)
    k_hbm,  # [num_pages, page_size, KV*D] full K pool (HBM)
    v_hbm,  # [num_pages, page_size, KV*D] full V pool (HBM)
    out_ref,  # [1, 1, R, CD] (VMEM; revisited by every segment of the window)
    # scratch
    k_buf,  # [2, PB, page_size, CD]
    v_buf,
    sem_k,
    sem_v,
    *,
    page_size: int,
    pages_per_block: int,
    num_page_slots: int,
    head_dim: int,
    attn_softcap: float = 0.0,
):
    """Ragged mixed-batch body: each grid step is one (window, row)
    SEGMENT — the tokens of one batch row that fall inside one TQ-wide
    window of the packed query axis. Rows are packed back-to-back
    (PackInfer-style), so a window can hold many decode rows (q_len 1
    each) next to a prefill chunk's tail; segments of the same window run
    as consecutive grid steps and read-modify-write the shared out block
    (the first one zero-initializes it). The KV loop covers only the
    segment's row, exactly like the decode/prefill kernels' per-row loop
    — ragged per-row trip counts are the whole point."""
    i = pl.program_id(1)
    R, CD = qbd_ref.shape[2], qbd_ref.shape[3]
    PB = pages_per_block
    blk_tokens = PB * page_size

    b = wrow_ref[i]
    bb = jnp.maximum(b, 0)
    valid = jnp.where(b >= 0, valid_ref[bb], 0)
    pos_r = posr_ref[0].reshape(R, 1)
    row_r = rowr_ref[0].reshape(R, 1)
    belongs = (row_r == b) & (b >= 0)

    # the segment's query-position span bounds the KV loop: nothing past
    # the last query's causal horizon (or the row's valid length) is read
    seg_hi = jnp.max(jnp.where(belongs, pos_r, -1)) + 1
    kv_upper = jnp.minimum(valid, seg_hi)
    num_blocks = lax.div(kv_upper + blk_tokens - 1, blk_tokens)
    w = window_ref[0]
    seg_lo = jnp.min(jnp.where(belongs, pos_r, jnp.int32(2**30)))
    first_block = lax.div(
        jnp.where(w > 0, jnp.maximum(seg_lo - w + 1, 0), 0), blk_tokens
    )
    eff_w = jnp.where(w > 0, w, jnp.int32(2**30))

    @pl.when(wfirst_ref[i] != 0)
    def _init():
        out_ref[0, 0] = jnp.zeros((R, CD), out_ref.dtype)

    def start_block(slot, blk):
        for j in range(PB):
            page = tables_ref[bb, jnp.minimum(blk * PB + j,
                                              num_page_slots - 1)]
            pltpu.make_async_copy(
                k_hbm.at[page], k_buf.at[slot, j], sem_k.at[slot, j]
            ).start()
            pltpu.make_async_copy(
                v_hbm.at[page], v_buf.at[slot, j], sem_v.at[slot, j]
            ).start()

    def wait_block(slot, blk):
        for j in range(PB):
            page = tables_ref[bb, jnp.minimum(blk * PB + j,
                                              num_page_slots - 1)]
            pltpu.make_async_copy(
                k_hbm.at[page], k_buf.at[slot, j], sem_k.at[slot, j]
            ).wait()
            pltpu.make_async_copy(
                v_hbm.at[page], v_buf.at[slot, j], sem_v.at[slot, j]
            ).wait()

    m0 = jnp.full((R, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((R, 1), jnp.float32)
    acc0 = jnp.zeros((R, CD), jnp.float32)
    qbd = qbd_ref[0, 0] * (1.0 / (head_dim**0.5))  # [R, CD]

    def loop(blk, carry):
        m, l, acc = carry
        slot = lax.rem(blk, 2)

        @pl.when(blk + 1 < num_blocks)
        def _prefetch():
            start_block(lax.rem(blk + 1, 2), blk + 1)

        wait_block(slot, blk)
        start = blk * blk_tokens
        kv_idx = start + lax.broadcasted_iota(
            jnp.int32, (R, blk_tokens), 1
        )
        mask = belongs & (kv_idx <= pos_r) & (kv_idx < valid)
        mask &= kv_idx > pos_r - eff_w

        k = k_buf[slot].reshape(blk_tokens, CD)
        v = v_buf[slot].reshape(blk_tokens, CD)
        s = lax.dot_general(
            qbd.astype(k.dtype), k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if attn_softcap:
            s = jnp.tanh(s * (1.0 / attn_softcap)) * attn_softcap
        s = jnp.where(mask, s, _NEG_INF)

        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_cur)
        alpha = jnp.exp(m - m_new)
        probs = jnp.where(s > _NEG_INF * 0.5, jnp.exp(s - m_new), 0.0)
        l_new = l * alpha + jnp.sum(probs, -1, keepdims=True)
        acc_new = acc * alpha + lax.dot_general(
            probs.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return (m_new, l_new, acc_new)

    def run():
        start_block(lax.rem(first_block, 2), first_block)
        return lax.fori_loop(first_block, num_blocks, loop, (m0, l0, acc0))

    _, l, acc = lax.cond(
        num_blocks > first_block, run, lambda: (m0, l0, acc0)
    )
    vals = (acc / jnp.maximum(l, 1e-30)).astype(out_ref.dtype)
    # RMW: only this segment's rows land; the window's other segments own
    # (and have written / will write) the rest
    out_ref[0, 0] = jnp.where(belongs, vals, out_ref[0, 0])


@functools.partial(
    jax.jit,
    static_argnames=("page_size", "q_block", "pages_per_block", "interpret",
                     "attn_softcap"),
)
def paged_attention_ragged(
    q: jnp.ndarray,
    pool_k: jnp.ndarray,
    pool_v: jnp.ndarray,
    page_tables: jnp.ndarray,
    tok_row: jnp.ndarray,
    q_pos: jnp.ndarray,
    kv_valid_len: jnp.ndarray,
    *,
    page_size: int,
    q_block: int = 128,
    pages_per_block: int = 8,
    interpret: bool | None = None,
    sliding_window=0,
    attn_softcap: float = 0.0,
) -> jnp.ndarray:
    """Ragged mixed-batch paged GQA attention — ONE kernel for a packed
    batch of decode tokens (q_len 1) and prefill chunks (q_len up to the
    chunk budget), the Ragged Paged Attention recipe (PAPERS.md) with
    PackInfer-style packing: rows sit back-to-back on a flat token axis,
    TQ-wide windows of it become MXU tiles, and per-(window, row)
    segments run as grid steps whose KV loops cover only that row's
    pages. Subsumes the decode kernel (all rows q_len 1) and the
    chunked-prefill kernel (one row per window) — the engine's mixed
    step launches THIS kernel for both phases so they cannot drift.

    Contract: ``tok_row`` must be non-decreasing over the packed axis
    (each row's tokens contiguous; -1 padding anywhere is masked but the
    work-item bound assumes the packed form, so keep padding at the
    end). ``q_pos`` is each token's absolute position in its row, and
    positions within a row must ascend. K/V for the new tokens must
    already be written to the pool.

    Args:
      q: [S, H, D] packed query tokens.
      pool_k, pool_v: [num_slots, KV, D] one layer's flat page pool.
      page_tables: [Bm, P] page ids per row.
      tok_row: [S] owning row per packed token (-1 = padding).
      q_pos: [S] absolute position of each packed token.
      kv_valid_len: [Bm] valid tokens per row INCLUDING its new tokens.
      q_block: packed-query window width (VMEM residency unit).

    Returns: [S, H, D] attention outputs in q.dtype (padding and
    fully-masked rows are garbage; callers mask by tok_row).
    """
    S, H, D = q.shape
    num_slots, KV, _ = pool_k.shape
    G = H // KV
    num_pages = num_slots // page_size
    Bm, P = page_tables.shape
    PB = min(pages_per_block, P)
    TQ = min(q_block, S)
    while S % TQ:
        TQ //= 2
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    # head packing into 128-lane chunks, exactly as the prefill kernel
    C = max(1, min(_LANES // D, KV))
    while KV % C:
        C -= 1
    KVc = KV // C
    CD = C * D
    R = TQ * C * G
    num_win = S // TQ

    tok_row = tok_row.astype(jnp.int32)
    q_pos = q_pos.astype(jnp.int32)

    # ---- work-item metadata (plain XLA, tiny arrays) ----
    # M[w, b]: window w holds tokens of row b. Segments are the set bits,
    # ordered (w, b) so same-window segments are consecutive grid steps;
    # with rows contiguous on the packed axis there are at most
    # num_win + Bm of them (one boundary row per window plus one segment
    # per window), the static work list size.
    onehot = tok_row[:, None] == jnp.arange(Bm, dtype=jnp.int32)[None, :]
    M = onehot.reshape(num_win, TQ, Bm).any(axis=1)  # [num_win, Bm]
    flat = M.reshape(-1)
    big = jnp.int32(num_win * Bm)
    keys = jnp.where(flat, jnp.arange(num_win * Bm, dtype=jnp.int32), big)
    W = num_win + Bm
    # pad the key pool to W before sorting: with num_win == 1 (or
    # Bm == 1) the set-bit pool is SMALLER than the work list, and a
    # bare [:W] slice would leave the scalar-prefetch arrays shorter
    # than the grid — out-of-bounds SMEM reads on real silicon (the
    # clamping gather hides it in interpret mode)
    keys = jnp.concatenate([keys, jnp.full((W,), big, jnp.int32)])
    sel = jnp.sort(keys)[:W]
    present = sel < big
    sel = jnp.where(present, sel, 0)
    work_row = jnp.where(present, sel % Bm, -1).astype(jnp.int32)
    # padding items park on the LAST window: the work list is ordered so
    # they form a suffix, and a belongs-empty RMW there is a no-op
    work_win = jnp.where(present, sel // Bm, num_win - 1).astype(jnp.int32)
    prev = jnp.concatenate([jnp.full((1,), -1, jnp.int32), work_win[:-1]])
    work_first = ((work_win != prev) & present).astype(jnp.int32)

    # block-diagonal query expansion per window (same trick as prefill)
    eye = jnp.eye(C, dtype=q.dtype)
    qbd = jnp.einsum(
        "wtkugd,uj->wtkugjd",
        q.reshape(num_win, TQ, KVc, C, G, D), eye,
    )  # [num_win, TQ, KVc, C, G, C, D]
    qbd = qbd.transpose(0, 2, 1, 3, 4, 5, 6).reshape(num_win, KVc, R, CD)
    # per-q-row position / owning row (token-expanded to the R axis)
    pos_r = jnp.broadcast_to(
        q_pos.reshape(num_win, TQ, 1), (num_win, TQ, C * G)
    ).reshape(num_win, R)
    row_r = jnp.broadcast_to(
        tok_row.reshape(num_win, TQ, 1), (num_win, TQ, C * G)
    ).reshape(num_win, R)

    k_pages = pool_k.reshape(num_pages, page_size, KV * D)
    v_pages = pool_v.reshape(num_pages, page_size, KV * D)
    tables = jnp.clip(page_tables.astype(jnp.int32), 0, num_pages - 1)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=6,
        grid=(KVc, W),
        in_specs=[
            pl.BlockSpec((1, 1, R, CD),
                         lambda c, i, t, vl, wr, ww, wf, w: (ww[i], c, 0, 0)),
            pl.BlockSpec((1, R),
                         lambda c, i, t, vl, wr, ww, wf, w: (ww[i], 0)),
            pl.BlockSpec((1, R),
                         lambda c, i, t, vl, wr, ww, wf, w: (ww[i], 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, R, CD),
            lambda c, i, t, vl, wr, ww, wf, w: (ww[i], c, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, PB, page_size, CD), pool_k.dtype),
            pltpu.VMEM((2, PB, page_size, CD), pool_v.dtype),
            pltpu.SemaphoreType.DMA((2, PB)),
            pltpu.SemaphoreType.DMA((2, PB)),
        ],
    )

    out_big = pl.pallas_call(
        functools.partial(
            _ragged_kernel,
            page_size=page_size,
            pages_per_block=PB,
            num_page_slots=P,
            head_dim=D,
            attn_softcap=attn_softcap,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((num_win, KVc, R, CD), q.dtype),
        interpret=interpret,
        compiler_params=tpu_compiler_params(
            # segments of one window REVISIT the same out block (RMW);
            # both axes stay sequential
            dimension_semantics=("arbitrary", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=2 * S * H * P * page_size * CD,
            bytes_accessed=2 * Bm * KV * P * page_size * D
            * pool_k.dtype.itemsize,
            transcendentals=S * H * P * page_size,
        ),
    )(
        tables, kv_valid_len.astype(jnp.int32), work_row, work_win,
        work_first, jnp.asarray(sliding_window, jnp.int32).reshape(1),
        qbd, pos_r, row_r, k_pages, v_pages,
    )
    out = jnp.einsum(
        "wktugjd,uj->wtkugd",
        out_big.reshape(num_win, KVc, TQ, C, G, C, D), eye,
    )
    return out.reshape(S, H, D)
