"""Ragged paged-attention decode kernel (Pallas / Mosaic TPU).

The serving hot loop's attention: one new query token per sequence attends
to that sequence's KV pages scattered through the HBM page pool. The
pure-XLA path (``models/llama.py:paged_forward``) first gathers every
sequence's pages into a dense ``[B, S_max, KV, D]`` buffer and then runs
dense attention — materializing S_max slots per row in HBM each step. This
kernel reads pages straight from the pool instead: the block-table entry is
a *scalar-prefetch* argument, so Pallas pipelines the page DMAs
(HBM → VMEM) chosen by the table while the MXU works on the previous page,
and nothing is materialized beyond one page per grid step.

Online-softmax accumulation over pages (flash-attention style), f32
accumulators, causal masking implied by the ragged ``kv_valid_len`` (the
query IS the last valid token — decode only). Each grid step loads one
whole page ([page_size, KV, D] — Mosaic requires the trailing two block
dims to match the array, so the KV-head loop is unrolled inside the kernel
rather than gridded).

Replaces the reference's planned llama.cpp attention (design.md:7 [spec])
as the native tier; same contract as ops/attention.py:gqa_attention.
Kernel shape follows the ragged-paged-attention recipe (PAPERS.md).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30
_LANES = 128  # VPU lane width; scratch statistics are broadcast across lanes


def _decode_kernel(
    # scalar-prefetch refs
    tables_ref,  # [B, P] page id per (row, page-slot)
    valid_ref,  # [B] valid token count per row
    # tensor refs
    q_ref,  # [1, KV, G, D] this row's query tile, grouped by kv head
    k_ref,  # [1, page_size, KV, D] this grid step's K page
    v_ref,  # [1, page_size, KV, D] this grid step's V page
    out_ref,  # [1, KV, G, D]
    # scratch
    m_ref,  # [KV*G, LANES] f32 running max (broadcast across lanes)
    l_ref,  # [KV*G, LANES] f32 running denominator
    acc_ref,  # [KV*G, D] f32 running numerator
    *,
    page_size: int,
):
    b = pl.program_id(0)
    p = pl.program_id(1)
    num_pages_per_seq = pl.num_programs(1)
    num_kv = q_ref.shape[1]
    G = q_ref.shape[2]

    @pl.when(p == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    valid = valid_ref[b]
    start = p * page_size

    @pl.when(start < valid)
    def _accumulate():
        # static unroll over the (small) kv-head count; each head is a
        # plain 2D MXU matmul — Mosaic has no batched dot_general
        for kv in range(num_kv):
            q = q_ref[0, kv].astype(jnp.float32)  # [G, D]
            k = k_ref[0, :, kv, :].astype(jnp.float32)  # [S_p, D]
            v = v_ref[0, :, kv, :].astype(jnp.float32)  # [S_p, D]
            d = q.shape[-1]
            rows = slice(kv * G, (kv + 1) * G)

            # [G, S_p] scores on the MXU, f32 accumulation
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * (1.0 / (d**0.5))

            token_ids = start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(token_ids < valid, s, _NEG_INF)

            m_prev = m_ref[rows, :1]  # [G, 1]
            l_prev = l_ref[rows, :1]
            m_cur = jnp.max(s, axis=-1, keepdims=True)
            m_new = jnp.maximum(m_prev, m_cur)
            alpha = jnp.exp(m_prev - m_new)
            probs = jnp.exp(s - m_new)  # [G, S_p]
            l_new = l_prev * alpha + jnp.sum(probs, axis=-1, keepdims=True)
            acc_ref[rows] = acc_ref[rows] * alpha + jax.lax.dot_general(
                probs, v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            m_ref[rows] = jnp.broadcast_to(m_new, (G, m_ref.shape[1]))
            l_ref[rows] = jnp.broadcast_to(l_new, (G, l_ref.shape[1]))

    @pl.when(p == num_pages_per_seq - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, :1], 1e-30)  # rows with valid=0 emit zeros
        out = acc_ref[:] / l  # [KV*G, D]
        out_ref[0] = out.reshape(num_kv, G, -1).astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("page_size", "interpret"))
def paged_attention_decode(
    q: jnp.ndarray,
    pool_k: jnp.ndarray,
    pool_v: jnp.ndarray,
    page_tables: jnp.ndarray,
    kv_valid_len: jnp.ndarray,
    *,
    page_size: int,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Decode-step paged GQA attention against the flat page pool.

    Args:
      q: [B, H, D] one query per row (the token being decoded).
      pool_k, pool_v: [num_slots, KV, D] one layer's flat page pool
        (num_slots = num_pages * page_size — engine/kv_cache.py layout).
      page_tables: [B, P] page ids per row (entries past the row's last
        page may be any in-range id; they are masked, and are clamped
        defensively to the pool).
      kv_valid_len: [B] valid tokens per row, INCLUDING the just-written
        query token (the query is causal-last by construction).
      page_size: tokens per page.
      interpret: force Pallas interpret mode; defaults to True off-TPU so
        tests run on the CPU backend.

    Returns: [B, H, D] attention outputs in q.dtype.
    """
    B, H, D = q.shape
    num_slots, KV, _ = pool_k.shape
    G = H // KV
    num_pages = num_slots // page_size
    P = page_tables.shape[1]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    qg = q.reshape(B, KV, G, D)
    k_pages = pool_k.reshape(num_pages, page_size, KV, D)
    v_pages = pool_v.reshape(num_pages, page_size, KV, D)
    tables = jnp.clip(page_tables.astype(jnp.int32), 0, num_pages - 1)

    def table_page(b, p, tables_ref, valid_ref):
        return (tables_ref[b, p], 0, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, P),
        in_specs=[
            pl.BlockSpec((1, KV, G, D), lambda b, p, t, vl: (b, 0, 0, 0)),
            pl.BlockSpec((1, page_size, KV, D), table_page),
            pl.BlockSpec((1, page_size, KV, D), table_page),
        ],
        out_specs=pl.BlockSpec((1, KV, G, D), lambda b, p, t, vl: (b, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((KV * G, _LANES), jnp.float32),
            pltpu.VMEM((KV * G, _LANES), jnp.float32),
            pltpu.VMEM((KV * G, D), jnp.float32),
        ],
    )

    out = pl.pallas_call(
        functools.partial(_decode_kernel, page_size=page_size),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, KV, G, D), q.dtype),
        interpret=interpret,
        compiler_params=pltpu.CompilerParams(
            # the batch grid dim is independent — scratch state only spans
            # the innermost page dim — so let megacore split it
            dimension_semantics=("parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=4 * B * H * P * page_size * D,
            bytes_accessed=2 * B * KV * P * page_size * D * pool_k.dtype.itemsize,
            transcendentals=B * H * P * page_size,
        ),
    )(tables, kv_valid_len.astype(jnp.int32), qg, k_pages, v_pages)
    return out.reshape(B, H, D)
