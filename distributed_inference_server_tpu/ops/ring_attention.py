"""Ring attention: context-parallel blockwise attention over a `seq` mesh axis.

Long-context scaling the reference entirely lacked (context hard-capped at
8192 tokens, ``validator.rs:20``; SURVEY.md §5 "long-context: entirely
absent"). Here prefill of long prompts spans chips: the sequence is sharded
over the ``seq`` mesh axis, every device holds one Q/K/V chunk, and KV
chunks rotate around the ring via ``lax.ppermute`` while each device
accumulates blockwise online-softmax attention of its local queries —
flash-attention's math, with the outer loop running over ICI neighbors.
Compute on chunk i overlaps the DMA of chunk i+1 (XLA schedules the
ppermute concurrently with the local block matmuls).

Causality rides on absolute positions, which rotate with the KV chunks, so
the mask is exact for any sequence layout (contiguous chunks, padding
tails, ragged batches via kv_valid masks).

``ring_attention`` is the per-shard body (call inside shard_map);
``ring_attention_sharded`` is the mesh-level wrapper.
"""

from __future__ import annotations

import jax
from distributed_inference_server_tpu.utils.compat import axis_size, pcast, shard_map
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

_NEG_INF = -1e30


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    q_positions: jnp.ndarray,
    kv_positions: jnp.ndarray,
    axis_name: str = "seq",
    sliding_window=None,
    attn_softcap: float | None = None,
) -> jnp.ndarray:
    """Per-shard ring attention body (must run inside shard_map/pmap).

    Args:
      q: [B, Tl, H, D] local query chunk (Tl = T / ring size).
      k, v: [B, Tl, KV, D] local key/value chunks (GQA: H = G * KV).
      q_positions: [B, Tl] absolute positions of local queries; negative
        positions mark padding rows (they attend nothing and emit zeros).
      kv_positions: [B, Tl] absolute positions of local keys; negative
        positions mark padding keys (never attended).
      axis_name: the mesh axis the ring runs over.
      sliding_window: None = full causal (static fast path); otherwise a
        scalar — possibly TRACED (Gemma-2 per-layer windows ride the
        layer scan) — where <= 0 means full causal.
      attn_softcap: Gemma-2 score soft-capping, tanh(s/cap)*cap applied
        before masking (None = off; static).

    Returns [B, Tl, H, D] in q.dtype — attention over the FULL sequence.
    """
    B, Tl, H, D = q.shape
    KV = k.shape[2]
    G = H // KV
    ring = axis_size(axis_name)
    scale = 1.0 / (D**0.5)

    qg = q.astype(jnp.float32).reshape(B, Tl, KV, G, D)

    def scores(k_blk, pos_kv):
        """Masked blockwise scores [B, KV, G, Tl, S] of the local queries
        against one KV chunk."""
        s = jnp.einsum(
            "btkgd,bskd->bkgts", qg, k_blk.astype(jnp.float32)
        ) * scale
        if attn_softcap is not None:
            s = jnp.tanh(s / attn_softcap) * attn_softcap
        causal = pos_kv[:, None, :] <= q_positions[:, :, None]  # [B, Tl, S]
        if sliding_window is not None:
            w = jnp.asarray(sliding_window, jnp.int32)
            causal &= (w <= 0) | (
                pos_kv[:, None, :] > q_positions[:, :, None] - w
            )
        valid = (pos_kv >= 0)[:, None, :] & (q_positions >= 0)[:, :, None]
        mask = (causal & valid)[:, None, None, :, :]
        return jnp.where(mask, s, _NEG_INF)

    def accumulate(stats, k_blk, v_blk, pos_kv):
        """Online-softmax update of (m, l, acc) with one KV chunk."""
        m, l, acc = stats
        s = scores(k_blk, pos_kv)
        m_cur = jnp.max(s, axis=-1)  # [B, KV, G, Tl]
        m_new = jnp.maximum(m, m_cur)
        alpha = jnp.exp(m - m_new)
        # explicit zero for masked entries: when a query has seen nothing
        # yet (m == -inf), exp(s - m) would be exp(0) = 1, not 0
        probs = jnp.where(
            s > _NEG_INF * 0.5, jnp.exp(s - m_new[..., None]), 0.0
        )  # [B,KV,G,Tl,S]
        l_new = l * alpha + jnp.sum(probs, axis=-1)
        upd = jnp.einsum("bkgts,bskd->btkgd", probs, v_blk.astype(jnp.float32))
        acc_new = acc * alpha.transpose(0, 3, 1, 2)[..., None] + upd
        return m_new, l_new, acc_new

    def step(carry, _):
        stats, k_blk, v_blk, pos_kv = carry
        stats = accumulate(stats, k_blk, v_blk, pos_kv)
        # rotate KV (and its positions) to the next ring neighbor
        perm = [(i, (i + 1) % ring) for i in range(ring)]
        k_nxt = lax.ppermute(k_blk, axis_name, perm)
        v_nxt = lax.ppermute(v_blk, axis_name, perm)
        p_nxt = lax.ppermute(pos_kv, axis_name, perm)
        return (stats, k_nxt, v_nxt, p_nxt), None

    stats0 = (
        jnp.full((B, KV, G, Tl), _NEG_INF, jnp.float32),
        jnp.zeros((B, KV, G, Tl), jnp.float32),
        jnp.zeros((B, Tl, KV, G, D), jnp.float32),
    )
    # when the surrounding manual region tracks varying-manual-axes (vma)
    # — e.g. the unified seq x stage shard_map of parallel/cp.py's
    # cp_pp_prefill — the scan carry must start with the same vma set the
    # accumulate step produces, or the carry types mismatch. Promote the
    # fresh zeros to the inputs' varying set (no-op under check_vma=False
    # wrappers, where the set is empty).
    try:
        vma = tuple(jax.typeof(q).vma | jax.typeof(k).vma)
    except (AttributeError, TypeError):
        vma = ()
    if vma:
        stats0 = tuple(pcast(x, vma, to="varying") for x in stats0)
    # ring-1 rotate-and-accumulate steps, then a peeled final accumulate —
    # the last rotation's result would be discarded, so don't issue it
    (stats, k_last, v_last, pos_last), _ = lax.scan(
        step, (stats0, k, v, kv_positions), None, length=ring - 1
    )
    m, l, acc = accumulate(stats, k_last, v_last, pos_last)
    l = jnp.maximum(l, 1e-30).transpose(0, 3, 1, 2)[..., None]
    return (acc / l).reshape(B, Tl, H, D).astype(q.dtype)


def ring_attention_sharded(
    mesh,
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    q_positions: jnp.ndarray,
    kv_positions: jnp.ndarray,
    axis_name: str = "seq",
    sliding_window=None,
    attn_softcap: float | None = None,
) -> jnp.ndarray:
    """shard_map wrapper: sequence dim sharded over ``axis_name``, heads
    over ``tensor`` (ring attention composes with TP: each tensor shard
    rings its own heads). ``sliding_window`` may be a traced scalar (it
    rides the specs as a replicated operand, never a closure capture)."""
    row_specs = (
        P("data", axis_name, "tensor", None),
        P("data", axis_name, "tensor", None),
        P("data", axis_name, "tensor", None),
        P("data", axis_name),
        P("data", axis_name),
    )
    if sliding_window is None:
        fn = shard_map(
            lambda *a: ring_attention(*a, axis_name=axis_name,
                                      attn_softcap=attn_softcap),
            mesh=mesh,
            in_specs=row_specs,
            out_specs=P("data", axis_name, "tensor", None),
            check_vma=False,
        )
        return fn(q, k, v, q_positions, kv_positions)
    fn = shard_map(
        lambda q, k, v, qp, kp, w: ring_attention(
            q, k, v, qp, kp, axis_name=axis_name, sliding_window=w,
            attn_softcap=attn_softcap,
        ),
        mesh=mesh,
        in_specs=row_specs + (P(),),  # window: replicated scalar
        out_specs=P("data", axis_name, "tensor", None),
        check_vma=False,
    )
    return fn(q, k, v, q_positions, kv_positions,
              jnp.asarray(sliding_window, jnp.int32))
