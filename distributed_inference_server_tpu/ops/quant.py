"""Weight quantization: symmetric per-group int8 and packed int4.

The reference planned quantized inference through llama.cpp's GGUF levels
(F32/F16/Q8_0/Q4_0/Q4_K_M — design.md:324-332 [spec]). The TPU-native
equivalents are weight-only int8 ("Q8_0"-class) and group-wise packed
int4 ("Q4_0"-class): weights live in HBM at 1/2 or 1/4 the bytes — decode
is HBM-bandwidth-bound, so weight bytes ≈ step time — and are dequantized
on the fly; XLA fuses the convert+scale into the matmul's operand read,
so nothing dense is materialized in HBM.

Representation: ``Q8Tensor``/``Q4Tensor`` NamedTuples (valid JAX pytrees,
so they ride through ``lax.scan`` layer stacking, ``jax.jit``, and
``shard_params`` unchanged). Scales are per (input-group, out-column),
group size along the input (contraction) axis. int4 packs two values per
byte along the input axis.

``quantize_params`` converts a Llama/Mixtral parameter tree's seven
linear families; embeddings/norms/unembedding stay full precision (they
are small and accuracy-critical).
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Tuple, Union

import jax.numpy as jnp

_QUANT_KEYS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


class Q8Tensor(NamedTuple):
    """int8 weight [..., in, out] + f32 scales [..., in/G, out]."""

    q: jnp.ndarray
    s: jnp.ndarray


class Q4Tensor(NamedTuple):
    """packed uint8 weight [..., in/2, out] (two int4 along the input
    axis) + f32 scales [..., in/G, out]."""

    q: jnp.ndarray
    s: jnp.ndarray


QuantTensor = Union[Q8Tensor, Q4Tensor]


def _group_scales(w: jnp.ndarray, group_size: int, qmax: int) -> jnp.ndarray:
    *lead, d_in, d_out = w.shape
    g = w.reshape(*lead, d_in // group_size, group_size, d_out)
    absmax = jnp.max(jnp.abs(g.astype(jnp.float32)), axis=-2)
    return jnp.maximum(absmax, 1e-8) / qmax  # [..., G, out]


def quantize_int8(w: jnp.ndarray, group_size: int = 128) -> Q8Tensor:
    """Symmetric int8 over input-axis groups. w: [..., in, out]."""
    *lead, d_in, d_out = w.shape
    gs = min(group_size, d_in)
    if d_in % gs:
        raise ValueError(f"group_size {gs} does not divide in-dim {d_in}")
    s = _group_scales(w, gs, 127)
    g = w.astype(jnp.float32).reshape(*lead, d_in // gs, gs, d_out)
    q = jnp.clip(jnp.round(g / s[..., None, :]), -127, 127).astype(jnp.int8)
    return Q8Tensor(q=q.reshape(*lead, d_in, d_out), s=s)


def quantize_int4(w: jnp.ndarray, group_size: int = 64) -> Q4Tensor:
    """Symmetric int4 (range [-7, 7]) over input-axis groups, packed two
    values per byte along the input axis. w: [..., in, out], in even."""
    *lead, d_in, d_out = w.shape
    gs = min(group_size, d_in)
    if d_in % gs or d_in % 2:
        raise ValueError(
            f"int4 needs even in-dim divisible by group {gs}, got {d_in}"
        )
    s = _group_scales(w, gs, 7)
    g = w.astype(jnp.float32).reshape(*lead, d_in // gs, gs, d_out)
    q = jnp.clip(jnp.round(g / s[..., None, :]), -7, 7).astype(jnp.int8)
    q = q.reshape(*lead, d_in, d_out)
    # pack adjacent input rows: low nibble = even row, high nibble = odd
    even = q[..., 0::2, :].astype(jnp.uint8) & 0xF
    odd = q[..., 1::2, :].astype(jnp.uint8) & 0xF
    return Q4Tensor(q=(odd << 4) | even, s=s)


def dequantize(w: QuantTensor, dtype=jnp.bfloat16) -> jnp.ndarray:
    """Dense [..., in, out] weight; under jit XLA fuses this into the
    consuming matmul (the HBM read stays int8/int4)."""
    if isinstance(w, Q4Tensor):
        packed = w.q
        low = (packed & 0xF).astype(jnp.int8)
        high = (packed >> 4).astype(jnp.int8)
        # sign-extend nibbles: values were clipped to [-7, 7]
        low = jnp.where(low > 7, low - 16, low)
        high = jnp.where(high > 7, high - 16, high)
        *lead, half, d_out = packed.shape
        q = jnp.stack([low, high], axis=-2)  # [..., half, 2, out]
        q = q.reshape(*lead, half * 2, d_out)
    elif isinstance(w, Q8Tensor):
        q = w.q
    else:
        return w.astype(dtype) if w.dtype != dtype else w
    *lead, d_in, d_out = q.shape
    groups = w.s.shape[-2]
    gs = d_in // groups
    deq = (
        q.astype(jnp.float32).reshape(*lead, groups, gs, d_out)
        * w.s[..., None, :]
    )
    return deq.reshape(*lead, d_in, d_out).astype(dtype)


def is_quantized(w: Any) -> bool:
    return isinstance(w, (Q8Tensor, Q4Tensor))


def init_random_quantized(
    rng, cfg, mode: str, dtype=jnp.bfloat16, group_size: int = 0
) -> Dict[str, Any]:
    """Random param tree with the linear families created DIRECTLY in
    quantized form — no dense intermediate. ``quantize_params`` over
    ``llama.init_params`` would materialize the full-precision tree
    first, which at 8B bf16 (~16 GB) exceeds one v5e chip's HBM; this
    builds int8/int4 leaves from random bits (an 8B int8 tree is ~8 GB),
    so single-chip 8B benchmarking is possible. Weight content is
    irrelevant to throughput; scales are 1/(qmax*sqrt(d_in)) so
    dequantized magnitudes match init_params' 0.02-ish normal init.
    """
    import jax
    from jax.tree_util import (
        DictKey,
        tree_flatten_with_path,
        tree_unflatten,
    )

    from distributed_inference_server_tpu.models import llama

    if mode == "none":
        return llama.init_params(rng, cfg, dtype=dtype)
    if mode not in ("int8", "int4"):
        raise ValueError(f"unknown quantization mode {mode!r}")
    qmax = 127 if mode == "int8" else 7
    gs_default = group_size or (128 if mode == "int8" else 64)

    shapes = jax.eval_shape(
        lambda k: llama.init_params(k, cfg, dtype=dtype), rng
    )
    leaves, treedef = tree_flatten_with_path(shapes)
    keys = jax.random.split(rng, len(leaves))

    def quant_leaf(shape, k):
        *lead, d_in, d_out = shape
        gs = min(gs_default, d_in)
        s = jnp.full(
            (*lead, d_in // gs, d_out),
            1.0 / (qmax * (d_in ** 0.5)), jnp.float32,
        )
        if mode == "int8":
            bits = jax.random.bits(k, tuple(shape), jnp.uint8)
            return Q8Tensor(
                q=jax.lax.bitcast_convert_type(bits, jnp.int8), s=s
            )
        packed = jax.random.bits(k, (*lead, d_in // 2, d_out), jnp.uint8)
        return Q4Tensor(q=packed, s=s)

    new_leaves = []
    for (path, sds), k in zip(leaves, keys):
        name = path[-1].key if isinstance(path[-1], DictKey) else ""
        if name in _QUANT_KEYS:
            new_leaves.append(quant_leaf(sds.shape, k))
        elif name.endswith("norm"):
            new_leaves.append(jnp.ones(sds.shape, sds.dtype))
        else:
            new_leaves.append(
                (jax.random.normal(k, sds.shape, jnp.float32) * 0.02)
                .astype(sds.dtype)
            )
    return tree_unflatten(treedef, new_leaves)


def dense_view(w: Any, dtype=jnp.bfloat16) -> jnp.ndarray:
    """Dense array for a possibly-quantized weight (pass-through for plain
    arrays) — the single dispatch point for matmul/einsum call sites."""
    return dequantize(w, dtype) if is_quantized(w) else w


def quantize_params(
    params: Dict[str, Any], mode: str, group_size: int = 0
) -> Dict[str, Any]:
    """Quantize a Llama/Mixtral parameter tree's linear weights.

    mode: "int8" | "int4" | "none". Stacked layouts ([L, in, out] and MoE
    [L, E, in, out]) quantize directly — groups run along the input axis.
    """
    if mode == "none":
        return params
    if mode == "int8":
        fn = lambda w: quantize_int8(w, group_size or 128)
    elif mode == "int4":
        fn = lambda w: quantize_int4(w, group_size or 64)
    else:
        raise ValueError(f"unknown quantization mode {mode!r}")
    out = dict(params)
    out["layers"] = {
        k: (fn(v) if k in _QUANT_KEYS else v)
        for k, v in params["layers"].items()
    }
    return out


# ---------------------------------------------------------------------------
# KV-cache quantization (per-vector absmax int8)
# ---------------------------------------------------------------------------


class QuantPool(NamedTuple):
    """Int8-quantized KV pool: per-(slot, head) absmax scaling.

    Halves KV HBM traffic and doubles KV capacity vs bf16 — the decode
    bottleneck at long context, where per-step KV reads dwarf the fixed
    weight reads. Each cached K/V vector [D] stores int8 codes plus one
    f32 scale (absmax/127, ~6% overhead at D=64), reconstructed as
    ``codes * scale`` at attention time. A pytree, so ``lax.scan`` over
    stacked layers, buffer donation, and device_put thread it like a
    plain array; XLA-gather attention dequantizes after the page-granular
    gather. The Pallas DECODE kernel also accepts it (int8 page DMA with
    in-kernel scale folding, ops/pallas/paged_attention.py), but serving
    keeps the XLA path for kv_quant until that variant is proven on real
    silicon (tools/kernel_probe.py KP_KV_QUANT=1 is the proof step); the
    prefill kernel has no int8 variant.

    data:  [..., num_slots, KV, D] int8 codes
    scale: [..., num_slots, KV] f32 per-vector scales
    """

    data: jnp.ndarray
    scale: jnp.ndarray


def pool_num_slots(pool) -> int:
    """Slot count of a per-layer (or stacked) pool, quantized or not —
    the slot axis is -3 in both layouts."""
    return (pool.data if isinstance(pool, QuantPool) else pool).shape[-3]


def quantize_kv(x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-vector absmax int8 quantization of new K/V tokens.

    x: [..., KV, D] -> (codes int8 same shape, scale f32 [..., KV]).
    Zero vectors get scale 0 and reconstruct exactly to zero.
    """
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = amax / 127.0
    q = jnp.where(
        scale[..., None] > 0.0,
        jnp.round(x.astype(jnp.float32) / jnp.maximum(scale, 1e-30)[..., None]),
        0.0,
    ).astype(jnp.int8)
    return q, scale


def dequantize_kv(codes: jnp.ndarray, scale: jnp.ndarray,
                  dtype=jnp.bfloat16) -> jnp.ndarray:
    """Reconstruct K/V vectors: codes [..., KV, D] * scale [..., KV]."""
    return (codes.astype(jnp.float32)
            * scale[..., None]).astype(dtype)
