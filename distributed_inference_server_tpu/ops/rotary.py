"""Rotary position embeddings (RoPE), Llama-3 style.

TPU notes: frequencies are computed once per call in f32 and applied in the
activation dtype; the half-split rotation form (not interleaved) matches HF
Llama so loaded checkpoints are bit-compatible. XLA fuses the sin/cos and
elementwise rotate into neighbouring ops — the default path; the Pallas
kernel (ops/pallas/fused.py, opt-in via DIS_TPU_PALLAS_FUSED=1) computes
sin/cos in VMEM per row block instead.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from distributed_inference_server_tpu.models.configs import RopeScaling


def rope_frequencies(
    head_dim: int,
    theta: float,
    scaling: Optional[RopeScaling] = None,
) -> jnp.ndarray:
    """Inverse frequencies [head_dim//2], with optional Llama-3 scaling."""
    exponents = jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim
    inv_freq = 1.0 / (theta**exponents)
    if scaling is None:
        return inv_freq

    # Llama-3 frequency-dependent scaling: low-frequency components are
    # slowed by `factor`, high-frequency kept, mid smoothly interpolated.
    low_wavelen = scaling.original_max_position / scaling.low_freq_factor
    high_wavelen = scaling.original_max_position / scaling.high_freq_factor
    wavelen = 2.0 * jnp.pi / inv_freq
    smooth = (scaling.original_max_position / wavelen - scaling.low_freq_factor) / (
        scaling.high_freq_factor - scaling.low_freq_factor
    )
    smooth = jnp.clip(smooth, 0.0, 1.0)
    scaled = (1.0 - smooth) * inv_freq / scaling.factor + smooth * inv_freq
    return jnp.where(
        wavelen > low_wavelen,
        inv_freq / scaling.factor,
        jnp.where(wavelen < high_wavelen, inv_freq, scaled),
    )


def apply_rope(
    x: jnp.ndarray,
    positions: jnp.ndarray,
    inv_freq: jnp.ndarray,
) -> jnp.ndarray:
    """Rotate ``x`` by position-dependent angles.

    x: [..., seq, heads, head_dim]; positions: broadcastable to [..., seq].
    Uses the half-split convention: (x1, x2) -> (x1*cos - x2*sin,
    x2*cos + x1*sin) with x1 the first half of head_dim.
    """
    from distributed_inference_server_tpu.ops.pallas.fused import (
        apply_rope_pallas,
        fused_mode,
    )

    mode = fused_mode()
    if mode is not None and x.ndim >= 3 and x.shape[-1] % 16 == 0:
        return apply_rope_pallas(x, positions, inv_freq,
                                 interpret=mode == "interpret")
    angles = positions[..., None].astype(jnp.float32) * inv_freq  # [..., seq, hd/2]
    cos = jnp.cos(angles)[..., None, :]  # [..., seq, 1, hd/2]
    sin = jnp.sin(angles)[..., None, :]
    half = x.shape[-1] // 2
    x1 = x[..., :half].astype(jnp.float32)
    x2 = x[..., half:].astype(jnp.float32)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)
