"""Normalization ops.

RMSNorm computes the variance in f32 regardless of activation dtype (bf16
activations lose too much precision in the sum of squares), then casts back.
XLA fuses this into the surrounding elementwise graph — that is the
default path; the Pallas variant (ops/pallas/fused.py) is opt-in via
DIS_TPU_PALLAS_FUSED=1 for single-device runs where the measured number
(tools/kernel_probe.py) says it pays.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float) -> jnp.ndarray:
    """y = x / rms(x) * weight, computed in f32."""
    from distributed_inference_server_tpu.ops.pallas.fused import (
        fused_mode,
        rms_norm_pallas,
    )

    mode = fused_mode()
    if mode is not None and x.shape[-1] % 128 == 0:
        return rms_norm_pallas(x, weight, eps,
                               interpret=mode == "interpret")
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    normed = xf * lax.rsqrt(var + eps)
    return (normed * weight.astype(jnp.float32)).astype(x.dtype)
