"""Normalization ops.

RMSNorm computes the variance in f32 regardless of activation dtype (bf16
activations lose too much precision in the sum of squares), then casts back.
XLA fuses this into the surrounding elementwise graph; the Pallas fused
variant (ops/pallas/) exists for cases where we want it welded to the
following matmul's prologue.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float) -> jnp.ndarray:
    """y = x / rms(x) * weight, computed in f32."""
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    normed = xf * lax.rsqrt(var + eps)
    return (normed * weight.astype(jnp.float32)).astype(x.dtype)
