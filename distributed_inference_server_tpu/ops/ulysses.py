"""Ulysses sequence parallelism: all-to-all head-scatter attention.

The second long-context strategy SURVEY.md §2.3 names next to ring
attention (the reference had neither — context hard-capped at 8192,
``validator.rs:20``). Where ring attention (ops/ring_attention.py) keeps
queries resident and rotates KV chunks around the ICI ring, Ulysses
re-shards: one all-to-all turns the sequence-sharded activations
[B, T/s, H, D] into head-sharded, sequence-complete [B, T, H/s, D]; each
device then runs ordinary full-sequence attention for its head group, and
a second all-to-all restores sequence sharding. Two collectives per layer
instead of s-1 permutes — cheaper when the head count comfortably divides
(attention is embarrassingly parallel over heads) and the all-to-all fits
ICI; ring wins when s exceeds the shardable head count or overlap with
compute matters more.

Constraint: the axis size must divide BOTH the query-head and KV-head
counts (GQA keeps its group structure after the scatter).
"""

from __future__ import annotations

import jax
from distributed_inference_server_tpu.utils.compat import axis_size, shard_map
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from distributed_inference_server_tpu.ops.attention import gqa_attention


def ulysses_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    q_positions: jnp.ndarray,
    kv_valid_len: jnp.ndarray,
    axis_name: str = "seq",
    sliding_window=None,
    attn_softcap: float | None = None,
) -> jnp.ndarray:
    """Per-shard Ulysses attention body (must run inside shard_map).

    Args:
      q: [B, Tl, H, D] local query chunk (Tl = T / axis size), all heads.
      k, v: [B, Tl, KV, D] local key/value chunks.
      q_positions: [B, Tl] absolute positions of the local tokens
        (contiguous chunks: shard i holds positions [i*Tl, (i+1)*Tl)).
      kv_valid_len: [B] valid sequence length per row (replicated).
      axis_name: mesh axis to all-to-all over.

    Returns [B, Tl, H, D] in q.dtype — attention over the FULL sequence.
    """
    s = axis_size(axis_name)
    H, KV = q.shape[2], k.shape[2]
    if H % s or KV % s:
        raise ValueError(
            f"Ulysses axis size {s} must divide query heads {H} and "
            f"KV heads {KV}; use ring attention for larger axes"
        )
    # scatter heads / gather sequence: [B, Tl, H, D] -> [B, T, H/s, D]
    qh = lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1, tiled=True)
    kh = lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1, tiled=True)
    vh = lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1, tiled=True)
    pos = lax.all_gather(q_positions, axis_name, axis=1, tiled=True)  # [B, T]
    # full-sequence causal attention for this device's head group; padding
    # keys sit at positions >= kv_valid_len (right-padded) and are masked
    out = gqa_attention(qh, kh, vh, pos, kv_valid_len, sliding_window,
                        attn_softcap)
    # gather heads / scatter sequence back: [B, T, H/s, D] -> [B, Tl, H, D]
    return lax.all_to_all(
        out, axis_name, split_axis=1, concat_axis=2, tiled=True
    )


def ulysses_attention_sharded(
    mesh,
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    q_positions: jnp.ndarray,
    kv_valid_len: jnp.ndarray,
    axis_name: str = "seq",
    sliding_window=None,
    attn_softcap: float | None = None,
) -> jnp.ndarray:
    """shard_map wrapper: sequence over ``axis_name``, heads over
    ``tensor`` (Ulysses composes with TP: the all-to-all re-shards each
    tensor shard's own heads). ``sliding_window`` may be a traced scalar
    (rides the specs as a replicated operand, never a closure capture)."""
    row_specs = (
        P("data", axis_name, "tensor", None),
        P("data", axis_name, "tensor", None),
        P("data", axis_name, "tensor", None),
        P("data", axis_name),
        P("data"),
    )
    if sliding_window is None:
        fn = shard_map(
            lambda *a: ulysses_attention(*a, axis_name=axis_name,
                                         attn_softcap=attn_softcap),
            mesh=mesh,
            in_specs=row_specs,
            out_specs=P("data", axis_name, "tensor", None),
            check_vma=False,
        )
        return fn(q, k, v, q_positions, kv_valid_len)
    fn = shard_map(
        lambda q, k, v, qp, kv, w: ulysses_attention(
            q, k, v, qp, kv, axis_name=axis_name, sliding_window=w,
            attn_softcap=attn_softcap,
        ),
        mesh=mesh,
        in_specs=row_specs + (P(),),  # window: replicated scalar
        out_specs=P("data", axis_name, "tensor", None),
        check_vma=False,
    )
    return fn(q, k, v, q_positions, kv_valid_len,
              jnp.asarray(sliding_window, jnp.int32))
