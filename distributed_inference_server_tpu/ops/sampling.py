"""On-device token sampling: greedy, temperature, top-p (nucleus).

The reference spec'd host-side sampling per token (``design.md:666-671``
[spec]); on TPU that would bounce logits to the host every decode step, so
sampling is fused into the compiled step: a single jittable function over the
batch, driven by a threaded PRNG key. Temperature==0 rows degrade to argmax;
top_p==1 rows skip the nucleus cutoff — all branchless (lax.select) so one
compiled program covers every request mix.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def sample_tokens(
    rng: jax.Array,
    logits: jnp.ndarray,
    temperature: jnp.ndarray,
    top_p: jnp.ndarray,
) -> jnp.ndarray:
    """Sample next tokens for a batch.

    Args:
      rng: PRNG key.
      logits: [B, V] f32 final-position logits.
      temperature: [B] per-request temperature (0 => greedy).
      top_p: [B] per-request nucleus threshold (1 => disabled).

    Returns: [B] int32 sampled token ids.
    """
    B, V = logits.shape
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    # temperature scale (guard zero-temp rows; their result is overridden)
    safe_temp = jnp.where(temperature > 0, temperature, 1.0)[:, None]
    scaled = logits / safe_temp

    # top-p: sort descending, keep the smallest prefix with cumprob >= top_p
    sorted_logits = jnp.sort(scaled, axis=-1)[:, ::-1]
    sorted_probs = jax.nn.softmax(sorted_logits, axis=-1)
    cumprobs = jnp.cumsum(sorted_probs, axis=-1)
    # keep tokens while the cumulative prob *before* them is < top_p;
    # the top-1 token is always kept so top_p=0 degrades to greedy
    keep = (cumprobs - sorted_probs) < top_p[:, None]
    keep = keep.at[:, 0].set(True)
    # threshold logit = smallest kept logit per row
    kept_logits = jnp.where(keep, sorted_logits, jnp.inf)
    cutoff = jnp.min(kept_logits, axis=-1, keepdims=True)
    filtered = jnp.where(scaled >= cutoff, scaled, -jnp.inf)

    sampled = jax.random.categorical(rng, filtered, axis=-1).astype(jnp.int32)
    return jnp.where(temperature > 0, sampled, greedy)


def top_p_filter_probs(probs: jnp.ndarray, top_p: jnp.ndarray) -> jnp.ndarray:
    """Zero out probabilities outside the top-p nucleus (per row), keeping
    at least the most-probable token; the result is unnormalized (callers
    sample via ``categorical(log(probs))``, which is scale-invariant).

    Args:
      probs: [B, V] probability rows.
      top_p: [B] nucleus thresholds (1 => unfiltered).

    Returns: [B, V] filtered (unnormalized) probabilities.
    """
    sorted_probs = jnp.sort(probs, axis=-1)[:, ::-1]
    cumprobs = jnp.cumsum(sorted_probs, axis=-1)
    keep_sorted = (cumprobs - sorted_probs) < top_p[:, None]
    keep_sorted = keep_sorted.at[:, 0].set(True)
    # smallest kept probability per row is the cutoff
    kept = jnp.where(keep_sorted, sorted_probs, jnp.inf)
    cutoff = jnp.min(kept, axis=-1, keepdims=True)
    return jnp.where(probs >= cutoff, probs, 0.0)


def nucleus_probs(probs: jnp.ndarray, top_p: jnp.ndarray) -> jnp.ndarray:
    """NORMALIZED nucleus distribution: top-p filter, then renormalize.

    The speculative verifier needs true distributions (the accept ratio
    p̃/q̃ and the residual max(p̃-q̃, 0) are only meaningful when both
    sides sum to 1), unlike ``categorical`` callers for whom the
    unnormalized ``top_p_filter_probs`` suffices.

    Args:
      probs: [..., V] probability rows (each summing to 1).
      top_p: broadcastable to probs.shape[:-1]; 1 => identity.

    Returns: [..., V] renormalized nucleus distributions.
    """
    lead = probs.shape[:-1]
    V = probs.shape[-1]
    f = top_p_filter_probs(
        probs.reshape(-1, V),
        jnp.broadcast_to(top_p, lead).reshape(-1),
    )
    f = f / jnp.maximum(jnp.sum(f, axis=-1, keepdims=True), 1e-30)
    return f.reshape(*lead, V)
