"""On-device token sampling: greedy, temperature, top-p (nucleus).

The reference spec'd host-side sampling per token (``design.md:666-671``
[spec]); on TPU that would bounce logits to the host every decode step, so
sampling is fused into the compiled step: a single jittable function over the
batch, driven by a threaded PRNG key. Temperature==0 rows degrade to argmax;
top_p==1 rows skip the nucleus cutoff — per-ROW mixes are branchless
(lax.select). Per LAUNCH, the engine's decode block picks the cheapest
sampler the seated mix needs via ``lax.switch`` on a runtime scalar: pure
argmax for all-greedy launches (bypassing this module — no Gumbel noise at
all), ``use_topp=False`` for sampled launches with every top_p == 1, and
``use_topp=True`` (the full nucleus machinery here) otherwise — one device
program per shape still covers every request mix.

The nucleus cutoff is computed WITHOUT a vocabulary sort. ``jnp.sort`` over
[B, 128k] logits lowers to O(log^2 V) bitonic passes on TPU and was the
single most expensive non-matmul op in the sampled-decode step; an
equivalent cutoff is found by binary-searching the probability threshold
(``nucleus_cutoff``), which is ~26 masked sums over [B, V] — each a cheap,
fusable HBM pass.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

# Binary-search iterations for the nucleus threshold. The kept set is exact
# up to a threshold resolution of 2**-_CUTOFF_ITERS (~1.5e-8): a token whose
# probability lies within that margin BELOW the true boundary token's
# probability may additionally be kept. f32 probabilities themselves only
# resolve ~6e-8 near 1.0, so this matches the input precision.
_CUTOFF_ITERS = 26


def nucleus_cutoff(probs: jnp.ndarray, top_p: jnp.ndarray) -> jnp.ndarray:
    """Per-row nucleus cutoff probability, sort-free.

    Returns ``c`` of shape [B, 1] such that ``{i : probs[b, i] >= c[b]}``
    equals the classic sorted-prefix nucleus — the smallest descending-order
    prefix whose cumulative probability reaches ``top_p[b]``, extended to
    all ties at the boundary value — up to the resolution documented at
    ``_CUTOFF_ITERS``. The row argmax is always kept (``top_p == 0``
    degrades to greedy); ``top_p >= 1`` keeps every token.

    Mechanism: S(t) = sum of probabilities >= t is a decreasing step
    function of t; the boundary probability is the largest t with
    S(t) >= top_p. Bisect t in [0, 1]: the invariant S(lo) >= top_p holds
    from S(0) = 1, so ``lo`` converges to the boundary from below and never
    drops a token the sorted rule would keep.

    Args:
      probs: [B, V] probability rows (each summing to ~1).
      top_p: [B] nucleus thresholds.

    Returns: [B, 1] cutoff probabilities.
    """
    tp = top_p[:, None]
    pmax = jnp.max(probs, axis=-1, keepdims=True)

    def body(_, lohi):
        lo, hi = lohi
        mid = 0.5 * (lo + hi)
        s = jnp.sum(jnp.where(probs >= mid, probs, 0.0), -1, keepdims=True)
        ge = s >= tp
        return jnp.where(ge, mid, lo), jnp.where(ge, hi, mid)

    lo, _ = lax.fori_loop(
        0, _CUTOFF_ITERS, body,
        (jnp.zeros_like(pmax), jnp.ones_like(pmax)),
    )
    # top_p == 0 (or a float-sum shortfall at top_p == 1) leaves lo at an
    # endpoint; clamping to pmax guarantees the top-1 token always survives
    # while never excluding a token the prefix rule would keep.
    # top_p >= 1 pins the cutoff to 0 explicitly: when the f32 probability
    # sum lands a hair ABOVE 1.0, the bisection would otherwise find a
    # positive threshold and shave ~1e-7 of tail mass off the "keep
    # everything" contract.
    return jnp.where(tp >= 1.0, 0.0, jnp.minimum(lo, pmax))


def sample_tokens(
    rng: jax.Array,
    logits: jnp.ndarray,
    temperature: jnp.ndarray,
    top_p: jnp.ndarray,
    *,
    use_topp: bool = True,
) -> jnp.ndarray:
    """Sample next tokens for a batch.

    Args:
      rng: PRNG key.
      logits: [B, V] f32 final-position logits.
      temperature: [B] per-request temperature (0 => greedy).
      top_p: [B] per-request nucleus threshold (1 => disabled).
      use_topp: static; False compiles out the nucleus machinery entirely
        (softmax + threshold search) for launches where every row has
        top_p == 1 or temperature == 0 — for those rows the nucleus is a
        no-op, so results are identical and the decode step saves the
        full-vocab passes.

    Returns: [B] int32 sampled token ids.
    """
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    # temperature scale (guard zero-temp rows; their result is overridden)
    safe_temp = jnp.where(temperature > 0, temperature, 1.0)[:, None]
    scaled = logits / safe_temp

    if use_topp:
        probs = jax.nn.softmax(scaled, axis=-1)
        cutoff = nucleus_cutoff(probs, top_p)
        filtered = jnp.where(probs >= cutoff, scaled, -jnp.inf)
    else:
        filtered = scaled

    sampled = jax.random.categorical(rng, filtered, axis=-1).astype(jnp.int32)
    return jnp.where(temperature > 0, sampled, greedy)


def top_p_filter_probs(probs: jnp.ndarray, top_p: jnp.ndarray) -> jnp.ndarray:
    """Zero out probabilities outside the top-p nucleus (per row), keeping
    at least the most-probable token; the result is unnormalized (callers
    sample via ``categorical(log(probs))``, which is scale-invariant).

    Args:
      probs: [B, V] probability rows.
      top_p: [B] nucleus thresholds (1 => unfiltered).

    Returns: [B, V] filtered (unnormalized) probabilities.
    """
    cutoff = nucleus_cutoff(probs, top_p)
    return jnp.where(probs >= cutoff, probs, 0.0)


def nucleus_probs(probs: jnp.ndarray, top_p: jnp.ndarray) -> jnp.ndarray:
    """NORMALIZED nucleus distribution: top-p filter, then renormalize.

    The speculative verifier needs true distributions (the accept ratio
    p̃/q̃ and the residual max(p̃-q̃, 0) are only meaningful when both
    sides sum to 1), unlike ``categorical`` callers for whom the
    unnormalized ``top_p_filter_probs`` suffices.

    Args:
      probs: [..., V] probability rows (each summing to 1).
      top_p: broadcastable to probs.shape[:-1]; 1 => identity.

    Returns: [..., V] renormalized nucleus distributions.
    """
    lead = probs.shape[:-1]
    V = probs.shape[-1]
    f = top_p_filter_probs(
        probs.reshape(-1, V),
        jnp.broadcast_to(top_p, lead).reshape(-1),
    )
    f = f / jnp.maximum(jnp.sum(f, axis=-1, keepdims=True), 1e-30)
    return f.reshape(*lead, V)
