"""Pipeline parallelism: layer stages over the `stage` mesh axis.

For models too big for one chip/slice even under TP (north star: Llama-3
70B TP×PP on v5p-64, BASELINE.md), layers are split into contiguous
stages. TPU-idiomatic formulation: one SPMD program via ``shard_map`` over
``stage`` — every device runs the same tick loop on its own layer slice,
activations hop stage→stage through ``lax.ppermute`` over ICI/DCN, and
GPipe fill-drain microbatching keeps stages busy (M microbatches, M+S-1
ticks, bubble fraction (S-1)/(M+S-1)).

Key layout choices:
- Layer-stacked params keep their standard [L, ...] layout; shard_map's
  in_specs split the layer axis, so stage s holds layers [s*L/S, (s+1)*L/S)
  — no host-side re-packing.
- Each stage's dense KV cache lives on that stage (cache sharded over the
  layer axis too): cache HBM scales down 1/S per device.
- The shard_map is *partial-manual* (``axis_names={'stage'}``): the
  ``tensor`` axis stays GSPMD-managed inside the body, so TP composes with
  PP without manual collectives (weights keep their tp.py shardings).
- Embedding/final-norm/unembedding are replicated compute on every stage
  (cheap relative to the stacks; vocab-parallel unembed is a later
  optimization).

The reference has no PP (SURVEY.md §2.3 absence audit).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
from distributed_inference_server_tpu.utils.compat import pcast, shard_map
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from distributed_inference_server_tpu.models import llama
from distributed_inference_server_tpu.models.configs import ModelConfig
from distributed_inference_server_tpu.ops.attention import gqa_attention
from distributed_inference_server_tpu.ops.norms import rms_norm
from distributed_inference_server_tpu.ops.rotary import rope_frequencies


def validate_pp(cfg: ModelConfig, stages: int, batch: int,
                num_microbatches: int) -> None:
    if cfg.num_layers % stages:
        raise ValueError(
            f"{stages} stages do not divide num_layers={cfg.num_layers}"
        )
    if batch % num_microbatches:
        raise ValueError(
            f"{num_microbatches} microbatches do not divide batch={batch}"
        )


def pp_forward(
    mesh,
    params: llama.Params,
    cfg: ModelConfig,
    input_ids: jnp.ndarray,
    positions: jnp.ndarray,
    cache_k: jnp.ndarray,
    cache_v: jnp.ndarray,
    write_pos: jnp.ndarray,
    kv_valid_len: jnp.ndarray,
    num_microbatches: int = 1,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Pipeline-parallel forward over the dense KV cache.

    Same contract as ``llama.forward`` (prefill: T = prompt chunk; decode:
    T = 1), executed over the mesh's ``stage`` axis. Returns
    (logits [B, T, V] f32, new cache_k, new cache_v) with caches sharded
    over the layer axis by stage.
    """
    S = mesh.shape.get("stage", 1)
    B, T = input_ids.shape
    M = num_microbatches
    validate_pp(cfg, S, B, M)
    B_mb = B // M
    Smax = cache_k.shape[2]
    inv_freq = rope_frequencies(cfg.head_dim, cfg.rope_theta, cfg.rope_scaling)

    def body(layers, embed, final_norm, unembed, ids, pos, ck, cv, wp, kvv):
        # local views: layers/ck/cv hold this stage's L/S layers
        stage = lax.axis_index("stage")

        # this stage's slice of the per-layer sliding windows (0 = full
        # causal) — Gemma-2-style alternating layers keep their schedule
        # across stage boundaries. Non-sliding models skip the traced
        # window entirely (static None keeps gqa's maskless branch).
        L_stage = layers["attn_norm"].shape[0]
        if cfg.sliding_window:
            win_stage = jnp.asarray(cfg.layer_windows(), jnp.int32).reshape(
                -1, L_stage
            )[stage]
        else:
            win_stage = None

        def run_stage(h_mb, pos_mb, ck_mb, cv_mb, wp_mb, kvv_mb):
            write_fn = lambda pool, l, new: llama._write_kv(
                pool, l, new, wp_mb)
            attend_fn = lambda q, k, v, w: gqa_attention(
                q, k, v, pos_mb, kvv_mb, w, cfg.attn_logit_softcap)

            h_mb, (nk, nv) = llama.scan_layer_blocks(
                cfg, h_mb, layers, ck_mb, cv_mb, win_stage, pos_mb,
                write_fn, attend_fn, inv_freq,
            )
            return h_mb, nk, nv

        def tick(t, carry):
            state, ck, cv, out = carry
            mb = t - stage
            valid = (mb >= 0) & (mb < M)
            row = jnp.clip(mb, 0, M - 1) * B_mb
            ids_mb = lax.dynamic_slice_in_dim(ids, row, B_mb, 0)
            pos_mb = lax.dynamic_slice_in_dim(pos, row, B_mb, 0)
            wp_mb = lax.dynamic_slice_in_dim(wp, row, B_mb, 0)
            kvv_mb = lax.dynamic_slice_in_dim(kvv, row, B_mb, 0)
            ck_mb = lax.dynamic_slice_in_dim(ck, row, B_mb, 1)
            cv_mb = lax.dynamic_slice_in_dim(cv, row, B_mb, 1)
            # invalid ticks (pipeline bubble) must not mutate the cache
            wp_eff = jnp.where(valid, wp_mb, Smax)

            h_emb = embed[ids_mb]
            if cfg.scale_embeddings:  # Gemma: sqrt(hidden) on input
                h_emb = h_emb * jnp.asarray(
                    cfg.hidden_size**0.5, h_emb.dtype
                )
            h_in = jnp.where(stage == 0, h_emb, state)
            h_out, nk, nv = run_stage(h_in, pos_mb, ck_mb, cv_mb, wp_eff,
                                      kvv_mb)
            ck = lax.dynamic_update_slice_in_dim(ck, nk, row, 1)
            cv = lax.dynamic_update_slice_in_dim(cv, nv, row, 1)

            out_upd = lax.dynamic_update_slice_in_dim(out, h_out, row, 0)
            out = jnp.where(valid & (stage == S - 1), out_upd, out)

            # hand activations to the next stage (stage 0 always injects,
            # so the non-circular permute's zero-fill there is harmless)
            state = lax.ppermute(
                h_out, "stage", [(i, i + 1) for i in range(S - 1)]
            )
            return state, ck, cv, out

        # carries start stage-varying (vma tracking needs the promotion)
        state0 = pcast(
            jnp.zeros((B_mb, T, cfg.hidden_size), embed.dtype),
            "stage", to="varying",
        )
        out0 = pcast(
            jnp.zeros((B, T, cfg.hidden_size), embed.dtype),
            "stage", to="varying",
        )
        state, ck, cv, out = lax.fori_loop(
            0, M + S - 1, tick, (state0, ck, cv, out0)
        )

        out = lax.psum(out, "stage")  # only the last stage wrote; broadcast
        h = rms_norm(out, final_norm, cfg.rms_norm_eps)
        logits = jnp.einsum(
            "bth,hv->btv", h, unembed, preferred_element_type=jnp.float32
        )
        if cfg.final_logit_softcap is not None:  # Gemma soft-capping
            cap = cfg.final_logit_softcap
            logits = jnp.tanh(logits / cap) * cap
        return logits, ck, cv

    unembed = (
        params["embed"].T if cfg.tie_word_embeddings else params["lm_head"]
    )
    fn = shard_map(
        body,
        mesh=mesh,
        axis_names={"stage"},  # tensor/data stay GSPMD-managed inside
        in_specs=(
            P("stage"),  # layer stacks [L, ...] -> local [L/S, ...]
            P(),  # embed
            P(),  # final_norm
            P(),  # unembed
            P(),  # ids
            P(),  # positions
            P("stage"),  # cache_k [L, B, Smax, KV, D]
            P("stage"),  # cache_v
            P(),  # write_pos
            P(),  # kv_valid_len
        ),
        out_specs=(P(), P("stage"), P("stage")),
    )
    return fn(
        params["layers"], params["embed"],
        params["final_norm"], unembed,
        input_ids, positions, cache_k, cache_v, write_pos, kv_valid_len,
    )


def pp_paged_forward(
    mesh,
    params: llama.Params,
    cfg: ModelConfig,
    input_ids: jnp.ndarray,
    positions: jnp.ndarray,
    pool_k: jnp.ndarray,
    pool_v: jnp.ndarray,
    write_slots: jnp.ndarray,
    gather_slots: jnp.ndarray,
    kv_valid_len: jnp.ndarray,
    num_microbatches: int = 1,
    page_size: int = 0,
    logits_idx: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Pipeline-parallel forward over the PAGED KV pool — the serving
    engine's hot path under a ``stage`` mesh axis (the 70B TP x PP north
    star, BASELINE.md config 5).

    Same contract as ``llama.paged_forward`` (XLA gather attention path):
    pools are [L, num_slots, KV, D] and sharded over ``stage`` on the
    layer axis, so each stage holds its own layers' pages; write slots and
    gather rows are position-indexed and microbatch-sliced on the batch
    axis. The ``tensor`` axis (if present) stays GSPMD-managed inside the
    shard_map body, so TP composes without manual collectives. Unlike the
    dense ``pp_forward``, the pool is carried whole through the tick loop:
    microbatches write disjoint slots (their own rows' pages), and bubble
    ticks write to the drop sentinel.

    Int8 KV (VERDICT r4 #4): ``QuantPool`` pools thread through as
    pytrees — both members (codes [L, num_slots, KV, D] int8, scales
    [L, num_slots, KV] f32) shard over ``stage`` on the layer axis, new
    KV quantizes at write time inside each stage's scan, and the gather
    path dequantizes after the page-granular gather, exactly as the
    single-device ``llama.paged_forward`` XLA path does.
    """
    from distributed_inference_server_tpu.ops.quant import (
        QuantPool,
        dequantize_kv,
        pool_num_slots,
    )

    S = mesh.shape.get("stage", 1)
    B, T = input_ids.shape
    M = num_microbatches
    validate_pp(cfg, S, B, M)
    B_mb = B // M
    kv_quantized = isinstance(pool_k, QuantPool)
    num_slots = pool_num_slots(pool_k)
    inv_freq = rope_frequencies(cfg.head_dim, cfg.rope_theta, cfg.rope_scaling)

    slice_h = logits_idx is not None

    def body(layers, embed, final_norm, unembed, ids, pos, pk, pv, ws, gs,
             kvv, lidx):
        stage = lax.axis_index("stage")

        L_stage = layers["attn_norm"].shape[0]
        if cfg.sliding_window:
            win_stage = jnp.asarray(cfg.layer_windows(), jnp.int32).reshape(
                -1, L_stage
            )[stage]
        else:  # static None keeps the maskless gqa branch (no traced w)
            win_stage = None

        def run_stage(h_mb, pos_mb, pk, pv, ws_mb, gs_mb, kvv_mb):
            write_fn = llama.make_paged_write_fn(ws_mb, kv_quantized)

            def attend_fn(q, k_layer, v_layer, w):
                if kv_quantized:
                    kd, vd = llama.gather_kv_window(
                        k_layer.data, v_layer.data, gs_mb, page_size
                    )
                    ks, vs = llama.gather_kv_window(
                        k_layer.scale, v_layer.scale, gs_mb, page_size
                    )
                    k_seq = dequantize_kv(kd, ks, q.dtype)
                    v_seq = dequantize_kv(vd, vs, q.dtype)
                else:
                    k_seq, v_seq = llama.gather_kv_window(
                        k_layer, v_layer, gs_mb, page_size
                    )
                return gqa_attention(q, k_seq, v_seq, pos_mb, kvv_mb, w,
                                     cfg.attn_logit_softcap)

            h_mb, (nk, nv) = llama.scan_layer_blocks(
                cfg, h_mb, layers, pk, pv, win_stage, pos_mb,
                write_fn, attend_fn, inv_freq,
            )
            return h_mb, nk, nv

        def tick(t, carry):
            state, pk, pv, out = carry
            mb = t - stage
            valid = (mb >= 0) & (mb < M)
            row = jnp.clip(mb, 0, M - 1) * B_mb
            ids_mb = lax.dynamic_slice_in_dim(ids, row, B_mb, 0)
            pos_mb = lax.dynamic_slice_in_dim(pos, row, B_mb, 0)
            ws_mb = lax.dynamic_slice_in_dim(ws, row, B_mb, 0)
            gs_mb = lax.dynamic_slice_in_dim(gs, row, B_mb, 0)
            kvv_mb = lax.dynamic_slice_in_dim(kvv, row, B_mb, 0)
            # bubble ticks must not mutate the pool
            ws_eff = jnp.where(valid, ws_mb, num_slots)

            h_emb = embed[ids_mb]
            if cfg.scale_embeddings:  # Gemma: sqrt(hidden) on input
                h_emb = h_emb * jnp.asarray(
                    cfg.hidden_size**0.5, h_emb.dtype
                )
            h_in = jnp.where(stage == 0, h_emb, state)
            h_out, pk, pv = run_stage(h_in, pos_mb, pk, pv, ws_eff, gs_mb,
                                      kvv_mb)

            out_upd = lax.dynamic_update_slice_in_dim(out, h_out, row, 0)
            out = jnp.where(valid & (stage == S - 1), out_upd, out)

            state = lax.ppermute(
                h_out, "stage", [(i, i + 1) for i in range(S - 1)]
            )
            return state, pk, pv, out

        state0 = pcast(
            jnp.zeros((B_mb, T, cfg.hidden_size), embed.dtype),
            "stage", to="varying",
        )
        out0 = pcast(
            jnp.zeros((B, T, cfg.hidden_size), embed.dtype),
            "stage", to="varying",
        )
        state, pk, pv, out = lax.fori_loop(
            0, M + S - 1, tick, (state0, pk, pv, out0)
        )

        out = lax.psum(out, "stage")  # only the last stage wrote; broadcast
        if slice_h:
            # single-position unembed (prefill chunks): slice hidden
            # states BEFORE the vocab projection so the [B, T, V]
            # materialization never happens on any stage
            out = out[jnp.arange(out.shape[0]), lidx][:, None]
        h = rms_norm(out, final_norm, cfg.rms_norm_eps)
        logits = jnp.einsum(
            "bth,hv->btv", h, unembed, preferred_element_type=jnp.float32
        )
        if cfg.final_logit_softcap is not None:  # Gemma soft-capping
            cap = cfg.final_logit_softcap
            logits = jnp.tanh(logits / cap) * cap
        return logits, pk, pv

    unembed = (
        params["embed"].T if cfg.tie_word_embeddings else params["lm_head"]
    )
    # QuantPool pools: codes AND scales stage-shard on the layer axis
    pool_spec = (
        QuantPool(P("stage"), P("stage")) if kv_quantized else P("stage")
    )
    fn = shard_map(
        body,
        mesh=mesh,
        axis_names={"stage"},  # tensor/data stay GSPMD-managed inside
        in_specs=(
            P("stage"),  # layer stacks [L, ...] -> local [L/S, ...]
            P(),  # embed
            P(),  # final_norm
            P(),  # unembed
            P(),  # ids
            P(),  # positions
            pool_spec,  # pool_k [L, num_slots, KV, D]
            pool_spec,  # pool_v
            P(),  # write_slots
            P(),  # gather_slots
            P(),  # kv_valid_len
            P(),  # logits_idx (or its zero placeholder)
        ),
        out_specs=(P(), pool_spec, pool_spec),
    )
    lidx = (
        logits_idx if slice_h
        else jnp.zeros((input_ids.shape[0],), jnp.int32)
    )
    return fn(
        params["layers"], params["embed"],
        params["final_norm"], unembed,
        input_ids, positions, pool_k, pool_v, write_slots, gather_slots,
        kv_valid_len, lidx,
    )


def pp_greedy_generate(
    mesh,
    params: llama.Params,
    cfg: ModelConfig,
    prompt_ids: jnp.ndarray,
    max_new_tokens: int,
    max_seq: int,
    num_microbatches: int = 1,
) -> jnp.ndarray:
    """Greedy generation through the pipeline: prefill then per-token
    decode steps, all over the stage axis. prompt_ids: [B, T0] (no
    padding). Returns [B, max_new_tokens]."""
    B, T0 = prompt_ids.shape
    cache = llama.KVCache.create(cfg, B, max_seq, dtype=params["embed"].dtype)
    positions = jnp.broadcast_to(jnp.arange(T0)[None], (B, T0))
    step = functools.partial(pp_forward, mesh, params, cfg,
                             num_microbatches=num_microbatches)
    with mesh:
        logits, ck, cv = step(
            prompt_ids, positions, cache.k, cache.v, positions,
            jnp.full((B,), T0, jnp.int32),
        )
        tok = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        outs = [tok]
        for i in range(1, max_new_tokens):
            pos = jnp.full((B, 1), T0 + i - 1, jnp.int32)
            logits, ck, cv = step(
                tok[:, None], pos, ck, cv, pos,
                jnp.full((B,), T0 + i, jnp.int32),
            )
            tok = jnp.argmax(logits[:, 0], axis=-1).astype(jnp.int32)
            outs.append(tok)
    return jnp.stack(outs, axis=1)
