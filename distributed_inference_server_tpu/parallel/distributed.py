"""Multi-host distributed backend: DCN + ICI two-plane communication.

SURVEY.md §5 sets the bar the reference never attempted (its "workers"
were in-process tokio channels, ``design.md:264-265`` [spec]; WorkerId
"local to a single server instance", ``types.rs:10``):

- **data plane** — the JAX distributed runtime: every host runs the same
  program, ``jax.distributed.initialize`` connects them through the
  coordinator, ``jax.devices()`` becomes the GLOBAL device set, and GSPMD
  emits DCN collectives for mesh axes that cross hosts and ICI
  collectives for axes within a slice. ``hybrid_mesh`` builds the
  canonical layout: slow axes (data/stage) outermost over DCN, fast axes
  (tensor/seq/expert) innermost over ICI — collectives ride the right
  fabric by construction.
- **control plane** — serving/router.py: request routing between hosts
  stays at the HTTP boundary (the reference's scheduler shape, one
  process per host), so the data plane never carries request traffic.

Single-host processes (num_processes == 1) skip initialization entirely —
the same binary serves laptop CPU, one chip, or a pod slice.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

from distributed_inference_server_tpu.parallel.mesh import AXES, MeshSpec

_initialized = False


@dataclasses.dataclass(frozen=True)
class DistributedConfig:
    """jax.distributed settings for one process of a multi-host fleet.

    coordinator_address: "host:port" of process 0 (every process passes
    the same value). num_processes: world size. process_id: this
    process's rank; -1 = let the TPU platform infer it (metadata-based
    auto-detection on Cloud TPU VMs).
    """

    coordinator_address: str = ""
    num_processes: int = 1
    process_id: int = -1
    local_device_ids: Optional[Tuple[int, ...]] = None

    @property
    def enabled(self) -> bool:
        return self.num_processes > 1


def initialize(cfg: DistributedConfig) -> bool:
    """Connect this process to the fleet (idempotent). Returns True when
    the distributed runtime was (or already is) live, False for
    single-process configs. Must run before any backend touches devices."""
    global _initialized
    if not cfg.enabled:
        return False
    if _initialized:
        return True
    import jax

    kwargs = {
        "coordinator_address": cfg.coordinator_address or None,
        "num_processes": cfg.num_processes,
    }
    if cfg.process_id >= 0:
        kwargs["process_id"] = cfg.process_id
    if cfg.local_device_ids is not None:
        kwargs["local_device_ids"] = list(cfg.local_device_ids)
    jax.distributed.initialize(**kwargs)
    _initialized = True
    return True


def hybrid_mesh(
    spec: MeshSpec,
    dcn_spec: Optional[MeshSpec] = None,
    devices: Optional[Sequence] = None,
):
    """Mesh over a multi-host fleet with DCN-aware device placement.

    ``spec`` sizes the per-slice (ICI) extent of each axis; ``dcn_spec``
    sizes the cross-slice (DCN) extent (default: replicate nothing across
    DCN except the data axis, absorbed from the process count). The
    resulting global axis size is ici * dcn per axis, laid out so that
    consecutive devices along a DCN-extended axis stay within a slice —
    jax.experimental.mesh_utils.create_hybrid_device_mesh's contract —
    and GSPMD therefore lowers intra-slice hops to ICI collectives and
    only the outer strides to DCN.

    Falls back to the dense mesh (mesh.py:make_mesh) when the runtime is
    not distributed (tests, single host): same axis names, same specs,
    so PartitionSpecs are portable between the two.
    """
    import jax
    from jax.sharding import Mesh

    devices = list(devices if devices is not None else jax.devices())
    n_slices = len({getattr(d, "slice_index", 0) for d in devices})
    if dcn_spec is None:
        # data-parallel across hosts by default
        dcn_spec = MeshSpec(data=n_slices) if n_slices > 1 else MeshSpec()
    elif 0 in dcn_spec.sizes():
        dcn_spec = dcn_spec.resolve(n_slices)
    ici = spec.resolve(len(devices) // max(1, _prod(dcn_spec.sizes())))
    if n_slices <= 1:
        # single slice: collapse to the dense mesh (DCN extents fold in)
        merged = MeshSpec(*[a * b for a, b in
                            zip(ici.sizes(), dcn_spec.sizes())])
        from distributed_inference_server_tpu.parallel.mesh import make_mesh

        return make_mesh(merged, devices)
    from jax.experimental import mesh_utils

    grid = mesh_utils.create_hybrid_device_mesh(
        ici.sizes(), dcn_spec.sizes(), devices=devices,
        allow_split_physical_axes=True,
    )
    return Mesh(grid, axis_names=AXES)


def _prod(xs) -> int:
    out = 1
    for x in xs:
        out *= x
    return out


def process_count() -> int:
    import jax

    return jax.process_count()


def is_coordinator() -> bool:
    import jax

    return jax.process_index() == 0


def global_batch_shard(batch: int) -> Tuple[int, int]:
    """(this process's shard size, offset) of a global batch laid out
    contiguously over processes — the serving layer's unit of cross-host
    data parallelism when one logical engine spans hosts."""
    import jax

    n, i = jax.process_count(), jax.process_index()
    base, rem = divmod(batch, n)
    size = base + (1 if i < rem else 0)
    offset = i * base + min(i, rem)
    return size, offset
