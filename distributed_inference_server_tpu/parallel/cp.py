"""Context-parallel prefill: long prompts sharded over the `seq` mesh axis.

The reference capped context at 8192 tokens and had no sequence scaling
(``validator.rs:20``; SURVEY.md §5). Here a long prompt's prefill spans
chips: token ids/positions/activations are sharded over ``seq`` (GSPMD
keeps every elementwise/matmul op local to its chunk), and attention runs
as ring attention (ops/ring_attention.py) — KV chunks rotating over ICI
via collective-permute while each chip accumulates blockwise softmax for
its queries. Composes with tensor parallelism (heads sharded over
``tensor`` inside the ring) and data parallelism (batch over ``data``).

This is the prefill path for prompts too long for one chip's HBM or too
slow for one chip's MXU; decode afterwards proceeds on the paged cache
(the KV produced here lands in cache layout [B, S, KV, D] with slot ==
position, ready to be scattered into pool pages).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_inference_server_tpu.models import llama
from distributed_inference_server_tpu.models.configs import ModelConfig
from distributed_inference_server_tpu.ops.ring_attention import (
    ring_attention_sharded,
)


def cp_prefill(
    params: llama.Params,
    cfg: ModelConfig,
    mesh,
    input_ids: jnp.ndarray,
    valid_len: jnp.ndarray,
    sp_impl: str = "ring",
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Context-parallel prefill of a ragged batch of prompts.

    Args:
      input_ids: [B, T] token ids, right-padded; T must divide by the
        ``seq`` axis size.
      valid_len: [B] prompt lengths.
      sp_impl: "ring" (KV chunks rotate over ICI, ops/ring_attention.py)
        or "ulysses" (all-to-all head scatter, ops/ulysses.py — axis size
        must divide the query- and KV-head counts).

    Returns (last_logits [B, V] f32, k, v) where k, v are
    [L, B, T, KV, D] caches with slot == position (padding slots hold
    zeros) — the dense-cache layout decode starts from.
    """
    B, T = input_ids.shape
    seq = mesh.shape.get("seq", 1)
    if T % seq:
        raise ValueError(f"prompt buffer {T} not divisible by seq axis {seq}")
    if sp_impl not in ("ring", "ulysses"):
        raise ValueError(f"sp_impl must be 'ring' or 'ulysses', got {sp_impl!r}")

    pos = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None], (B, T))
    positions = jnp.where(pos < valid_len[:, None], pos, -1)
    # padding writes are dropped (slot T is out of range for the cache)
    write_pos = jnp.where(positions >= 0, positions, T)

    # per-layer window: w rides the layer scan as a traced scalar (the
    # Gemma-2 alternating local/global schedule works under CP), passed
    # into the attends through their specs; score soft-capping applies
    # inside the blockwise softmax. Non-sliding models keep the static
    # maskless branch (w arrives as None from scan_layer_blocks).
    softcap = cfg.attn_logit_softcap
    if sp_impl == "ulysses":
        from distributed_inference_server_tpu.ops.ulysses import (
            ulysses_attention_sharded,
        )

        def attend(q, k_layer, v_layer, w):
            return ulysses_attention_sharded(
                mesh, q, k_layer, v_layer, positions, valid_len,
                sliding_window=w, attn_softcap=softcap,
            )
    else:

        def attend(q, k_layer, v_layer, w):
            return ring_attention_sharded(
                mesh, q, k_layer, v_layer, positions, positions,
                sliding_window=w, attn_softcap=softcap,
            )

    cache = llama.KVCache.create(cfg, B, T, dtype=params["embed"].dtype)
    h, new_k, new_v = llama._run_layers(
        params, cfg, input_ids, positions, cache.k, cache.v,
        lambda layer, new: llama._write_kv(layer, new, write_pos),
        attend,
    )
    last = jnp.take_along_axis(
        h, (valid_len - 1)[:, None, None].astype(jnp.int32), axis=1
    )  # [B, 1, H]
    logits = llama._unembed(params, cfg, last)[:, 0]
    return logits, new_k, new_v


def cp_paged_prefill(
    params: llama.Params,
    cfg: ModelConfig,
    mesh,
    input_ids: jnp.ndarray,
    valid_len: jnp.ndarray,
    pool_k: jnp.ndarray,
    pool_v: jnp.ndarray,
    write_slots: jnp.ndarray,
    sp_impl: str = "ring",
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Sequence-parallel prefill that lands in the paged pool — the
    dense-KV→pages hand-off the engine's long-prompt admission path uses
    (the reference had no long-context path at all; context hard-capped
    at 8192, ``validator.rs:20``).

    Runs ``cp_prefill`` (sequence sharded over the ``seq`` mesh axis;
    ``sp_impl`` picks ring attention or Ulysses all-to-all), then
    scatters the position-ordered dense K/V into the flat page pools at
    per-token ``write_slots`` ([B, T] flat slot per position, >=
    num_slots drops the write — padding). After this the prompt decodes
    from pages like any other sequence.

    Returns (last_logits [B, V] f32, new pool_k, new pool_v).
    """
    logits, k, v = cp_prefill(
        params, cfg, mesh, input_ids, valid_len, sp_impl=sp_impl
    )
    # k, v: [L, B, T, KV, D] slot==position; pool: [L, num_slots, KV, D]
    pool_k = pool_k.at[:, write_slots].set(k.astype(pool_k.dtype), mode="drop")
    pool_v = pool_v.at[:, write_slots].set(v.astype(pool_v.dtype), mode="drop")
    return logits, pool_k, pool_v


def cp_shardings(mesh):
    """(ids, valid) input shardings for jitting ``cp_prefill``."""
    return (
        NamedSharding(mesh, P("data", "seq")),
        NamedSharding(mesh, P("data")),
    )
