"""Context-parallel prefill: long prompts sharded over the `seq` mesh axis.

The reference capped context at 8192 tokens and had no sequence scaling
(``validator.rs:20``; SURVEY.md §5). Here a long prompt's prefill spans
chips: token ids/positions/activations are sharded over ``seq`` (GSPMD
keeps every elementwise/matmul op local to its chunk), and attention runs
as ring attention (ops/ring_attention.py) — KV chunks rotating over ICI
via collective-permute while each chip accumulates blockwise softmax for
its queries. Composes with tensor parallelism (heads sharded over
``tensor`` inside the ring) and data parallelism (batch over ``data``).

This is the prefill path for prompts too long for one chip's HBM or too
slow for one chip's MXU; decode afterwards proceeds on the paged cache
(the KV produced here lands in cache layout [B, S, KV, D] with slot ==
position, ready to be scattered into pool pages).
"""

from __future__ import annotations

from typing import Tuple

import jax
from distributed_inference_server_tpu.utils.compat import pcast, shard_map
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_inference_server_tpu.models import llama
from distributed_inference_server_tpu.models.configs import ModelConfig
from distributed_inference_server_tpu.ops.ring_attention import (
    ring_attention,
    ring_attention_sharded,
)


def cp_prefill(
    params: llama.Params,
    cfg: ModelConfig,
    mesh,
    input_ids: jnp.ndarray,
    valid_len: jnp.ndarray,
    sp_impl: str = "ring",
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Context-parallel prefill of a ragged batch of prompts.

    Args:
      input_ids: [B, T] token ids, right-padded; T must divide by the
        ``seq`` axis size.
      valid_len: [B] prompt lengths.
      sp_impl: "ring" (KV chunks rotate over ICI, ops/ring_attention.py)
        or "ulysses" (all-to-all head scatter, ops/ulysses.py — axis size
        must divide the query- and KV-head counts).

    Returns (last_logits [B, V] f32, k, v) where k, v are
    [L, B, T, KV, D] caches with slot == position (padding slots hold
    zeros) — the dense-cache layout decode starts from.
    """
    B, T = input_ids.shape
    seq = mesh.shape.get("seq", 1)
    if T % seq:
        raise ValueError(f"prompt buffer {T} not divisible by seq axis {seq}")
    if sp_impl not in ("ring", "ulysses"):
        raise ValueError(f"sp_impl must be 'ring' or 'ulysses', got {sp_impl!r}")

    pos = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None], (B, T))
    positions = jnp.where(pos < valid_len[:, None], pos, -1)
    # padding writes are dropped (slot T is out of range for the cache)
    write_pos = jnp.where(positions >= 0, positions, T)

    # per-layer window: w rides the layer scan as a traced scalar (the
    # Gemma-2 alternating local/global schedule works under CP), passed
    # into the attends through their specs; score soft-capping applies
    # inside the blockwise softmax. Non-sliding models keep the static
    # maskless branch (w arrives as None from scan_layer_blocks).
    softcap = cfg.attn_logit_softcap
    if sp_impl == "ulysses":
        from distributed_inference_server_tpu.ops.ulysses import (
            ulysses_attention_sharded,
        )

        def attend(q, k_layer, v_layer, w):
            return ulysses_attention_sharded(
                mesh, q, k_layer, v_layer, positions, valid_len,
                sliding_window=w, attn_softcap=softcap,
            )
    else:

        def attend(q, k_layer, v_layer, w):
            return ring_attention_sharded(
                mesh, q, k_layer, v_layer, positions, positions,
                sliding_window=w, attn_softcap=softcap,
            )

    cache = llama.KVCache.create(cfg, B, T, dtype=params["embed"].dtype)
    h, new_k, new_v = llama._run_layers(
        params, cfg, input_ids, positions, cache.k, cache.v,
        lambda pool, l, new: llama._write_kv(pool, l, new, write_pos),
        attend,
    )
    last = jnp.take_along_axis(
        h, (valid_len - 1)[:, None, None].astype(jnp.int32), axis=1
    )  # [B, 1, H]
    logits = llama._unembed(params, cfg, last)[:, 0]
    return logits, new_k, new_v


def cp_paged_prefill(
    params: llama.Params,
    cfg: ModelConfig,
    mesh,
    input_ids: jnp.ndarray,
    valid_len: jnp.ndarray,
    pool_k: jnp.ndarray,
    pool_v: jnp.ndarray,
    write_slots: jnp.ndarray,
    sp_impl: str = "ring",
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Sequence-parallel prefill that lands in the paged pool — the
    dense-KV→pages hand-off the engine's long-prompt admission path uses
    (the reference had no long-context path at all; context hard-capped
    at 8192, ``validator.rs:20``).

    Runs ``cp_prefill`` (sequence sharded over the ``seq`` mesh axis;
    ``sp_impl`` picks ring attention or Ulysses all-to-all), then
    scatters the position-ordered dense K/V into the flat page pools at
    per-token ``write_slots`` ([B, T] flat slot per position, >=
    num_slots drops the write — padding). After this the prompt decodes
    from pages like any other sequence.

    Returns (last_logits [B, V] f32, new pool_k, new pool_v).
    """
    logits, k, v = cp_prefill(
        params, cfg, mesh, input_ids, valid_len, sp_impl=sp_impl
    )
    # k, v: [L, B, T, KV, D] slot==position; pool: [L, num_slots, KV, D]
    return logits, _scatter_pool(pool_k, k, write_slots), _scatter_pool(
        pool_v, v, write_slots
    )


def _scatter_pool(pool, kv, write_slots):
    """Scatter dense slot==position K/V [L, B, T, KV, D] into a flat page
    pool at per-token ``write_slots`` (>= num_slots drops — padding).
    ``QuantPool`` pools quantize at scatter time (per-vector absmax), so
    ring/Ulysses prefill composes with the int8 KV cache."""
    from distributed_inference_server_tpu.ops.quant import (
        QuantPool,
        quantize_kv,
    )

    if isinstance(pool, QuantPool):
        codes, scale = quantize_kv(kv)
        return QuantPool(
            pool.data.at[:, write_slots].set(codes, mode="drop"),
            pool.scale.at[:, write_slots].set(scale, mode="drop"),
        )
    return pool.at[:, write_slots].set(kv.astype(pool.dtype), mode="drop")


def cp_pp_prefill(
    params: llama.Params,
    cfg: ModelConfig,
    mesh,
    input_ids: jnp.ndarray,
    valid_len: jnp.ndarray,
    num_microbatches: int = 1,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Ring-attention prefill on a ``seq`` x ``stage`` mesh — CP composed
    with pipeline parallelism in ONE program (VERDICT r4 #5).

    Why not ``cp_prefill`` under ``pp.pp_forward``: ring attention was a
    self-contained shard_map over {data, seq, tensor}, and nesting that
    inside the GPipe stage loop's partial-manual ``stage`` shard_map
    deadlocked XLA's collective scheduling (repro:
    tools/nested_shardmap_repro.py). The fix is structural — ONE
    partial-manual shard_map spanning BOTH axes, with the stage tick loop
    inside and the per-shard ``ring_attention`` body (not its sharded
    wrapper) as the attend. Every device then runs the identical tick
    program: the seq-axis ``ppermute``s of the KV ring and the stage-axis
    ``ppermute``s of the activation hand-off are issued in the same
    static order everywhere, which is exactly the property the nested
    form lost. ``data``/``tensor`` stay GSPMD-managed inside, so DP x TP
    x SP x PP all compose here.

    Layout: stage s holds layers [s*L/S, (s+1)*L/S); seq shard i holds
    token chunk i (Tl = T/seq) of every microbatch's activations and of
    the dense slot==position KV cache — each device's cache slice is
    [L/S, B, Tl, KV, D]: HBM for the prefill intermediate scales down by
    BOTH axes. Causality rides absolute positions (padding = -1), which
    rotate with the KV chunks, so the mask is exact for ragged batches.

    Args/returns match ``cp_prefill`` (plus ``num_microbatches``):
    (last_logits [B, V] f32, k, v [L, B, T, KV, D] slot==position).
    """
    from distributed_inference_server_tpu.ops.norms import rms_norm
    from distributed_inference_server_tpu.ops.rotary import rope_frequencies

    S = mesh.shape.get("stage", 1)
    R = mesh.shape.get("seq", 1)
    B, T = input_ids.shape
    M = num_microbatches
    if cfg.num_layers % S:
        raise ValueError(f"{S} stages do not divide num_layers={cfg.num_layers}")
    if B % M:
        raise ValueError(f"{M} microbatches do not divide batch={B}")
    if T % R:
        raise ValueError(f"prompt buffer {T} not divisible by seq axis {R}")
    B_mb = B // M
    Tl = T // R
    inv_freq = rope_frequencies(cfg.head_dim, cfg.rope_theta, cfg.rope_scaling)
    softcap = cfg.attn_logit_softcap

    pos = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None], (B, T))
    positions = jnp.where(pos < valid_len[:, None], pos, -1)

    def body(layers, embed, final_norm, unembed, ids, pos_l, valid):
        # locals: layers [L/S,...] (this stage), ids/pos_l [B, Tl] (this
        # seq chunk); cache slices are [L/S, B, Tl, KV, D]
        stage = lax.axis_index("stage")
        seq_i = lax.axis_index("seq")

        L_stage = layers["attn_norm"].shape[0]
        if cfg.sliding_window:
            win_stage = jnp.asarray(
                cfg.layer_windows(), jnp.int32
            ).reshape(-1, L_stage)[stage]
        else:
            win_stage = None

        # dense slot==position (local) writes; padding tokens drop (Tl)
        slot_of = jnp.broadcast_to(
            jnp.arange(Tl, dtype=jnp.int32)[None], (B, Tl)
        )
        wp_all = jnp.where(pos_l >= 0, slot_of, Tl)

        def run_stage(h_mb, pos_mb, ck_mb, cv_mb, wp_mb):
            write_fn = lambda pool, l, new: llama._write_kv(
                pool, l, new, wp_mb)

            def attend_fn(q, k_layer, v_layer, w):
                # per-shard ring body: KV chunks rotate over `seq` while
                # this device accumulates blockwise softmax for its
                # queries. Cache slot == local position, so the layer
                # cache IS the local KV chunk and pos_mb is both the
                # query- and key-position map (padding -1 never attends).
                return ring_attention(
                    q, k_layer, v_layer, pos_mb, pos_mb,
                    axis_name="seq", sliding_window=w,
                    attn_softcap=softcap,
                )

            h_mb, (nk, nv) = llama.scan_layer_blocks(
                cfg, h_mb, layers, ck_mb, cv_mb, win_stage, pos_mb,
                write_fn, attend_fn, inv_freq,
            )
            return h_mb, nk, nv

        def tick(t, carry):
            state, ck, cv, out = carry
            mb = t - stage
            tick_valid = (mb >= 0) & (mb < M)
            row = jnp.clip(mb, 0, M - 1) * B_mb
            ids_mb = lax.dynamic_slice_in_dim(ids, row, B_mb, 0)
            pos_mb = lax.dynamic_slice_in_dim(pos_l, row, B_mb, 0)
            wp_mb = lax.dynamic_slice_in_dim(wp_all, row, B_mb, 0)
            ck_mb = lax.dynamic_slice_in_dim(ck, row, B_mb, 1)
            cv_mb = lax.dynamic_slice_in_dim(cv, row, B_mb, 1)
            # bubble ticks must not mutate the cache
            wp_eff = jnp.where(tick_valid, wp_mb, Tl)

            h_emb = embed[ids_mb]
            if cfg.scale_embeddings:  # Gemma: sqrt(hidden) on input
                h_emb = h_emb * jnp.asarray(cfg.hidden_size**0.5, h_emb.dtype)
            h_in = jnp.where(stage == 0, h_emb, state)
            h_out, nk, nv = run_stage(h_in, pos_mb, ck_mb, cv_mb, wp_eff)
            ck = lax.dynamic_update_slice_in_dim(ck, nk, row, 1)
            cv = lax.dynamic_update_slice_in_dim(cv, nv, row, 1)

            out_upd = lax.dynamic_update_slice_in_dim(out, h_out, row, 0)
            out = jnp.where(tick_valid & (stage == S - 1), out_upd, out)

            state = lax.ppermute(
                h_out, "stage", [(i, i + 1) for i in range(S - 1)]
            )
            return state, ck, cv, out

        dt = embed.dtype
        state0 = pcast(
            jnp.zeros((B_mb, Tl, cfg.hidden_size), dt), "stage", to="varying"
        )
        state0 = pcast(state0, "seq", to="varying")
        out0 = pcast(
            jnp.zeros((B, Tl, cfg.hidden_size), dt), "stage", to="varying"
        )
        out0 = pcast(out0, "seq", to="varying")
        ck0 = pcast(
            pcast(
                jnp.zeros((L_stage, B, Tl, cfg.num_kv_heads, cfg.head_dim),
                          dt),
                "stage", to="varying",
            ),
            "seq", to="varying",
        )
        cv0 = ck0
        state, ck, cv, out = lax.fori_loop(
            0, M + S - 1, tick, (state0, ck0, cv0, out0)
        )

        out = lax.psum(out, "stage")  # only the last stage wrote
        # the last valid token lives on exactly one seq shard: pick the
        # local row (or zeros) and combine across the ring
        li = (valid - 1).astype(jnp.int32) - seq_i * Tl  # [B]
        here = (li >= 0) & (li < Tl)
        last = jnp.take_along_axis(
            out, jnp.clip(li, 0, Tl - 1)[:, None, None], axis=1
        )  # [B, 1, H]
        last = lax.psum(
            jnp.where(here[:, None, None], last, 0.0), "seq"
        )
        h = rms_norm(last, final_norm, cfg.rms_norm_eps)
        logits = jnp.einsum(
            "bth,hv->btv", h, unembed, preferred_element_type=jnp.float32
        )
        if cfg.final_logit_softcap is not None:
            cap = cfg.final_logit_softcap
            logits = jnp.tanh(logits / cap) * cap
        return logits[:, 0], ck, cv

    unembed = (
        params["embed"].T if cfg.tie_word_embeddings else params["lm_head"]
    )
    fn = shard_map(
        body,
        mesh=mesh,
        axis_names={"seq", "stage"},  # data/tensor stay GSPMD-managed
        in_specs=(
            P("stage"),  # layer stacks [L, ...] -> local [L/S, ...]
            P(),  # embed
            P(),  # final_norm
            P(),  # unembed
            P(None, "seq"),  # ids [B, T] -> [B, Tl]
            P(None, "seq"),  # positions
            P(),  # valid_len
        ),
        out_specs=(
            P(),  # last logits [B, V]
            P("stage", None, "seq"),  # k [L, B, T, KV, D]
            P("stage", None, "seq"),  # v
        ),
    )
    return fn(
        params["layers"], params["embed"], params["final_norm"], unembed,
        input_ids, positions, valid_len.astype(jnp.int32),
    )


def cp_paged_prefill_any(
    params: llama.Params,
    cfg: ModelConfig,
    mesh,
    input_ids: jnp.ndarray,
    valid_len: jnp.ndarray,
    pool_k: jnp.ndarray,
    pool_v: jnp.ndarray,
    write_slots: jnp.ndarray,
    sp_impl: str = "ring",
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """``cp_paged_prefill`` that also handles ``stage`` meshes: on a
    seq x stage mesh the ring runs via ``cp_pp_prefill`` (one unified
    shard_map) and the dense K/V — sharded over BOTH the layer axis
    (stage) and positions (seq) — scatter into the stage-sharded page
    pools. The layer axis of pool and source align, so the scatter stays
    stage-local; GSPMD all-gathers each stage's seq chunks over ICI."""
    if mesh.shape.get("stage", 1) > 1:
        if sp_impl != "ring":
            raise ValueError(
                "sequence parallelism on a stage mesh supports sp_impl="
                "'ring' only (ulysses is seq-only)"
            )
        logits, k, v = cp_pp_prefill(params, cfg, mesh, input_ids, valid_len)
        return logits, _scatter_pool(pool_k, k, write_slots), _scatter_pool(
            pool_v, v, write_slots
        )
    return cp_paged_prefill(
        params, cfg, mesh, input_ids, valid_len, pool_k, pool_v,
        write_slots, sp_impl=sp_impl,
    )


def cp_shardings(mesh):
    """(ids, valid) input shardings for jitting ``cp_prefill``."""
    return (
        NamedSharding(mesh, P("data", "seq")),
        NamedSharding(mesh, P("data")),
    )
