"""Tensor parallelism for the Llama family: sharding rules + helpers.

Megatron-style layout expressed as GSPMD annotations (no manual collectives
— XLA inserts AllReduce over ICI where a contraction dimension is sharded):

- ``wq``/``wk``/``wv``: column-parallel — output features (heads) split on
  ``tensor``; each shard computes its own heads' q/k/v.
- ``wo``: row-parallel — input features split; the matmul produces partial
  sums that XLA AllReduces into the residual stream.
- ``w_gate``/``w_up``: column-parallel on the intermediate dim;
  ``w_down``: row-parallel (second AllReduce per block).
- Unembedding: VOCAB-PARALLEL — ``lm_head`` [H, V] splits V on ``tensor``
  (tied-embedding models split ``embed`` [V, H] on V instead, paying a
  small [B, T, H] AllReduce on the masked embedding lookup). Each shard
  projects its vocab slice — at Llama-3's 128k vocab a replicated [B, V]
  projection per shard is the single largest TP tax — and XLA inserts
  the gather/reduce the consuming sampling op actually needs (argmax and
  sort reduce over the sharded axis; no hand-written collectives).
- Norms: replicated.
- Paged KV pool: sharded on the KV-head dim — each shard holds its own
  heads' pages, so cache writes and the attention gather are fully local;
  per-shard GQA groups stay intact (num_heads/num_kv_heads q heads per KV
  head per shard).

TP size must divide ``num_kv_heads`` (and thereby ``num_heads`` and
``intermediate_size`` for any real config); ``validate_tp`` checks this.

The reference has no equivalent (SURVEY.md §2.3: TP "No"); the north-star
configuration is TP=8 for Llama-3-8B on a v5e-8 (BASELINE.md).
"""

from __future__ import annotations

from typing import Any, Dict

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_inference_server_tpu.models.configs import ModelConfig
from distributed_inference_server_tpu.models.llama import Params


def validate_tp(cfg: ModelConfig, tp: int) -> None:
    if tp <= 0:
        raise ValueError(f"tensor parallel size must be positive, got {tp}")
    for dim_name, dim in (
        ("num_kv_heads", cfg.num_kv_heads),
        ("num_heads", cfg.num_heads),
        ("intermediate_size", cfg.intermediate_size),
    ):
        if dim % tp:
            raise ValueError(
                f"tensor parallel size {tp} does not divide {dim_name}={dim}"
            )


def llama_param_specs(
    cfg: ModelConfig, stage_axis: str | None = None
) -> Dict[str, Any]:
    """PartitionSpec pytree matching ``llama.init_params`` exactly.

    Layer weights are stacked [L, in, out]: axis 0 is the scan axis —
    unsharded under pure TP, or split over ``stage_axis`` when pipeline
    parallelism is active (each stage holds its contiguous layer slice,
    parallel/pp.py). Column-parallel = spec on axis 2, row-parallel =
    axis 1.
    """
    st = stage_axis
    layers: Dict[str, Any] = {
        "attn_norm": P(st, None),
        "wq": P(st, None, "tensor"),
        "wk": P(st, None, "tensor"),
        "wv": P(st, None, "tensor"),
        "wo": P(st, "tensor", None),
        "mlp_norm": P(st, None),
    }
    if cfg.sandwich_norms:
        # Gemma-2 output norms: [L, H] replicated like the pre-norms
        layers.update(
            post_attn_norm=P(st, None),
            post_mlp_norm=P(st, None),
        )
    if cfg.attention_bias:
        # biases follow their column-parallel projections: [L, out] with
        # the output features (heads) split on "tensor"
        layers.update(
            bq=P(st, "tensor"),
            bk=P(st, "tensor"),
            bv=P(st, "tensor"),
        )
    if cfg.is_moe:
        layers.update(
            router=P(st, None, None),
            # [L, E, in, out]: experts on "expert", features on "tensor"
            w_gate=P(st, "expert", None, "tensor"),
            w_up=P(st, "expert", None, "tensor"),
            w_down=P(st, "expert", "tensor", None),
        )
    else:
        layers.update(
            w_gate=P(st, None, "tensor"),
            w_up=P(st, None, "tensor"),
            w_down=P(st, "tensor", None),
        )
    specs: Dict[str, Any] = {
        # vocab-parallel unembedding: untied models shard lm_head's vocab
        # axis; tied models shard the embedding table's vocab axis (its
        # transpose IS the unembedding) and GSPMD masks the lookup
        "embed": (
            P("tensor", None) if cfg.tie_word_embeddings else P(None, None)
        ),
        "layers": layers,
        "final_norm": P(None),
    }
    if not cfg.tie_word_embeddings:
        specs["lm_head"] = P(None, "tensor")
    return specs


def kv_pool_spec(stage_axis: str | None = None) -> P:
    """Paged KV pool [L, num_slots, KV_heads, D]: KV heads on 'tensor';
    layers on ``stage_axis`` under pipeline parallelism."""
    return P(stage_axis, None, "tensor", None)


def shard_params(
    params: Params, mesh: Mesh, cfg: ModelConfig,
    stage_axis: str | None = None,
) -> Params:
    """Place parameters onto the mesh per the TP layout (the weight-loading
    "restore" path — SURVEY.md §5 checkpoint/resume equivalent: safetensors
    → host → sharded device buffers). Quantized weights (ops/quant.py)
    shard q and scales with the same spec: both are [..., in-ish, out], so
    column/row-parallel axes line up."""
    from distributed_inference_server_tpu.ops.quant import is_quantized

    specs = llama_param_specs(cfg, stage_axis=stage_axis)

    def place(spec, leaf):
        sh = NamedSharding(mesh, spec)
        if is_quantized(leaf):
            # scales are [..., groups, out]: the group axis replaces the
            # weight's input axis and its count (in/group_size) need not
            # divide tp — replicate that axis, keep the rest of the spec
            # (scales are tiny; replication is free)
            parts = list(spec) + [None] * (leaf.s.ndim - len(spec))
            parts[-2] = None
            s_sh = NamedSharding(mesh, P(*parts))
            return type(leaf)(
                q=jax.device_put(leaf.q, sh), s=jax.device_put(leaf.s, s_sh)
            )
        return jax.device_put(leaf, sh)

    return jax.tree_util.tree_map(
        place, specs, params, is_leaf=lambda x: isinstance(x, P)
    )
