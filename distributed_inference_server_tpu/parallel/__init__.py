"""Parallel execution: device meshes, TP/EP/PP/SP sharding rules.

The reference has no parallelism (SURVEY.md §2.3 absence audit); this
package is the TPU-native scale-out layer: explicit meshes + GSPMD
shardings compiled by pjit, collectives over ICI/DCN inserted by XLA.
"""

from distributed_inference_server_tpu.parallel.mesh import (
    AXES,
    MeshSpec,
    largest_tp,
    make_mesh,
    sharding,
    tp_mesh,
)
from distributed_inference_server_tpu.parallel.tp import (
    kv_pool_spec,
    llama_param_specs,
    shard_params,
    validate_tp,
)
from distributed_inference_server_tpu.parallel.cp import (
    cp_prefill,
    cp_shardings,
)
from distributed_inference_server_tpu.parallel.distributed import (
    DistributedConfig,
    hybrid_mesh,
    initialize as initialize_distributed,
)

__all__ = [
    "cp_prefill",
    "cp_shardings",
    "DistributedConfig",
    "hybrid_mesh",
    "initialize_distributed",
    "AXES",
    "MeshSpec",
    "largest_tp",
    "make_mesh",
    "sharding",
    "tp_mesh",
    "kv_pool_spec",
    "llama_param_specs",
    "shard_params",
    "validate_tp",
]
