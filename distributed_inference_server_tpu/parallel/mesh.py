"""Device-mesh construction for TP/DP/EP/PP/SP execution.

The reference has no parallelism of any kind (SURVEY.md §2.3 — "workers"
are whole-model replicas in one process, ``types.rs:10``); this module is
the TPU-native foundation it lacked: an explicit ``jax.sharding.Mesh`` with
named axes, over which pjit/GSPMD lays out weights and inserts ICI
collectives (AllReduce/AllGather/AllToAll/CollectivePermute).

Axis vocabulary (SURVEY.md §7.1):

- ``data``   — batch rows (replica-level DP *within* one engine; across
  engines, DP is scheduler-level replica routing, as in the reference);
- ``tensor`` — attention heads + MLP intermediate (TP; north star TP=8 on
  v5e-8 ICI);
- ``expert`` — MoE experts (EP; Mixtral on v5e-16);
- ``stage``  — pipeline stages (PP; 70B TP×PP on v5p-64);
- ``seq``    — sequence/context parallelism (ring-attention prefill).

Meshes are built over whatever devices exist — the single real TPU chip, a
multi-chip slice, or virtual CPU devices
(``--xla_force_host_platform_device_count``) for tests (SURVEY.md §4.3).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

AXES = ("data", "tensor", "expert", "stage", "seq")


@dataclass(frozen=True)
class MeshSpec:
    """Sizes per mesh axis; 1 = axis unused. ``data=0`` means "absorb all
    remaining devices" (exactly one axis may be 0)."""

    data: int = 1
    tensor: int = 1
    expert: int = 1
    stage: int = 1
    seq: int = 1

    def sizes(self) -> Tuple[int, ...]:
        return (self.data, self.tensor, self.expert, self.stage, self.seq)

    def resolve(self, n_devices: int) -> "MeshSpec":
        """Fill a single 0 axis with the remaining device count."""
        sizes = list(self.sizes())
        zeros = [i for i, s in enumerate(sizes) if s == 0]
        if len(zeros) > 1:
            raise ValueError("at most one mesh axis may be 0 (auto)")
        fixed = math.prod(s for s in sizes if s > 0)
        if zeros:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes {fixed}"
                )
            sizes[zeros[0]] = n_devices // fixed
            return MeshSpec(*sizes)
        if fixed > n_devices:
            raise ValueError(
                f"mesh needs {fixed} devices, only {n_devices} available"
            )
        return self


def make_mesh(
    spec: Optional[MeshSpec] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a Mesh with the canonical axis names. Axes of size 1 are kept
    (GSPMD treats them as replicated), so PartitionSpecs are portable
    across mesh shapes."""
    devices = list(devices if devices is not None else jax.devices())
    spec = (spec or MeshSpec()).resolve(len(devices))
    n = math.prod(spec.sizes())
    grid = np.array(devices[:n]).reshape(spec.sizes())
    return Mesh(grid, axis_names=AXES)


def tp_mesh(
    tensor: int, devices: Optional[Sequence[jax.Device]] = None
) -> Mesh:
    """Tensor-parallel-only mesh (the engine's intra-replica layout)."""
    return make_mesh(MeshSpec(tensor=tensor), devices)


def sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(*spec))


def largest_tp(n_devices: int, num_kv_heads: int) -> int:
    """Largest tensor-axis size that divides both the device count and the
    KV-head count (KV heads are the binding constraint for GQA TP)."""
    return math.gcd(n_devices, num_kv_heads)
