"""Windowed admission batcher.

Preserves the reference's spec'd ``RequestBatcher`` semantics
(``design.md:227-267`` [spec]; behavior ``requirements.md:45-49``) at the
*admission* boundary of the continuous-batching engine (SURVEY.md §7.1):

- dispatch when the batching window expires (default 50 ms) **or** the batch
  reaches ``max_batch_size`` (default 32), whichever first (Properties 4-5);
- strict priority inclusion via ``PriorityQueueManager.dequeue_batch``;
- per-batch stats (size, mean sequence length, padding overhead had the
  batch been padded to max — the reference pads, we don't, but the metric
  keeps parity with ``requirements.md:49``).

Downstream, batches go to the scheduler → engine runner, where requests
join the continuous decode pool individually; the batch is an admission
unit, not an execution shape. Execution-shape batching lives in the
engine: prefill chunks share bucketed programs, and under
``engine.mixed_step_tokens`` the engine composes RAGGED mixed batches —
decode rows plus exact-length prefill chunks packed into one
token-budgeted dispatch (engine/engine.py ``_mixed_step``) — so nothing
here pads or shapes; admission stays window/size-bounded only.

Deterministic for tests: ``poll(now)`` takes an explicit clock.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Generic, List, Optional, TypeVar

from distributed_inference_server_tpu.core.queue import (
    PriorityQueueManager,
    QueuedRequest,
)
from distributed_inference_server_tpu.core.types import BatchId, new_batch_id

T = TypeVar("T")


@dataclass(frozen=True)
class BatcherConfig:
    """Reference defaults: 50 ms window, 32 max (requirements.md:45-46)."""

    window_ms: float = 50.0
    max_batch_size: int = 32


@dataclass
class AdmissionBatch(Generic[T]):
    """One dispatched admission batch (reference ``InferenceBatch``,
    design.md:241-248 [spec], minus the padded tensors — the engine is
    paged, so no pad-to-max happens here)."""

    batch_id: BatchId
    requests: List[QueuedRequest[T]]
    created_at: float

    def __len__(self) -> int:
        return len(self.requests)


class AdmissionBatcher(Generic[T]):
    """Collects queued requests into window/size-bounded batches."""

    def __init__(
        self,
        queue: PriorityQueueManager[T],
        config: Optional[BatcherConfig] = None,
    ):
        self.queue = queue
        self.config = config or BatcherConfig()
        # written only by the degradation controller (serving/degradation.py):
        # effective cap = max_batch_size // size_divisor. Keeping the divisor
        # separate from config means hot-reloaded config changes and
        # degradation throttling compose instead of overwriting each other.
        self.size_divisor = 1
        self._pending: List[QueuedRequest[T]] = []
        self._window_opened: Optional[float] = None
        # poll/flush run on the dispatch thread; cancel() arrives from the
        # event loop on client disconnect
        self._lock = threading.Lock()

    def pending_count(self) -> int:
        return len(self._pending)

    def cancel(self, request_id) -> Optional[QueuedRequest[T]]:
        """Remove a request still waiting in the batching window (client
        disconnected between dequeue and dispatch, Req 5.4)."""
        with self._lock:
            for i, req in enumerate(self._pending):
                if req.id == request_id:
                    removed = self._pending.pop(i)
                    if not self._pending:
                        self._window_opened = None
                    return removed
        return None

    def effective_max_batch(self) -> int:
        return max(1, self.config.max_batch_size // max(1, self.size_divisor))

    def poll(self, now: Optional[float] = None) -> Optional[AdmissionBatch[T]]:
        """Pull from the queue; return a batch if the size cap is reached or
        the window has expired with at least one request (Property 4: every
        batch has 1 <= len <= max_batch_size; Property 5: a request waits at
        most one window before dispatch while capacity allows)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            cap = self.effective_max_batch()
            room = cap - len(self._pending)
            if room > 0:
                pulled = self.queue.dequeue_batch(room)
                if pulled and self._window_opened is None:
                    self._window_opened = now
                self._pending.extend(pulled)

            if not self._pending:
                return None
            window_expired = (
                self._window_opened is not None
                and (now - self._window_opened) * 1000.0 >= self.config.window_ms
            )
            if len(self._pending) >= cap or window_expired:
                batch = AdmissionBatch(
                    batch_id=new_batch_id(),
                    requests=self._pending,
                    created_at=now,
                )
                self._pending = []
                self._window_opened = None
                return batch
            return None

    def flush(self, now: Optional[float] = None) -> Optional[AdmissionBatch[T]]:
        """Dispatch whatever is pending immediately (shutdown drain)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            if not self._pending:
                return None
            batch = AdmissionBatch(new_batch_id(), self._pending, now)
            self._pending = []
            self._window_opened = None
            return batch
