"""HTTP transport: aiohttp application over the inference handler.

Realizes the reference's spec'd ``ApiServer`` (``design.md:139-145`` [spec];
endpoints ``requirements.md:32-38,118-119``):

- POST ``/generate`` ``/chat`` — JSON, or SSE when ``stream: true``
  (Req 1.6); client disconnect mid-stream aborts generation (Req 5.4);
- POST ``/embeddings``;
- GET ``/server/stats`` — ``MetricsSnapshot`` JSON;
- GET ``/metrics`` — Prometheus text;
- GET ``/health`` — liveness + per-engine health;
- errors → ``ErrorResponse`` JSON with the reference's status mapping
  (400/503/408/500, error.rs:39-56 semantics via core.errors.ApiError).

The axum/tower stack maps to aiohttp; SSE framing is hand-rolled (the wire
format is just ``data: {json}\\n\\n`` frames, streamer.sse_encode).
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import Optional

from aiohttp import web

from distributed_inference_server_tpu.core.errors import ApiError
from distributed_inference_server_tpu.core.models import ErrorResponse
from distributed_inference_server_tpu.serving.handler import InferenceHandler
from distributed_inference_server_tpu.serving.metrics import MetricsCollector
from distributed_inference_server_tpu.serving.streamer import SSE_DONE, sse_encode


def _error_response(err: ApiError) -> web.Response:
    body = ErrorResponse.of(str(err), err.error_type(), err.code())
    return web.json_response(
        body.to_dict(), status=err.status_code(), dumps=json.dumps
    )


def build_app(
    handler: InferenceHandler,
    metrics: Optional[MetricsCollector] = None,
    swap_fn=None,
    scale_fn=None,
) -> web.Application:
    """``swap_fn(model_name) -> (ok, error)`` enables the admin model-swap
    endpoint (Req 13.1: admin-API-triggered); ``scale_fn(n) -> (ok,
    error)`` enables the admin replica-scaling endpoint (runtime scale
    up/down, requirements.md:110). Both are blocking — they run in the
    default executor."""
    app = web.Application()
    app["handler"] = handler
    app["metrics"] = metrics

    @web.middleware
    async def observe(request: web.Request, handler):  # noqa: A002 — aiohttp
        # requires the parameter name "handler" (shadows the InferenceHandler)
        t0 = time.monotonic()
        code = 500
        try:
            resp = await handler(request)
            code = resp.status
            return resp
        except ApiError as e:
            resp = _error_response(e)
            code = resp.status
            return resp
        finally:
            if metrics and request.method == "POST":
                metrics.record_request(request.path, code, time.monotonic() - t0)

    app.middlewares.append(observe)

    class ApiErrorJson(ApiError):
        def __init__(self, msg: str):
            super().__init__(f"Validation error: {msg}")

        def status_code(self) -> int:
            return 400

        def error_type(self) -> str:
            return "invalid_request_error"

        def code(self) -> str:
            return "invalid_json"

    async def _json_body(request: web.Request) -> dict:
        try:
            obj = await request.json()
        except Exception:  # noqa: BLE001 — malformed body
            raise ApiErrorJson("request body is not valid JSON") from None
        if not isinstance(obj, dict):
            raise ApiErrorJson("request body must be a JSON object")
        return obj

    async def _stream_response(request: web.Request, request_id, events,
                               encode=sse_encode):
        """One SSE scaffold for every stream (native TokenEvent frames
        and the /v1 OpenAI-chunk encoding differ only in ``encode``) —
        the Req 5.4 abort-on-disconnect logic exists exactly once."""
        resp = web.StreamResponse(
            status=200,
            headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
                "Connection": "keep-alive",
            },
        )
        await resp.prepare(request)
        try:
            async for event in events:
                await resp.write(encode(event))
            await resp.write(SSE_DONE)
        except (ConnectionResetError, asyncio.CancelledError):
            # client went away: abort generation (Req 5.4)
            handler.dispatcher.abort(request_id)
            raise
        await resp.write_eof()
        return resp

    async def _serve_completion(request, *, chat: bool, v1: bool):
        """Shared stream-or-JSON dispatch for /generate, /chat and their
        /v1 aliases — one copy of the negotiation, with the OpenAI field
        translation and wire mapping applied only on the v1 paths."""
        obj = await _json_body(request)
        if v1:
            obj = _openai_fields(obj)
        stream_fn = handler.chat_stream if chat else handler.generate_stream
        call_fn = handler.chat if chat else handler.generate
        if obj.get("stream") is True:
            request_id, events = await stream_fn(obj)
            if v1:
                return await _stream_response_v1(
                    request, request_id, events, chat=chat
                )
            return await _stream_response(request, request_id, events)
        result = await call_fn(obj)
        d = result.to_dict()
        if v1:
            d = _v1_finish_reasons(d)
        return web.json_response(d)

    async def generate(request: web.Request) -> web.StreamResponse:
        return await _serve_completion(request, chat=False, v1=False)

    async def chat(request: web.Request) -> web.StreamResponse:
        return await _serve_completion(request, chat=True, v1=False)

    async def embeddings(request: web.Request) -> web.Response:
        obj = await _json_body(request)
        result = await handler.embeddings(obj)
        return web.json_response(result.to_dict())

    # -- OpenAI-compatible aliases -----------------------------------------
    # The non-stream response envelopes already follow the OpenAI shapes
    # (Req 11). The /v1/* aliases close the remaining wire gaps so
    # off-the-shelf OpenAI clients work: the "stop" request field,
    # finish_reason vocabulary ("stop_sequence" is not OpenAI's), and
    # streaming as text_completion / chat.completion.chunk objects with
    # choices[].text / choices[].delta instead of internal TokenEvents.

    def _openai_fields(obj: dict) -> dict:
        # _json_body already 400s on non-dict bodies
        n = obj.get("n")
        if n is not None and (type(n) is not int or n != 1):
            # a silent single choice where the client asked for n would
            # be a wrong response shape, not a degraded one (and bool is
            # not an int here: n=true must not pass as 1)
            raise ApiErrorJson('"n" must be 1 (multiple choices are not '
                               "supported)")
        # the SDKs' recommended replacement for the deprecated max_tokens
        if "max_completion_tokens" in obj and "max_tokens" not in obj:
            obj["max_tokens"] = obj.pop("max_completion_tokens")
        if "stop" in obj and "stop_sequences" not in obj:
            stop = obj.pop("stop")
            if stop is None:
                stop = []
            elif isinstance(stop, str):
                stop = [stop]
            if not (isinstance(stop, list)
                    and all(isinstance(s, str) for s in stop)):
                # name the field the CLIENT sent, not our internal one
                raise ApiErrorJson('"stop" must be a string or an array '
                                   "of strings")
            if any(s == "" for s in stop):
                # OpenAI rejects empty stop strings; ours would match at
                # position 0 and instantly truncate to an empty output
                raise ApiErrorJson('"stop" strings must be non-empty')
            obj["stop_sequences"] = stop
        return obj

    def _v1_finish_reasons(d: dict) -> dict:
        for c in d.get("choices", ()):
            if c.get("finish_reason") == "stop_sequence":
                c["finish_reason"] = "stop"
        return d

    async def _stream_response_v1(request, request_id, events, *,
                                  chat: bool):
        obj_name = "chat.completion.chunk" if chat else "text_completion"
        rid = ("chatcmpl-" if chat else "cmpl-") + str(request_id)
        created = int(time.time())
        model = handler.model_name

        def frame(payload: dict) -> bytes:
            return b"data: " + json.dumps(payload).encode() + b"\n\n"

        first = [True]  # OpenAI wire: role appears only in the 1st delta

        def chunk(ev: dict) -> bytes:
            t = ev.get("type")
            if t == "token":
                if chat:
                    delta = {"content": ev.get("token") or ""}
                    if first[0]:
                        delta = {"role": "assistant", **delta}
                        first[0] = False
                    choice = {"index": 0, "delta": delta,
                              "finish_reason": None}
                else:
                    choice = {"text": ev.get("token") or "", "index": 0,
                              "logprobs": None, "finish_reason": None}
            elif t == "done":
                fr = ev.get("finish_reason")
                fr = "stop" if fr == "stop_sequence" else fr
                choice = (
                    {"index": 0, "delta": {}, "finish_reason": fr}
                    if chat else
                    {"text": "", "index": 0, "logprobs": None,
                     "finish_reason": fr}
                )
            else:  # error: no OpenAI stream-error standard; error object
                return frame({"error": {
                    "message": ev.get("messages") or "",
                    "code": ev.get("code") or "server_error",
                }})
            return frame({"id": rid, "object": obj_name,
                          "created": created, "model": model,
                          "choices": [choice]})

        return await _stream_response(
            request, request_id, events,
            encode=lambda event: chunk(event.to_dict()),
        )

    async def generate_v1(request: web.Request) -> web.StreamResponse:
        return await _serve_completion(request, chat=False, v1=True)

    async def chat_v1(request: web.Request) -> web.StreamResponse:
        return await _serve_completion(request, chat=True, v1=True)

    async def stats(request: web.Request) -> web.Response:
        statuses = tuple(handler.dispatcher.scheduler.statuses())
        if metrics is None:
            return web.json_response(
                {"worker_statuses": [s.to_dict() for s in statuses]}
            )
        return web.json_response(metrics.snapshot(statuses).to_dict())

    async def prom(request: web.Request) -> web.Response:
        if metrics is None:
            return web.Response(status=404, text="metrics disabled")
        return web.Response(
            body=metrics.prometheus_text(),
            content_type="text/plain",
            charset="utf-8",
        )

    async def health(request: web.Request) -> web.Response:
        statuses = handler.dispatcher.scheduler.statuses()
        healthy = any(s.healthy for s in statuses)
        return web.json_response(
            {
                "status": "ok" if healthy else "unhealthy",
                "accepting": handler.dispatcher.is_accepting(),
                "engines": [s.to_dict() for s in statuses],
            },
            status=200 if healthy else 503,
        )

    async def model_swap(request: web.Request) -> web.Response:
        if swap_fn is None:
            return web.json_response(
                {"error": {"message": "model swap not configured",
                           "error_type": "invalid_request_error",
                           "code": "swap_unavailable"}},
                status=501,
            )
        obj = await _json_body(request)
        name = obj.get("model")
        if not isinstance(name, str) or not name:
            return web.json_response(
                {"error": {"message": "body must contain 'model'",
                           "error_type": "invalid_request_error",
                           "code": "invalid_body"}},
                status=400,
            )
        loop = asyncio.get_running_loop()
        ok, err = await loop.run_in_executor(None, swap_fn, name)
        if not ok:
            return web.json_response(
                {"error": {"message": err, "error_type": "server_error",
                           "code": "swap_failed"}},
                status=500,
            )
        return web.json_response({"status": "ok", "model": name})

    async def trace(request: web.Request) -> web.Response:
        tracer = getattr(handler, "tracer", None)
        if tracer is None:
            return web.json_response({"spans": []})
        try:
            n = max(0, int(request.query.get("n", "100")))
        except ValueError:
            return web.json_response(
                {"error": {"message": "query parameter 'n' must be an "
                           "integer", "error_type": "invalid_request_error",
                           "code": "invalid_parameter"}},
                status=400,
            )
        trace_id = request.query.get("trace_id")
        return web.json_response(
            {"spans": [s.to_dict() for s in tracer.recent(n, trace_id)]}
        )

    async def profile(request: web.Request) -> web.Response:
        """Device-trace capture (SURVEY §5 device-tracing bar;
        utils/profiler.py). Body: {"steps": N} traces the next N engine
        steps on one replica (optional "engine_id"), or
        {"duration_ms": M} traces a wall-clock window process-wide.
        Returns the TensorBoard trace directory."""
        obj = await _json_body(request)
        loop = asyncio.get_running_loop()
        if "steps" in obj:
            steps = obj.get("steps")
            if not isinstance(steps, int) or not 1 <= steps <= 1000:
                return web.json_response(
                    {"error": {"message": "'steps' must be an integer "
                               "in [1, 1000]",
                               "error_type": "invalid_request_error",
                               "code": "invalid_body"}},
                    status=400,
                )
            runners = handler.dispatcher.scheduler.engines()
            engine_id = obj.get("engine_id")
            if engine_id is not None:
                runners = [r for r in runners if r.engine_id == engine_id]
            if not runners:
                return web.json_response(
                    {"error": {"message": "no such engine",
                               "error_type": "invalid_request_error",
                               "code": "invalid_body"}},
                    status=400,
                )
            timeout_s = float(obj.get("timeout_s", 30.0))
            result = await loop.run_in_executor(
                None, runners[0].profile_steps, steps, timeout_s
            )
            result.setdefault("engine_id", runners[0].engine_id)
        else:
            ms = obj.get("duration_ms", 500)
            if not isinstance(ms, (int, float)) or not 0 < ms <= 60_000:
                return web.json_response(
                    {"error": {"message": "'duration_ms' must be in "
                               "(0, 60000]",
                               "error_type": "invalid_request_error",
                               "code": "invalid_body"}},
                    status=400,
                )
            from distributed_inference_server_tpu.utils.profiler import (
                capture_duration,
            )

            def _cap():
                try:
                    return capture_duration(ms / 1000.0)
                except Exception as e:  # noqa: BLE001 — capture busy etc.
                    return {"error": str(e)}

            result = await loop.run_in_executor(None, _cap)
        status = 409 if "error" in result else 200
        return web.json_response(result, status=status)

    async def scale(request: web.Request) -> web.Response:
        """Runtime replica scaling (requirements.md:110): body
        {"num_engines": N}; removal drains in-flight work."""
        if scale_fn is None:
            return web.json_response(
                {"error": {"message": "scaling not configured",
                           "error_type": "invalid_request_error",
                           "code": "scale_unavailable"}},
                status=501,
            )
        obj = await _json_body(request)
        n = obj.get("num_engines")
        if not isinstance(n, int) or not 1 <= n <= 64:
            return web.json_response(
                {"error": {"message": "'num_engines' must be an integer "
                           "in [1, 64]",
                           "error_type": "invalid_request_error",
                           "code": "invalid_body"}},
                status=400,
            )
        loop = asyncio.get_running_loop()
        ok, err = await loop.run_in_executor(None, scale_fn, n)
        if not ok:
            return web.json_response(
                {"error": {"message": err, "error_type": "server_error",
                           "code": "scale_failed"}},
                status=500,
            )
        statuses = handler.dispatcher.scheduler.statuses()
        return web.json_response({
            "status": "ok",
            "num_engines": len(statuses),
            "engines": [s.to_dict() for s in statuses],
        })

    async def speculation(request: web.Request) -> web.Response:
        """Speculation control (Req 12.5): {"action": "reset"} clears the
        acceptance trackers fleet-wide — explicit operator signal that
        the request pattern changed (the automatic probation re-enable
        handles the common case)."""
        obj = await _json_body(request)
        if obj.get("action") != "reset":
            return web.json_response(
                {"error": {"message": "'action' must be 'reset'",
                           "error_type": "invalid_request_error",
                           "code": "invalid_body"}},
                status=400,
            )
        runners = handler.dispatcher.scheduler.engines()
        n = 0
        for r in runners:
            if hasattr(r, "reset_speculation"):
                r.reset_speculation()
                n += 1
        return web.json_response({"status": "ok", "engines_reset": n})

    app.router.add_post("/admin/speculation", speculation)
    app.router.add_post("/admin/scale", scale)
    app.router.add_post("/server/profile", profile)
    app.router.add_get("/server/trace", trace)
    app.router.add_post("/admin/model-swap", model_swap)
    app.router.add_post("/generate", generate)
    app.router.add_post("/chat", chat)
    app.router.add_post("/embeddings", embeddings)
    app.router.add_post("/v1/completions", generate_v1)
    app.router.add_post("/v1/chat/completions", chat_v1)
    app.router.add_post("/v1/embeddings", embeddings)
    app.router.add_get("/server/stats", stats)
    app.router.add_get("/metrics", prom)
    app.router.add_get("/health", health)
    return app
