"""HTTP transport: aiohttp application over the inference handler.

Realizes the reference's spec'd ``ApiServer`` (``design.md:139-145`` [spec];
endpoints ``requirements.md:32-38,118-119``):

- POST ``/generate`` ``/chat`` — JSON, or SSE when ``stream: true``
  (Req 1.6); client disconnect mid-stream aborts generation (Req 5.4);
- POST ``/embeddings``;
- GET ``/server/stats`` — ``MetricsSnapshot`` JSON;
- GET ``/metrics`` — Prometheus text;
- GET ``/health`` — liveness + per-engine health;
- errors → ``ErrorResponse`` JSON with the reference's status mapping
  (400/503/408/500, error.rs:39-56 semantics via core.errors.ApiError).

The axum/tower stack maps to aiohttp; SSE framing is hand-rolled (the wire
format is just ``data: {json}\\n\\n`` frames, streamer.sse_encode).
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import Optional

from aiohttp import web

from distributed_inference_server_tpu.core.errors import ApiError
from distributed_inference_server_tpu.core.models import ErrorResponse
from distributed_inference_server_tpu.serving.handler import InferenceHandler
from distributed_inference_server_tpu.serving.metrics import MetricsCollector
from distributed_inference_server_tpu.serving.streamer import SSE_DONE, sse_encode


def _error_response(err: ApiError) -> web.Response:
    body = ErrorResponse.of(str(err), err.error_type(), err.code())
    headers = None
    retry_after = getattr(err, "retry_after_s", None)
    if retry_after is not None:
        # deadline-aware admission shed (serving/health.py): the
        # standard backoff hint rides the 503 so well-behaved clients
        # retry after the backlog drains instead of hammering it
        headers = {"Retry-After": str(int(max(1, round(retry_after))))}
    return web.json_response(
        body.to_dict(), status=err.status_code(), dumps=json.dumps,
        headers=headers,
    )


def build_app(
    handler: InferenceHandler,
    metrics: Optional[MetricsCollector] = None,
    swap_fn=None,
    scale_fn=None,
    fleet_fn=None,
    perf_fn=None,
    health_fn=None,
) -> web.Application:
    """``swap_fn(model_name) -> (ok, error)`` enables the admin model-swap
    endpoint (Req 13.1: admin-API-triggered); ``scale_fn(n) -> (ok,
    error)`` enables the admin replica-scaling endpoint (runtime scale
    up/down, requirements.md:110). Both are blocking — they run in the
    default executor. ``fleet_fn() -> dict`` adds the fleet control-plane
    block (members, role map, rebalance history; serving/fleet.py) to
    ``/server/stats``. ``perf_fn() -> dict`` serves ``GET /server/perf``
    (per-engine step clock, windowed percentiles, SLO burn, and the
    fleet-merged digest view; docs/OBSERVABILITY.md). ``health_fn() ->
    dict`` adds the gray-failure ``health`` block (per-engine scored
    state, breaker states, retry budget, admission estimator;
    serving/health.py) to ``/server/stats``."""
    app = web.Application()
    app["handler"] = handler
    app["metrics"] = metrics

    @web.middleware
    async def observe(request: web.Request, handler):  # noqa: A002 — aiohttp
        # requires the parameter name "handler" (shadows the InferenceHandler)
        t0 = time.monotonic()
        code = 500
        try:
            resp = await handler(request)
            code = resp.status
            return resp
        except ApiError as e:
            resp = _error_response(e)
            code = resp.status
            return resp
        finally:
            if metrics and request.method == "POST":
                metrics.record_request(request.path, code, time.monotonic() - t0)

    app.middlewares.append(observe)

    class ApiErrorJson(ApiError):
        def __init__(self, msg: str):
            super().__init__(f"Validation error: {msg}")

        def status_code(self) -> int:
            return 400

        def error_type(self) -> str:
            return "invalid_request_error"

        def code(self) -> str:
            return "invalid_json"

    async def _json_body(request: web.Request) -> dict:
        try:
            obj = await request.json()
        except Exception:  # noqa: BLE001 — malformed body
            raise ApiErrorJson("request body is not valid JSON") from None
        if not isinstance(obj, dict):
            raise ApiErrorJson("request body must be a JSON object")
        return obj

    async def _stream_response(request: web.Request, request_id, events,
                               encode=sse_encode):
        """One SSE scaffold for every stream (native TokenEvent frames
        and the /v1 OpenAI-chunk encoding differ only in ``encode``) —
        the Req 5.4 abort-on-disconnect logic exists exactly once.
        ``request_id`` may be a single id or the list of fanned-out ids
        (/v1 with n > 1): every live sequence is aborted on disconnect."""
        resp = web.StreamResponse(
            status=200,
            headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
                "Connection": "keep-alive",
            },
        )
        consuming = False
        try:
            await resp.prepare(request)
            consuming = True  # past here the generator is entered, and a
            # cancellation lands inside its frame — its finally then owns
            # the per-request metrics/span bookkeeping
            async for event in events:
                await resp.write(encode(event))
            await resp.write(SSE_DONE)
        except (ConnectionResetError, asyncio.CancelledError):
            # client went away: abort generation (Req 5.4)
            rids = (request_id if isinstance(request_id, (list, tuple))
                    else (request_id,))
            if consuming:
                for rid in rids:
                    handler.dispatcher.abort(rid)
            else:
                # disconnect during prepare: the generator never started,
                # its finally will never run, and abort drops requests
                # with no sink callback — so do the abort AND the
                # bookkeeping the stream's finally would have done
                handler.release_unstarted(rids)
            raise
        await resp.write_eof()
        return resp

    async def _serve_completion(request, *, chat: bool, v1: bool):
        """Shared stream-or-JSON dispatch for /generate, /chat and their
        /v1 aliases — one copy of the negotiation, with the OpenAI field
        translation and wire mapping applied only on the v1 paths."""
        obj = await _json_body(request)
        if v1:
            obj, opts = _openai_fields(obj, chat=chat)
            if obj.get("stream") is True:
                rids, events = await handler.stream_many(
                    obj, chat=chat, n=opts.n
                )
                return await _stream_response_v1(
                    request, rids, events, chat=chat, opts=opts
                )
            rid, choices, usage = await handler.complete_many(
                obj, chat=chat, n=opts.n
            )
            return web.json_response(
                _v1_response(rid, choices, usage, chat=chat, opts=opts)
            )
        stream_fn = handler.chat_stream if chat else handler.generate_stream
        call_fn = handler.chat if chat else handler.generate
        if obj.get("stream") is True:
            request_id, events = await stream_fn(obj)
            return await _stream_response(request, request_id, events)
        result = await call_fn(obj)
        return web.json_response(result.to_dict())

    async def generate(request: web.Request) -> web.StreamResponse:
        return await _serve_completion(request, chat=False, v1=False)

    async def chat(request: web.Request) -> web.StreamResponse:
        return await _serve_completion(request, chat=True, v1=False)

    async def embeddings(request: web.Request) -> web.Response:
        obj = await _json_body(request)
        result = await handler.embeddings(obj)
        return web.json_response(result.to_dict())

    # -- OpenAI-compatible aliases -----------------------------------------
    # The non-stream response envelopes already follow the OpenAI shapes
    # (Req 11). The /v1/* aliases close the remaining wire gaps so
    # off-the-shelf OpenAI clients work: the "stop" request field,
    # finish_reason vocabulary ("stop_sequence" is not OpenAI's), and
    # streaming as text_completion / chat.completion.chunk objects with
    # choices[].text / choices[].delta instead of internal TokenEvents.

    class _V1Opts:
        """Parsed OpenAI-only request options (everything the native
        schema doesn't carry)."""

        __slots__ = ("n", "include_usage", "logprobs")

        def __init__(self, n=1, include_usage=False, logprobs=False):
            self.n = n
            self.include_usage = include_usage
            self.logprobs = logprobs

    # fan-out bound: each choice is a full engine sequence admitted
    # through the same queue, so one request must not be able to claim
    # an unbounded slice of capacity (OpenAI itself caps n at 128)
    _MAX_N = 16

    def _openai_fields(obj: dict, *, chat: bool):
        """Translate/validate the OpenAI request spellings. Returns
        ``(obj, _V1Opts)``. Shape-changing fields we do not implement
        (echo, best_of>n, top-alternative logprobs, suffix) are rejected
        with a clear 400 — a silently wrong response shape is worse than
        an honest error."""
        # _json_body already 400s on non-dict bodies
        n = obj.get("n")
        if n is None:
            n = 1
        elif type(n) is not int or not 1 <= n <= _MAX_N:
            # bool is not an int here: n=true must not pass as 1
            raise ApiErrorJson(
                f'"n" must be an integer in [1, {_MAX_N}]'
            )
        opts = _V1Opts(n=n)

        so = obj.get("stream_options")
        if so is not None:
            if obj.get("stream") is not True:
                raise ApiErrorJson(
                    '"stream_options" requires "stream": true'
                )
            if not isinstance(so, dict):
                raise ApiErrorJson('"stream_options" must be an object')
            iu = so.get("include_usage", False)
            if not isinstance(iu, bool):
                raise ApiErrorJson(
                    '"stream_options.include_usage" must be a boolean'
                )
            opts.include_usage = iu

        lp = obj.get("logprobs")
        if chat:
            if lp is not None and not isinstance(lp, bool):
                raise ApiErrorJson('"logprobs" must be a boolean')
            opts.logprobs = bool(lp)
            tlp = obj.get("top_logprobs")
            if tlp is not None:
                if type(tlp) is not int or not 0 <= tlp <= 20:
                    raise ApiErrorJson(
                        '"top_logprobs" must be an integer in [0, 20]'
                    )
                if not opts.logprobs:
                    raise ApiErrorJson(
                        '"logprobs" must be true when "top_logprobs" '
                        "is used"
                    )
                if tlp > 0:
                    raise ApiErrorJson(
                        '"top_logprobs" > 0 (alternative-token logprobs) '
                        "is not supported; use 0 for sampled-token "
                        "logprobs"
                    )
        else:
            # completions spelling: logprobs is an int — the number of
            # TOP-ALTERNATIVE tokens to return per position. 0 = just the
            # sampled token's logprob (supported); >0 needs per-step
            # top-k alternatives we don't surface.
            if lp is not None:
                if type(lp) is not int or lp < 0:
                    raise ApiErrorJson(
                        '"logprobs" must be a non-negative integer'
                    )
                if lp > 0:
                    raise ApiErrorJson(
                        '"logprobs" > 0 (alternative-token logprobs) is '
                        "not supported; use 0 for sampled-token logprobs"
                    )
                opts.logprobs = True
            if obj.get("echo"):
                raise ApiErrorJson(
                    '"echo" is not supported (the response would have to '
                    "prepend the prompt)"
                )
            if obj.get("suffix") is not None:
                raise ApiErrorJson('"suffix" is not supported')
            bo = obj.get("best_of")
            if bo is not None and (type(bo) is not int or bo != n):
                # best_of == n degenerates to "return all n"; more means
                # server-side reranking we don't do, fewer than n is
                # self-contradictory (OpenAI 400s best_of < n too)
                raise ApiErrorJson(
                    f'"best_of" must equal n (= {n}); server-side '
                    "candidate reranking is not supported"
                )

        # the SDKs' recommended replacement for the deprecated max_tokens
        if "max_completion_tokens" in obj and "max_tokens" not in obj:
            obj["max_tokens"] = obj.pop("max_completion_tokens")
        if "stop" in obj and "stop_sequences" not in obj:
            stop = obj.pop("stop")
            if stop is None:
                stop = []
            elif isinstance(stop, str):
                stop = [stop]
            if not (isinstance(stop, list)
                    and all(isinstance(s, str) for s in stop)):
                # name the field the CLIENT sent, not our internal one
                raise ApiErrorJson('"stop" must be a string or an array '
                                   "of strings")
            if any(s == "" for s in stop):
                # OpenAI rejects empty stop strings; ours would match at
                # position 0 and instantly truncate to an empty output
                raise ApiErrorJson('"stop" strings must be non-empty')
            obj["stop_sequences"] = stop
        return obj, opts

    def _v1_finish(reason) -> Optional[str]:
        fr = getattr(reason, "value", reason)
        return "stop" if fr == "stop_sequence" else fr

    def _lp_completions(token_texts, logprobs) -> dict:
        """OpenAI completions logprobs object (sampled token only).
        text_offset is the cumulative character offset of each token's
        isolated decode within the generated text; tokens held back by
        incremental detok decode to U+FFFD fragments in isolation, same
        as OpenAI's own byte-fragment rendering."""
        offsets, pos = [], 0
        for t in token_texts:
            offsets.append(pos)
            pos += len(t)
        return {
            "tokens": token_texts,
            "token_logprobs": logprobs,
            "top_logprobs": None,
            "text_offset": offsets,
        }

    def _lp_chat(token_texts, logprobs) -> dict:
        """OpenAI chat logprobs object: content[] of per-token entries.
        top_logprobs is always [] — alternative-token logprobs are
        rejected at request parse (top_logprobs > 0). Entries without a
        logprob are dropped rather than emitted with null: the OpenAI
        schema requires a float (a held-back-text flush carries no
        logprob of its own — its tokens' logprobs already streamed)."""
        return {
            "content": [
                {
                    "token": t,
                    "logprob": lp,
                    "bytes": list(t.encode("utf-8")),
                    "top_logprobs": [],
                }
                for t, lp in zip(token_texts, logprobs)
                if lp is not None
            ]
        }

    def _v1_response(request_id, choices, usage, *, chat: bool,
                     opts) -> dict:
        """Non-streaming OpenAI response envelope from the handler's
        fan-out results (one entry per choice, indices 0..n-1)."""
        out = []
        for i, c in enumerate(choices):
            lp_obj = None
            if opts.logprobs:
                texts = [handler.tok.decode_token(t)
                         for t in c["token_ids"]]
                lp_obj = (
                    _lp_chat(texts, c["token_logprobs"]) if chat
                    else _lp_completions(texts, c["token_logprobs"])
                )
            if chat:
                out.append({
                    "index": i,
                    "message": {"role": "assistant",
                                "content": c["text"]},
                    "logprobs": lp_obj,
                    "finish_reason": _v1_finish(c["finish_reason"]),
                })
            else:
                out.append({
                    "text": c["text"],
                    "index": i,
                    "logprobs": lp_obj,
                    "finish_reason": _v1_finish(c["finish_reason"]),
                })
        return {
            "id": ("chatcmpl-" if chat else "cmpl-") + str(request_id),
            "object": "chat.completion" if chat else "text_completion",
            "created": int(time.time()),
            "model": handler.model_name,
            "choices": out,
            "usage": usage.to_dict(),
        }

    async def _stream_response_v1(request, request_ids, events, *,
                                  chat: bool, opts):
        """OpenAI chunk encoding over the merged (choice_index, event)
        stream. Per-choice state: the role appears only in a choice's
        first delta; each choice gets its own finish chunk. With
        stream_options.include_usage every chunk carries "usage": null
        and one final usage-only chunk (empty choices) precedes [DONE]."""
        obj_name = "chat.completion.chunk" if chat else "text_completion"
        rid = ("chatcmpl-" if chat else "cmpl-") + str(request_ids[0])
        created = int(time.time())
        model = handler.model_name
        n = len(request_ids)

        def frame(payload: dict) -> bytes:
            if opts.include_usage and "usage" not in payload:
                payload["usage"] = None
            return b"data: " + json.dumps(payload).encode() + b"\n\n"

        def envelope(choice: dict, usage=None) -> bytes:
            payload = {"id": rid, "object": obj_name, "created": created,
                       "model": model, "choices": [choice]}
            if usage is not None:
                payload["usage"] = usage
            return frame(payload)

        first = [True] * n  # role only in each choice's 1st delta
        offset = [0] * n  # per-choice char offset for completions logprobs
        observed = [0] * n  # sampled tokens seen per choice (usage
        # fallback for choices that error mid-generation: their done
        # event — the authoritative usage carrier — never arrives)
        prompt_tokens = [0]
        completion_tokens = [0]
        remaining = [n]

        def chunk(pair) -> bytes:
            idx, ev = pair
            if ev.type == "token":
                text = ev.token or ""
                if ev.logprob is not None:
                    # real sampled token (flushes carry no logprob)
                    observed[idx] += 1
                lp_obj = None
                if opts.logprobs:
                    # a held-back-text flush (no logprob of its own) gets
                    # a null logprobs object, matching the non-stream
                    # path which records sampled tokens only; its text
                    # still advances the completions offset so offsets
                    # keep matching the emitted text
                    if chat:
                        lp_obj = (
                            _lp_chat([text], [ev.logprob])
                            if ev.logprob is not None else None
                        )
                    else:
                        if ev.logprob is not None:
                            lp_obj = _lp_completions([text], [ev.logprob])
                            lp_obj["text_offset"] = [offset[idx]]
                        offset[idx] += len(text)
                if chat:
                    delta = {"content": text}
                    if first[idx]:
                        delta = {"role": "assistant", **delta}
                        first[idx] = False
                    choice = {"index": idx, "delta": delta,
                              "logprobs": lp_obj, "finish_reason": None}
                else:
                    choice = {"text": text, "index": idx,
                              "logprobs": lp_obj, "finish_reason": None}
                return envelope(choice)
            if ev.type == "done":
                fr = _v1_finish(ev.finish_reason)
                if ev.usage is not None:
                    prompt_tokens[0] = max(prompt_tokens[0],
                                           ev.usage.prompt_tokens)
                    completion_tokens[0] += ev.usage.completion_tokens
                choice = (
                    {"index": idx, "delta": {}, "logprobs": None,
                     "finish_reason": fr}
                    if chat else
                    {"text": "", "index": idx, "logprobs": None,
                     "finish_reason": fr}
                )
                return envelope(choice) + _maybe_usage_chunk()
            # error: no OpenAI stream-error standard; error object with
            # the choice index so n>1 clients can attribute it (the
            # stream keeps going for the surviving choices). An error
            # TERMINATES its choice (the sink closes after it), so it
            # counts toward stream completion like a done event —
            # otherwise the include_usage final chunk would never fire
            # when any choice errors.
            completion_tokens[0] += observed[idx]
            return frame({"error": {
                "message": ev.messages or "",
                "code": ev.code or "server_error",
                "index": idx,
            }}) + _maybe_usage_chunk()

        def _maybe_usage_chunk() -> bytes:
            """Decrement the live-choice count; on the LAST terminal
            event (done or error), emit the usage-only final chunk when
            stream_options.include_usage asked for it (OpenAI: empty
            choices array, preceding [DONE])."""
            remaining[0] -= 1
            if remaining[0] != 0 or not opts.include_usage:
                return b""
            total = prompt_tokens[0] + completion_tokens[0]
            return frame({
                "id": rid, "object": obj_name,
                "created": created, "model": model,
                "choices": [],
                "usage": {
                    "prompt_tokens": prompt_tokens[0],
                    "completion_tokens": completion_tokens[0],
                    "total_tokens": total,
                },
            })

        return await _stream_response(
            request, request_ids, events, encode=chunk
        )

    async def generate_v1(request: web.Request) -> web.StreamResponse:
        return await _serve_completion(request, chat=False, v1=True)

    async def chat_v1(request: web.Request) -> web.StreamResponse:
        return await _serve_completion(request, chat=True, v1=True)

    async def stats(request: web.Request) -> web.Response:
        statuses = tuple(handler.dispatcher.scheduler.statuses())
        if metrics is None:
            out = {"worker_statuses": [s.to_dict() for s in statuses]}
        else:
            out = metrics.snapshot(statuses).to_dict()
        if fleet_fn is not None:
            out["fleet"] = fleet_fn()
        if health_fn is not None:
            # gray-failure block (serving/health.py): scored per-engine
            # states, data-channel breaker states, the shared retry
            # budget, and the admission estimator
            out["health"] = health_fn()
        recorder = getattr(handler, "recorder", None)
        tracer = getattr(handler, "tracer", None)
        if recorder is not None or tracer is not None:
            blk = out.setdefault("tracing", {})
            if tracer is not None:
                # the tracer's own view (includes drops before metrics
                # wiring); the metrics mirror is spans_dropped above
                blk["tracer_dropped"] = tracer.dropped()
            if recorder is not None:
                blk["flight_recorder"] = recorder.stats()
        return web.json_response(out)

    async def prom(request: web.Request) -> web.Response:
        if metrics is None:
            return web.Response(status=404, text="metrics disabled")
        return web.Response(
            body=metrics.prometheus_text(),
            content_type="text/plain",
            charset="utf-8",
        )

    async def health(request: web.Request) -> web.Response:
        statuses = handler.dispatcher.scheduler.statuses()
        healthy = any(s.healthy for s in statuses)
        return web.json_response(
            {
                "status": "ok" if healthy else "unhealthy",
                "accepting": handler.dispatcher.is_accepting(),
                "engines": [s.to_dict() for s in statuses],
            },
            status=200 if healthy else 503,
        )

    async def model_swap(request: web.Request) -> web.Response:
        if swap_fn is None:
            return web.json_response(
                {"error": {"message": "model swap not configured",
                           "error_type": "invalid_request_error",
                           "code": "swap_unavailable"}},
                status=501,
            )
        obj = await _json_body(request)
        name = obj.get("model")
        if not isinstance(name, str) or not name:
            return web.json_response(
                {"error": {"message": "body must contain 'model'",
                           "error_type": "invalid_request_error",
                           "code": "invalid_body"}},
                status=400,
            )
        loop = asyncio.get_running_loop()
        ok, err = await loop.run_in_executor(None, swap_fn, name)
        if not ok:
            return web.json_response(
                {"error": {"message": err, "error_type": "server_error",
                           "code": "swap_failed"}},
                status=500,
            )
        return web.json_response({"status": "ok", "model": name})

    _TRACE_N_MAX = 10_000

    async def trace(request: web.Request) -> web.Response:
        """Finished spans from the in-memory ring, sorted by start time.
        Filters: ``trace_id=`` (one stitched trace — remote members'
        spans included once their FleetSpans frames merged) and
        ``request_id=`` (every span carrying that request_id
        attribute). ``n`` is validated: an integer in [1, 10000]."""
        tracer = getattr(handler, "tracer", None)
        if tracer is None:
            return web.json_response({"spans": []})
        try:
            n = int(request.query.get("n", "100"))
            if not 1 <= n <= _TRACE_N_MAX:
                raise ValueError
        except ValueError:
            return web.json_response(
                {"error": {"message": "query parameter 'n' must be an "
                           f"integer in [1, {_TRACE_N_MAX}]",
                           "error_type": "invalid_request_error",
                           "code": "invalid_parameter"}},
                status=400,
            )
        trace_id = request.query.get("trace_id")
        request_id = request.query.get("request_id")
        spans = tracer.recent(n, trace_id=trace_id, request_id=request_id)
        return web.json_response(
            {"spans": [s.to_dict() for s in spans]}
        )

    async def request_timeline(request: web.Request) -> web.Response:
        """GET /server/requests/<id> — the flight-recorder timeline:
        events, derived phase attribution (phases partition the wall
        clock), and the TTFT/TBT breakdown (docs/OBSERVABILITY.md)."""
        recorder = getattr(handler, "recorder", None)
        if recorder is None:
            return web.json_response(
                {"error": {"message": "flight recorder disabled",
                           "error_type": "invalid_request_error",
                           "code": "recorder_disabled"}},
                status=404,
            )
        tl = recorder.timeline(request.match_info["id"])
        if tl is None:
            return web.json_response(
                {"error": {"message": "no timeline for this request id "
                           "(expired from the bounded recorder, or never "
                           "admitted)",
                           "error_type": "invalid_request_error",
                           "code": "unknown_request"}},
                status=404,
            )
        return web.json_response(tl)

    async def request_list(request: web.Request) -> web.Response:
        recorder = getattr(handler, "recorder", None)
        if recorder is None:
            return web.json_response({"requests": []})
        try:
            n = int(request.query.get("n", "50"))
            if not 1 <= n <= 1000:
                raise ValueError
        except ValueError:
            return web.json_response(
                {"error": {"message": "query parameter 'n' must be an "
                           "integer in [1, 1000]",
                           "error_type": "invalid_request_error",
                           "code": "invalid_parameter"}},
                status=400,
            )
        # SLO triage (docs/OBSERVABILITY.md "Performance telemetry"):
        # ?verdict=violated lists exactly the timelines burning the SLO
        verdict = request.query.get("verdict")
        if verdict is not None and verdict not in ("ok", "violated"):
            return web.json_response(
                {"error": {"message": "query parameter 'verdict' must "
                           "be 'ok' or 'violated'",
                           "error_type": "invalid_request_error",
                           "code": "invalid_parameter"}},
                status=400,
            )
        return web.json_response(
            {"requests": recorder.recent(n, verdict=verdict),
             "stats": recorder.stats()})

    async def perf(request: web.Request) -> web.Response:
        """GET /server/perf — the performance-telemetry surface
        (docs/OBSERVABILITY.md): per-engine step-clock counters,
        windowed TTFT/TBT/queue-wait percentiles, SLO burn, the raw
        mergeable digests, and (registry host) the per-member +
        fleet-merged view."""
        if perf_fn is None:
            return web.json_response(
                {"error": {"message": "performance telemetry not "
                           "configured",
                           "error_type": "invalid_request_error",
                           "code": "perf_unavailable"}},
                status=404,
            )
        return web.json_response(perf_fn())

    async def profile(request: web.Request) -> web.Response:
        """Device-trace capture (SURVEY §5 device-tracing bar;
        utils/profiler.py). Body: {"steps": N} traces the next N engine
        steps on one replica (optional "engine_id"), or
        {"duration_ms": M} traces a wall-clock window process-wide.
        Returns the TensorBoard trace directory."""
        obj = await _json_body(request)
        loop = asyncio.get_running_loop()
        if "steps" in obj:
            steps = obj.get("steps")
            if not isinstance(steps, int) or not 1 <= steps <= 1000:
                return web.json_response(
                    {"error": {"message": "'steps' must be an integer "
                               "in [1, 1000]",
                               "error_type": "invalid_request_error",
                               "code": "invalid_body"}},
                    status=400,
                )
            runners = handler.dispatcher.scheduler.engines()
            engine_id = obj.get("engine_id")
            if engine_id is not None:
                runners = [r for r in runners if r.engine_id == engine_id]
            if not runners:
                return web.json_response(
                    {"error": {"message": "no such engine",
                               "error_type": "invalid_request_error",
                               "code": "invalid_body"}},
                    status=400,
                )
            timeout_s = float(obj.get("timeout_s", 30.0))
            result = await loop.run_in_executor(
                None, runners[0].profile_steps, steps, timeout_s
            )
            result.setdefault("engine_id", runners[0].engine_id)
        else:
            ms = obj.get("duration_ms", 500)
            if not isinstance(ms, (int, float)) or not 0 < ms <= 60_000:
                return web.json_response(
                    {"error": {"message": "'duration_ms' must be in "
                               "(0, 60000]",
                               "error_type": "invalid_request_error",
                               "code": "invalid_body"}},
                    status=400,
                )
            from distributed_inference_server_tpu.utils.profiler import (
                capture_duration,
            )

            def _cap():
                try:
                    return capture_duration(ms / 1000.0)
                except Exception as e:  # noqa: BLE001 — capture busy etc.
                    return {"error": str(e)}

            result = await loop.run_in_executor(None, _cap)
        status = 409 if "error" in result else 200
        return web.json_response(result, status=status)

    async def scale(request: web.Request) -> web.Response:
        """Runtime replica scaling (requirements.md:110): body
        {"num_engines": N}; removal drains in-flight work."""
        if scale_fn is None:
            return web.json_response(
                {"error": {"message": "scaling not configured",
                           "error_type": "invalid_request_error",
                           "code": "scale_unavailable"}},
                status=501,
            )
        obj = await _json_body(request)
        n = obj.get("num_engines")
        if not isinstance(n, int) or not 1 <= n <= 64:
            return web.json_response(
                {"error": {"message": "'num_engines' must be an integer "
                           "in [1, 64]",
                           "error_type": "invalid_request_error",
                           "code": "invalid_body"}},
                status=400,
            )
        loop = asyncio.get_running_loop()
        ok, err = await loop.run_in_executor(None, scale_fn, n)
        if not ok:
            return web.json_response(
                {"error": {"message": err, "error_type": "server_error",
                           "code": "scale_failed"}},
                status=500,
            )
        statuses = handler.dispatcher.scheduler.statuses()
        return web.json_response({
            "status": "ok",
            "num_engines": len(statuses),
            "engines": [s.to_dict() for s in statuses],
        })

    async def speculation(request: web.Request) -> web.Response:
        """Speculation control (Req 12.5): {"action": "reset"} clears the
        acceptance trackers fleet-wide — explicit operator signal that
        the request pattern changed (the automatic probation re-enable
        handles the common case)."""
        obj = await _json_body(request)
        if obj.get("action") != "reset":
            return web.json_response(
                {"error": {"message": "'action' must be 'reset'",
                           "error_type": "invalid_request_error",
                           "code": "invalid_body"}},
                status=400,
            )
        runners = handler.dispatcher.scheduler.engines()
        n = 0
        for r in runners:
            if hasattr(r, "reset_speculation"):
                r.reset_speculation()
                n += 1
        return web.json_response({"status": "ok", "engines_reset": n})

    app.router.add_post("/admin/speculation", speculation)
    app.router.add_post("/admin/scale", scale)
    app.router.add_post("/server/profile", profile)
    app.router.add_get("/server/trace", trace)
    app.router.add_get("/server/perf", perf)
    app.router.add_get("/server/requests", request_list)
    app.router.add_get("/server/requests/{id}", request_timeline)
    app.router.add_post("/admin/model-swap", model_swap)
    app.router.add_post("/generate", generate)
    app.router.add_post("/chat", chat)
    app.router.add_post("/embeddings", embeddings)
    app.router.add_post("/v1/completions", generate_v1)
    app.router.add_post("/v1/chat/completions", chat_v1)
    app.router.add_post("/v1/embeddings", embeddings)
    app.router.add_get("/server/stats", stats)
    app.router.add_get("/metrics", prom)
    app.router.add_get("/health", health)
    return app
