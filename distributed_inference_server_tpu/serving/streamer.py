"""Token streaming: engine-thread → asyncio bridge and SSE encoding.

Realizes the reference's spec'd ``TokenStreamer`` (``design.md:449-458``
[spec]; behavior ``requirements.md:82-86``) on asyncio:

- per-request channel: the engine runner thread pushes ``TokenEvent``s via
  ``loop.call_soon_threadsafe`` into an ``asyncio.Queue`` — the tokio
  ``mpsc`` analogue — so delivery to the HTTP writer happens within the
  next loop tick (≤10 ms budget, requirements.md:82);
- ``Done`` event carries finish_reason + usage, ``Error`` then close
  (``TokenEvent`` wire schema, core/models.py ← models.rs:270-288);
- client disconnect aborts generation upstream (Req 5.4) — the HTTP layer
  calls ``Dispatcher.abort``.

Non-streaming requests use ``CollectingSink``, which accumulates text and
resolves a future.
"""

from __future__ import annotations

import asyncio
import json
import threading
from typing import Optional

from distributed_inference_server_tpu.core.models import (
    FinishReason,
    TokenEvent,
    Usage,
)


def sse_encode(event: TokenEvent) -> bytes:
    """One SSE frame: ``data: {json}\\n\\n`` (Req 1.6)."""
    return f"data: {json.dumps(event.to_dict())}\n\n".encode()


SSE_DONE = b"data: [DONE]\n\n"


class StreamingSink:
    """ResultSink pushing TokenEvents onto an asyncio.Queue (runner thread →
    loop). ``None`` terminates the stream.

    Cross-thread wakeups are coalesced: events buffer on the runner side
    and one ``call_soon_threadsafe`` flush drains them to the queue — the
    engine emits tokens in decode-block bursts, so this is one loop wakeup
    per (request, block) instead of per token, while delivery still lands
    on the next loop tick (the ≤10 ms budget, requirements.md:82)."""

    def __init__(self, loop: asyncio.AbstractEventLoop):
        self._loop = loop
        self.queue: "asyncio.Queue[Optional[TokenEvent]]" = asyncio.Queue()
        self.finish_reason: Optional[FinishReason] = None
        self.usage: Optional[Usage] = None
        self.error: Optional[str] = None
        self._pending: list = []
        self._plock = threading.Lock()

    def _put(self, item: Optional[TokenEvent]) -> None:
        with self._plock:
            self._pending.append(item)
            if len(self._pending) > 1:
                return  # a flush is already scheduled for this burst
        self._loop.call_soon_threadsafe(self._flush)

    def _flush(self) -> None:
        with self._plock:
            items, self._pending = self._pending, []
        for item in items:
            self.queue.put_nowait(item)

    # runner-thread callbacks ------------------------------------------------

    def on_token(self, token_id: Optional[int], text: str,
                 token_index: int, logprob: Optional[float] = None) -> None:
        self._put(TokenEvent.token_event(text, token_index, logprob))

    def on_done(self, finish_reason: FinishReason, usage: Usage) -> None:
        self.finish_reason = finish_reason
        self.usage = usage
        self._put(TokenEvent.done_event(finish_reason, usage))
        self._put(None)

    def on_error(self, message: str, code: str) -> None:
        self.error = message
        self._put(TokenEvent.error_event(message, code))
        self._put(None)

    # loop-side consumption --------------------------------------------------

    async def events(self):
        while True:
            item = await self.queue.get()
            if item is None:
                return
            yield item


class CollectingSink:
    """ResultSink accumulating the full completion for non-streaming
    responses; resolves an asyncio future with
    ``(text, finish_reason, usage)`` or an error tuple.

    Also records the per-token ``(token_id, logprob)`` trail for the /v1
    ``logprobs`` surfaces. Safe to read after the future resolves: the
    runner thread appends strictly before it schedules ``on_done``'s
    resolution onto the loop."""

    def __init__(self, loop: asyncio.AbstractEventLoop):
        self._loop = loop
        self.future: asyncio.Future = loop.create_future()
        self._parts: list = []
        self.token_ids: list = []
        self.token_logprobs: list = []

    def _resolve(self, value) -> None:
        def _set() -> None:
            if not self.future.done():
                self.future.set_result(value)

        self._loop.call_soon_threadsafe(_set)

    # runner-thread callbacks ------------------------------------------------

    def on_token(self, token_id: Optional[int], text: str,
                 token_index: int, logprob: Optional[float] = None) -> None:
        if text:
            self._parts.append(text)
        # one record per REAL sampled token; a held-back-text flush rides
        # with token_id None and no logprob of its own
        if token_id is not None:
            self.token_ids.append(token_id)
            self.token_logprobs.append(logprob)

    def on_done(self, finish_reason: FinishReason, usage: Usage) -> None:
        self._resolve(("".join(self._parts), finish_reason, usage, None, None))

    def on_error(self, message: str, code: str) -> None:
        self._resolve((None, None, None, message, code))
