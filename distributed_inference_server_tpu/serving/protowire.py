"""Hand-rolled protobuf (proto3) wire codec for ``inference.proto``.

The reference spec'd a Tonic (protobuf-binary) gRPC surface
(``design.md:139-155`` [spec]); this image ships grpcio but no protoc
gRPC codegen plugin, so the ~17 message codecs are implemented directly
against the frozen schema in ``serving/inference.proto`` (VERDICT r3
next #5). The length-delimited protobuf wire format needs only three
primitives — varints, fixed32 floats, and length-delimited bytes — and
schema tables keep each message a data entry, not code.

Interface: ``encode(msg, obj) -> bytes`` / ``decode(msg, data) -> dict``
where ``obj``/``dict`` use the SAME canonical JSON-dict schema as the
HTTP endpoints and the JSON-over-gRPC wire (core/models.py ``to_dict``
shapes), including the two documented JSON deviations: TokenEvent is a
tagged union on ``"type"`` and enums are lowercase strings. The gRPC
server auto-detects the wire per request (JSON objects start with
``{``; no message here uses field 15 with group wire type, so the two
encodings are unambiguous) and answers in kind — a protobuf client and
a JSON client see identical payloads, differentially tested.

Decode fills proto3 defaults (0 / "" / false / []) for absent scalar
and repeated fields of RESPONSE messages so reconstructed dicts are
key-for-key identical to the JSON wire; unknown fields are skipped
(forward compatibility), and dict keys outside the schema are ignored
on encode (e.g. EngineStatus's optional ``speculation`` block, which
the proto schema does not carry).
"""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Tuple

# -- wire primitives --------------------------------------------------------

_VARINT = 0
_FIXED64 = 1
_LEN = 2
_FIXED32 = 5


def _enc_varint(value: int) -> bytes:
    if value < 0:
        # proto3 negative int64/int32 encode as 10-byte two's complement
        value &= (1 << 64) - 1
    out = bytearray()
    while True:
        bits = value & 0x7F
        value >>= 7
        if value:
            out.append(bits | 0x80)
        else:
            out.append(bits)
            return bytes(out)


def _dec_varint(data: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= len(data):
            raise ValueError("truncated varint")
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift >= 70:
            raise ValueError("varint too long")


def _key(field: int, wire: int) -> bytes:
    return _enc_varint((field << 3) | wire)


def _signed64(value: int) -> int:
    return value - (1 << 64) if value >= (1 << 63) else value


# -- schema -----------------------------------------------------------------

ENUMS: Dict[str, Dict[int, Optional[str]]] = {
    "Role": {1: "system", 2: "user", 3: "assistant"},
    "FinishReason": {1: "stop", 2: "length", 3: "stop_sequence"},
    "Priority": {1: "low", 2: "normal", 3: "high"},
}
_ENUM_TO_NUM = {
    name: {v: k for k, v in table.items() if v is not None}
    for name, table in ENUMS.items()
}

# field entry: (name, type, cardinality) where type is one of
# "string" "uint32" "uint64" "int64" "bool" "float" "double" "enum:<E>"
# "msg:<M>"
# and cardinality is "one" (implicit presence: zero omitted, default
# filled on decode), "opt" (explicit presence: emitted iff present in
# the dict and not None; absent from the decoded dict otherwise), or
# "rep" (repeated; packed scalars supported both ways).
_F = Tuple[str, str, str]
MESSAGES: Dict[str, Dict[int, _F]] = {
    # request numeric knobs are proto3 `optional` (explicit presence):
    # absent -> server default applies; explicit 0 is honored
    # (temperature 0 = greedy)
    "GenerateRequest": {
        1: ("prompt", "string", "one"),
        2: ("max_tokens", "uint32", "opt"),
        3: ("temperature", "float", "opt"),
        4: ("top_p", "float", "opt"),
        5: ("stop_sequences", "string", "rep"),
        6: ("stream", "bool", "one"),
        7: ("priority", "enum:Priority", "opt"),
    },
    "ChatMessage": {
        1: ("role", "enum:Role", "one"),
        2: ("content", "string", "one"),
    },
    "ChatRequest": {
        1: ("messages", "msg:ChatMessage", "rep"),
        2: ("max_tokens", "uint32", "opt"),
        3: ("temperature", "float", "opt"),
        4: ("top_p", "float", "opt"),
        5: ("stop_sequences", "string", "rep"),
        6: ("stream", "bool", "one"),
    },
    "EmbeddingsRequest": {
        1: ("input", "string", "rep"),
        2: ("model", "string", "opt"),
    },
    "HealthRequest": {},
    "Usage": {
        1: ("prompt_tokens", "uint32", "one"),
        2: ("completion_tokens", "uint32", "one"),
        3: ("total_tokens", "uint32", "one"),
    },
    "GenerateChoice": {
        1: ("text", "string", "one"),
        2: ("index", "uint32", "one"),
        3: ("finish_reason", "enum:FinishReason", "one"),
    },
    "GenerateResponse": {
        1: ("id", "string", "one"),
        2: ("object", "string", "one"),
        3: ("created", "int64", "one"),
        4: ("model", "string", "one"),
        5: ("choices", "msg:GenerateChoice", "rep"),
        6: ("usage", "msg:Usage", "opt"),
    },
    "ChatChoice": {
        1: ("index", "uint32", "one"),
        2: ("message", "msg:ChatMessage", "opt"),
        3: ("finish_reason", "enum:FinishReason", "one"),
    },
    "ChatResponse": {
        1: ("id", "string", "one"),
        2: ("object", "string", "one"),
        3: ("created", "int64", "one"),
        4: ("model", "string", "one"),
        5: ("choices", "msg:ChatChoice", "rep"),
        6: ("usage", "msg:Usage", "opt"),
    },
    "EmbeddingData": {
        1: ("object", "string", "one"),
        2: ("embedding", "float", "rep"),
        3: ("index", "uint32", "one"),
    },
    "EmbeddingsResponse": {
        1: ("object", "string", "one"),
        2: ("data", "msg:EmbeddingData", "rep"),
        3: ("model", "string", "one"),
        4: ("usage", "msg:Usage", "opt"),
    },
    "EngineStatus": {
        1: ("engine_id", "string", "one"),
        2: ("healthy", "bool", "one"),
        3: ("active_requests", "uint32", "one"),
        4: ("waiting_requests", "uint32", "one"),
        # uint64 to match inference.proto exactly (distlint DL005): the
        # varint bytes are identical for counts < 2^63, but a signed
        # decode would misread a colossal counter as negative
        5: ("total_processed", "uint64", "one"),
        6: ("memory_used_pages", "uint32", "one"),
        7: ("memory_total_pages", "uint32", "one"),
        # disaggregation role (serving/disagg.py); "unified" when the
        # topology is monolithic, so it is always on the wire
        8: ("role", "string", "one"),
        # reclaimable refcount-0 prefix pages within memory_used_pages
        9: ("pages_cached", "uint32", "one"),
        # fleet heartbeat payload (serving/fleet.py): the routing digest
        # travels with the status so the registry host's cache_aware
        # cost model can score a remote member's cached prefix chains
        10: ("prefix_digest", "uint64", "rep"),
        11: ("page_size", "uint32", "one"),
        12: ("digest_depth", "uint32", "one"),
        13: ("host_tier_bytes", "uint64", "one"),
        14: ("host_tier_pages", "uint32", "one"),
    },
    "HealthResponse": {
        1: ("status", "string", "one"),
        2: ("accepting", "bool", "one"),
        3: ("engines", "msg:EngineStatus", "rep"),
    },
    # TokenEvent's oneof members; the tagged-union translation to the
    # JSON shape happens in encode/decode_token_event below
    "TokenEvent.Token": {
        1: ("token", "string", "one"),
        2: ("index", "uint32", "one"),
        3: ("logprob", "float", "opt"),
    },
    "TokenEvent.Done": {
        1: ("finish_reason", "enum:FinishReason", "one"),
        2: ("usage", "msg:Usage", "opt"),
    },
    "TokenEvent.StreamError": {
        1: ("messages", "string", "one"),
        2: ("code", "string", "one"),
    },
    "TokenEvent": {
        1: ("token", "msg:TokenEvent.Token", "opt"),
        2: ("done", "msg:TokenEvent.Done", "opt"),
        3: ("error", "msg:TokenEvent.StreamError", "opt"),
    },
    # Multi-host fleet control plane (serving/fleet.py,
    # serving/remote_runner.py; docs/FLEET.md): a worker member's
    # heartbeat, the registry host's forwarded request, and the streamed
    # result events — the three frame kinds of the fleet wire.
    "FleetHeartbeat": {
        1: ("member_id", "string", "one"),
        2: ("seq", "uint64", "one"),
        3: ("engines", "msg:EngineStatus", "rep"),
        # fleet KV data plane (serving/fleet_kv.py): the member's KV
        # data listener port; 0 = no data plane
        4: ("data_port", "uint32", "one"),
    },
    "FleetSubmit": {
        1: ("request_id", "string", "one"),
        2: ("engine_id", "string", "one"),
        3: ("prompt_ids", "uint32", "rep"),
        4: ("max_tokens", "uint32", "one"),
        # double, not float: cross-host token identity needs the
        # sampling params bit-exact (same rationale as KvHandoff)
        5: ("temperature", "double", "one"),
        6: ("top_p", "double", "one"),
        7: ("stop_sequences", "string", "rep"),
        8: ("tenant", "string", "one"),
        9: ("abort", "bool", "one"),
        # distributed trace context (docs/OBSERVABILITY.md): the member
        # parents its fleet.serve span on this; "" = untraced
        10: ("trace_id", "string", "one"),
        11: ("parent_span_id", "string", "one"),
        # KV mesh fetch hint (serving/fleet_mesh.py): the registry host
        # attaches the fetch plan to the submit it was sending anyway,
        # and the member pulls the prefix straight from the named peer
        # over its own mesh channel — bulk bytes skip the registry.
        # fetch_member "" = no hint; old members skip unknown fields
        # and serve by recompute (graceful degradation).
        12: ("fetch_member", "string", "one"),
        13: ("fetch_source_engine", "string", "one"),
        14: ("fetch_hashes", "uint64", "rep"),
        15: ("fetch_chunk_pages", "uint32", "one"),
        16: ("fetch_wire_quant", "string", "one"),
        # registry HA epoch fence (serving/fleet_ha.py): members accept
        # control only from the highest epoch seen; 0 = unfenced legacy
        17: ("epoch", "uint64", "one"),
    },
    # KV mesh introduction (serving/fleet_mesh.py; docs/FLEET.md "KV
    # mesh"): the registry host brokers member↔member data-plane
    # endpoints over fleet-wire frame kind 6; gone=true retracts a dead
    # member's endpoint.
    "KvIntro": {
        1: ("member_id", "string", "one"),
        2: ("host", "string", "one"),
        3: ("data_port", "uint32", "one"),
        4: ("max_streams", "uint32", "one"),
        5: ("gone", "bool", "one"),
        # registry HA epoch fence (serving/fleet_ha.py): stale-epoch
        # intros from a fenced registry are ignored by members
        6: ("epoch", "uint64", "one"),
    },
    # Registry HA control wire (serving/fleet_ha.py; docs/FLEET.md
    # "Registry HA"): the primary's lease beat (frame kind 7) and a
    # standby's state echo (frame kind 8), exchanged registry↔registry
    # over the same fleet wire. Epochs are monotonic across takeovers
    # and fence partitioned old primaries.
    "RegistryLease": {
        1: ("registry_id", "string", "one"),
        2: ("epoch", "uint64", "one"),
        3: ("seq", "uint64", "one"),
        4: ("role", "string", "one"),
    },
    "RegistryState": {
        1: ("registry_id", "string", "one"),
        2: ("epoch", "uint64", "one"),
        3: ("role", "string", "one"),
    },
    "FleetEvent": {
        1: ("request_id", "string", "one"),
        2: ("engine_id", "string", "one"),
        3: ("kind", "string", "one"),
        4: ("token_id", "uint32", "opt"),
        5: ("text", "string", "one"),
        6: ("token_index", "uint32", "one"),
        7: ("logprob", "float", "opt"),
        8: ("finish_reason", "string", "one"),
        9: ("prompt_tokens", "uint32", "one"),
        10: ("completion_tokens", "uint32", "one"),
        11: ("message", "string", "one"),
        12: ("code", "string", "one"),
    },
    # Fleet-stitched distributed tracing (docs/OBSERVABILITY.md):
    # finished member spans batched back to the registry host at
    # heartbeat cadence (fleet-wire frame kind 4). Timestamps are EPOCH
    # nanoseconds — each process re-bases its own monotonic clock on the
    # wire, so the receiver can merge into its own monotonic domain.
    "TraceEvent": {
        1: ("offset_ns", "uint64", "one"),
        2: ("name", "string", "one"),
        3: ("attrs_json", "string", "one"),
    },
    "TraceSpan": {
        1: ("name", "string", "one"),
        2: ("trace_id", "string", "one"),
        3: ("span_id", "string", "one"),
        4: ("parent_id", "string", "one"),
        5: ("start_unix_ns", "uint64", "one"),
        6: ("duration_ns", "uint64", "one"),
        7: ("status", "string", "one"),
        8: ("attrs_json", "string", "one"),
        9: ("events", "msg:TraceEvent", "rep"),
    },
    "FleetSpans": {
        1: ("member_id", "string", "one"),
        2: ("spans", "msg:TraceSpan", "rep"),
        3: ("dropped", "uint64", "one"),
    },
    # Fleet-federated performance telemetry (serving/teledigest.py;
    # docs/OBSERVABILITY.md "Performance telemetry"): a member's
    # windowed log-bucket digests + cumulative step-clock counters,
    # piggybacked per heartbeat on fleet-wire frame kind 5. Epoch
    # indices are wall-clock aligned (time // epoch_s) so the registry
    # host merges member epochs exactly; bucket/count arrays are
    # parallel and sorted (canonical form — equal contents encode
    # equal bytes).
    "TeleEpoch": {
        1: ("index", "uint64", "one"),
        2: ("buckets", "uint32", "rep"),
        3: ("counts", "uint64", "rep"),
        4: ("n", "uint64", "one"),
        # integer microseconds, not a double: float addition is
        # order-dependent in its last bits, which would break the
        # bit-equality of merged views under re-grouping
        5: ("sum_us", "uint64", "one"),
    },
    "TeleDigest": {
        1: ("name", "string", "one"),
        2: ("epoch_s", "double", "one"),
        3: ("epochs", "msg:TeleEpoch", "rep"),
    },
    "TeleCounter": {
        1: ("name", "string", "one"),
        2: ("value", "double", "one"),
    },
    "FleetTelemetry": {
        1: ("member_id", "string", "one"),
        2: ("digests", "msg:TeleDigest", "rep"),
        3: ("counters", "msg:TeleCounter", "rep"),
    },
    "ErrorDetail": {
        1: ("message", "string", "one"),
        2: ("error_type", "string", "one"),
        3: ("code", "string", "one"),
    },
    "ErrorResponse": {
        1: ("error", "msg:ErrorDetail", "opt"),
    },
    # Streamed KV handoff framing (serving/disagg.py stream_to_frames):
    # header + crc-guarded page-group chunks + a terminal KvHandoff
    # state frame. Payloads are opaque KVP1 bytes (engine/kv_cache.py).
    "KvHandoffHeader": {
        1: ("handoff_id", "string", "one"),
        2: ("request_id", "string", "one"),
        3: ("wire_quant", "string", "one"),
        # distributed trace context (docs/OBSERVABILITY.md)
        4: ("trace_id", "string", "one"),
        5: ("parent_span_id", "string", "one"),
        # fleet KV data plane (serving/fleet_kv.py): stream operation
        # tag ("" = legacy in-process framing), member-local engine id,
        # and the stream geometry the receiver assembles against
        6: ("op", "string", "one"),
        7: ("engine_id", "string", "one"),
        8: ("prefix_pages", "uint32", "one"),
        9: ("total_chunks", "uint32", "one"),
    },
    "KvChunk": {
        1: ("handoff_id", "string", "one"),
        2: ("index", "uint32", "one"),
        3: ("total", "uint32", "one"),
        4: ("page_start", "uint32", "one"),
        5: ("page_count", "uint32", "one"),
        6: ("crc32", "uint32", "one"),
        7: ("payload", "bytes", "one"),
    },
    # Fleet-wide prefix sharing (serving/disagg.py PrefixFetcher): the
    # request half of the fetch_prefix RPC — a cold replica asks a warm
    # peer for a cached prefix chain by content hash; the response
    # reuses the KvHandoffHeader/KvChunk framing above. Hashes are the
    # 63-bit chain_hashes key space, so uint64 carries them exactly.
    "KvPrefixFetch": {
        1: ("request_id", "string", "one"),
        2: ("hashes", "uint64", "rep"),
        3: ("chunk_pages", "uint32", "one"),
        4: ("wire_quant", "string", "one"),
        # distributed trace context (docs/OBSERVABILITY.md)
        5: ("trace_id", "string", "one"),
        6: ("parent_span_id", "string", "one"),
        # fleet KV data plane (serving/fleet_kv.py): which member
        # engine serves the export ("" = in-process fetch)
        7: ("engine_id", "string", "one"),
    },
    # Fleet KV data plane (serving/fleet_kv.py): per-stream terminal
    # status of a member data channel — handoff open/commit/resume acks,
    # fetch-response terminators, and host->member import aborts.
    "KvStreamResult": {
        1: ("stream_id", "string", "one"),
        2: ("op", "string", "one"),
        3: ("ok", "bool", "one"),
        4: ("error", "string", "one"),
        5: ("depth", "uint32", "one"),
        6: ("engine_id", "string", "one"),
    },
    # Disaggregated prefill/decode serving (serving/disagg.py): a live
    # sequence lifted off a prefill engine for cross-process KV transfer.
    # ``kv`` / ``draft_kv`` carry the serialize_kv page payloads opaque;
    # the rest reconstructs the host-side sequence state exactly.
    "KvHandoff": {
        1: ("request_id", "string", "one"),
        2: ("token_ids", "uint32", "rep"),
        3: ("prompt_len", "uint32", "one"),
        4: ("seq_len", "uint32", "one"),
        5: ("next_token", "uint32", "one"),
        6: ("emitted_tokens", "uint32", "one"),
        7: ("output_text", "string", "one"),
        8: ("emitted_upto", "uint32", "one"),
        9: ("pending_ids", "uint32", "rep"),
        10: ("max_tokens", "uint32", "one"),
        # double, not float: sampled-path token identity across the
        # handoff requires the params bit-exact, and Python floats are
        # doubles
        11: ("temperature", "double", "one"),
        12: ("top_p", "double", "one"),
        13: ("stop_sequences", "string", "rep"),
        14: ("kv", "bytes", "one"),
        15: ("draft_kv", "bytes", "opt"),
        16: ("source_engine", "string", "one"),
    },
}

_SCALAR_DEFAULT = {
    "string": "",
    "bytes": b"",
    "uint32": 0,
    "uint64": 0,
    "int64": 0,
    "bool": False,
    "float": 0.0,
    "double": 0.0,
}


# -- encode -----------------------------------------------------------------


def _enc_scalar(ftype: str, value) -> Tuple[int, bytes]:
    """Returns (wire_type, payload bytes without the key)."""
    if ftype == "string":
        data = str(value).encode("utf-8")
        return _LEN, _enc_varint(len(data)) + data
    if ftype == "bytes":
        data = bytes(value)
        return _LEN, _enc_varint(len(data)) + data
    if ftype in ("uint32", "uint64", "int64"):
        return _VARINT, _enc_varint(int(value))
    if ftype == "bool":
        return _VARINT, _enc_varint(1 if value else 0)
    if ftype == "float":
        return _FIXED32, struct.pack("<f", float(value))
    if ftype == "double":
        return _FIXED64, struct.pack("<d", float(value))
    if ftype.startswith("enum:"):
        num = _ENUM_TO_NUM[ftype[5:]].get(value, 0)
        return _VARINT, _enc_varint(num)
    raise ValueError(f"not a scalar type: {ftype}")


def encode(msg: str, obj: Dict[str, Any]) -> bytes:
    if msg == "TokenEvent":
        return _encode_token_event(obj)
    return _encode_fields(msg, obj)


def _encode_fields(msg: str, obj: Dict[str, Any]) -> bytes:
    fields = MESSAGES[msg]
    out = bytearray()
    for num in sorted(fields):
        name, ftype, card = fields[num]
        if name not in obj:
            continue
        value = obj[name]
        if card == "rep":
            items = value or []
            if ftype.startswith("msg:"):
                sub = ftype[4:]
                for item in items:
                    data = encode(sub, item)
                    out += _key(num, _LEN) + _enc_varint(len(data)) + data
            elif ftype in ("float", "double", "uint32", "uint64", "int64",
                           "bool") or ftype.startswith("enum:"):
                # packed (proto3 default for scalars)
                packed = bytearray()
                for item in items:
                    _, payload = _enc_scalar(ftype, item)
                    packed += payload
                if packed:
                    out += (_key(num, _LEN)
                            + _enc_varint(len(packed)) + bytes(packed))
            else:  # strings/bytes are never packed
                for item in items:
                    wire, payload = _enc_scalar(ftype, item)
                    out += _key(num, wire) + payload
            continue
        if value is None:
            continue
        if ftype.startswith("msg:"):
            data = _encode_fields(ftype[4:], value)
            out += _key(num, _LEN) + _enc_varint(len(data)) + data
            continue
        if card == "one":
            # implicit presence: zero values stay off the wire
            if ftype.startswith("enum:"):
                if _ENUM_TO_NUM[ftype[5:]].get(value, 0) == 0:
                    continue
            elif value == _SCALAR_DEFAULT.get(ftype):
                continue
        wire, payload = _enc_scalar(ftype, value)
        out += _key(num, wire) + payload
    return bytes(out)


def _encode_token_event(obj: Dict[str, Any]) -> bytes:
    kind = obj.get("type")
    if kind == "token":
        inner = {"token": obj.get("token", ""),
                 "index": obj.get("index", 0)}
        if obj.get("logprob") is not None:
            inner["logprob"] = obj["logprob"]
        return _encode_fields("TokenEvent", {"token": inner})
    if kind == "done":
        return _encode_fields("TokenEvent", {"done": {
            "finish_reason": obj.get("finish_reason"),
            "usage": obj.get("usage"),
        }})
    if kind == "error":
        return _encode_fields("TokenEvent", {"error": {
            "messages": obj.get("messages", ""),
            "code": obj.get("code", ""),
        }})
    raise ValueError(f"unknown TokenEvent type: {kind!r}")


# -- decode -----------------------------------------------------------------


def _check_len(data: bytes, pos: int, length: int) -> None:
    # slicing past the buffer would silently shorten the field (a
    # truncated frame decoding to a plausible-but-wrong payload)
    if pos + length > len(data):
        raise ValueError("truncated length-delimited field")


def _skip(wire: int, data: bytes, pos: int) -> int:
    if wire == _VARINT:
        _, pos = _dec_varint(data, pos)
        return pos
    if wire == _FIXED64:
        return pos + 8
    if wire == _FIXED32:
        return pos + 4
    if wire == _LEN:
        length, pos = _dec_varint(data, pos)
        _check_len(data, pos, length)
        return pos + length
    raise ValueError(f"unsupported wire type {wire}")


def _dec_scalar(ftype: str, wire: int, data: bytes, pos: int):
    if ftype == "string":
        if wire != _LEN:
            raise ValueError("string field must be length-delimited")
        length, pos = _dec_varint(data, pos)
        _check_len(data, pos, length)
        return data[pos:pos + length].decode("utf-8"), pos + length
    if ftype == "bytes":
        if wire != _LEN:
            raise ValueError("bytes field must be length-delimited")
        length, pos = _dec_varint(data, pos)
        _check_len(data, pos, length)
        return bytes(data[pos:pos + length]), pos + length
    if ftype in ("uint32", "uint64", "int64"):
        v, pos = _dec_varint(data, pos)
        return (_signed64(v) if ftype == "int64" else v), pos
    if ftype == "bool":
        v, pos = _dec_varint(data, pos)
        return bool(v), pos
    if ftype == "float":
        return struct.unpack("<f", data[pos:pos + 4])[0], pos + 4
    if ftype == "double":
        return struct.unpack("<d", data[pos:pos + 8])[0], pos + 8
    if ftype.startswith("enum:"):
        v, pos = _dec_varint(data, pos)
        return ENUMS[ftype[5:]].get(v), pos
    raise ValueError(f"not a scalar type: {ftype}")


def decode(msg: str, data: bytes) -> Dict[str, Any]:
    if msg == "TokenEvent":
        return _decode_token_event(data)
    fields = MESSAGES[msg]
    obj: Dict[str, Any] = {}
    # proto3 defaults so decoded dicts are key-identical to the JSON wire
    for num in sorted(fields):
        name, ftype, card = fields[num]
        if card == "rep":
            obj[name] = []
        elif card == "one":
            if ftype.startswith("msg:"):
                continue
            obj[name] = (None if ftype.startswith("enum:")
                         else _SCALAR_DEFAULT[ftype])
    pos = 0
    while pos < len(data):
        tag, pos = _dec_varint(data, pos)
        num, wire = tag >> 3, tag & 7
        entry = fields.get(num)
        if entry is None:
            pos = _skip(wire, data, pos)
            continue
        name, ftype, card = entry
        if ftype.startswith("msg:"):
            if wire != _LEN:
                raise ValueError(f"message field {name} wire type {wire}")
            length, pos = _dec_varint(data, pos)
            _check_len(data, pos, length)
            sub = decode(ftype[4:], data[pos:pos + length])
            pos += length
            if card == "rep":
                obj[name].append(sub)
            else:
                obj[name] = sub
            continue
        if card == "rep" and wire == _LEN and ftype in (
            "uint32", "uint64", "int64", "bool", "float", "double"
        ) or (card == "rep" and wire == _LEN
              and ftype.startswith("enum:")):
            # packed scalars
            length, pos = _dec_varint(data, pos)
            end = pos + length
            while pos < end:
                v, pos = _dec_scalar(ftype, _wire_for(ftype), data, pos)
                obj[name].append(v)
            continue
        v, pos = _dec_scalar(ftype, wire, data, pos)
        if card == "rep":
            obj[name].append(v)
        else:
            obj[name] = v
    return obj


def _wire_for(ftype: str) -> int:
    if ftype == "float":
        return _FIXED32
    if ftype == "double":
        return _FIXED64
    return _VARINT


def _decode_token_event(data: bytes) -> Dict[str, Any]:
    # decode via the oneof table, then flatten to the tagged-union JSON
    fields = MESSAGES["TokenEvent"]
    obj: Dict[str, Any] = {}
    pos = 0
    while pos < len(data):
        tag, pos = _dec_varint(data, pos)
        num, wire = tag >> 3, tag & 7
        entry = fields.get(num)
        if entry is None:
            pos = _skip(wire, data, pos)
            continue
        name, ftype, _ = entry
        length, pos = _dec_varint(data, pos)
        _check_len(data, pos, length)
        obj[name] = decode(ftype[4:], data[pos:pos + length])
        pos += length
    if "token" in obj:
        out = {"type": "token", "token": obj["token"]["token"],
               "index": obj["token"]["index"]}
        if "logprob" in obj["token"]:
            out["logprob"] = obj["token"]["logprob"]
        return out
    if "done" in obj:
        return {"type": "done",
                "finish_reason": obj["done"]["finish_reason"],
                "usage": obj["done"].get(
                    "usage",
                    {"prompt_tokens": 0, "completion_tokens": 0,
                     "total_tokens": 0},
                )}
    if "error" in obj:
        return {"type": "error", "messages": obj["error"]["messages"],
                "code": obj["error"]["code"]}
    raise ValueError("TokenEvent with no oneof member set")
