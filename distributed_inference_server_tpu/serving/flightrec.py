"""Per-request flight recorder: one queryable timeline per request, with
phase-attributed latency (docs/OBSERVABILITY.md).

``/server/trace`` answers "what spans ran"; this answers the operator's
actual question — **"where did THIS request's latency go?"** The serving
spine notes structured events into a bounded per-request timeline as the
request moves:

    admit -> route_plan/schedule (strategy + plan_route cost terms)
    -> prefix_fetch / handoff phases -> first_token -> decode token
    BLOCKS -> terminal (done | error | redispatch hops in between)

and at the terminal event the recorder derives a **phase attribution**
that partitions the request's wall clock:

    queue_wait   admit -> dispatch (queue + admission batching)
    prefill      dispatch -> first token, minus fetch windows
    peer_fetch   fleet prefix-fetch wall time (docs/CACHING.md)
    handoff_stall  decode pauses from KV migration (docs/DISAGG.md)
    decode       first token -> last token, minus handoff stalls
    detok        last token -> terminal (final flush + usage delivery)

The partition is exact by construction (each window is subtracted from
the span that contains it), so the phases sum to the request's wall
clock; they export as ``request_phase_seconds{phase=...}`` and ride the
``GET /server/requests/<id>`` JSON with a TTFT/TBT breakdown.

Memory is bounded twice: at most ``max_requests`` timelines (oldest
evicted, counted) and at most ``max_events`` events per timeline
(further events drop, counted — the terminal event always lands). The
hot per-token path is one dict lookup + counter bump; token events
aggregate into blocks of ``block_tokens`` so a 4k-token decode costs
~256 timeline entries' worth of appends, not 4k. A ``None`` recorder on
the spine is a single identity check — the disabled fast path allocates
nothing per token.

Fleet-level hops that are not per-request — role rebalancing flips,
fault-injection arm/disarm — land in a global window
(``note_global``) and are merged into any timeline that overlaps them,
so a postmortem shows "the rerole happened mid-decode" without every
request paying for fleet bookkeeping.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from typing import Any, Deque, Dict, List, Optional, Tuple

from distributed_inference_server_tpu.serving.metrics import MetricsCollector
from distributed_inference_server_tpu.serving.teledigest import (
    SloSettings,
    slo_verdict,
)

PHASES = ("queue_wait", "prefill", "peer_fetch", "handoff_stall",
          "decode", "detok")


class _Timeline:
    """One request's bounded event timeline (single-writer per field at
    any instant — the request has exactly one owner on the spine; the
    recorder's lock orders the rare ownership handoffs)."""

    __slots__ = (
        "request_id", "admitted_at", "events", "events_dropped", "tokens",
        "first_token_at", "last_token_at", "dispatch_at", "terminal_at",
        "status", "code", "peer_fetch_s", "handoff_stall_s", "trace_id",
        "attrs", "slo", "_block_anchor",
    )

    def __init__(self, request_id, now: float):
        self.request_id = request_id
        self.admitted_at = now
        self.events: List[Tuple[float, str, Dict[str, Any]]] = []
        self.events_dropped = 0
        self.tokens = 0
        self.first_token_at: Optional[float] = None
        self.last_token_at: Optional[float] = None
        self.dispatch_at: Optional[float] = None
        self.terminal_at: Optional[float] = None
        self.status = "live"
        self.code: Optional[str] = None
        self.peer_fetch_s = 0.0
        self.handoff_stall_s = 0.0
        self.trace_id: Optional[str] = None
        self.attrs: Dict[str, Any] = {}
        # SLO verdict block, derived once at finish() (None = no
        # applicable objective; docs/OBSERVABILITY.md)
        self.slo: Optional[Dict[str, Any]] = None
        self._block_anchor = 0  # tokens already folded into block events


class FlightRecorder:
    """Bounded per-request timelines + derived phase attribution."""

    def __init__(self, metrics: Optional[MetricsCollector] = None,
                 max_requests: int = 256, max_events: int = 96,
                 block_tokens: int = 16, max_global_events: int = 128,
                 slo: Optional[SloSettings] = None):
        """``slo`` (serving/teledigest.py SloSettings) arms SLO
        accounting: ``finish()`` derives an ok/violated verdict from
        the request's exact phase partition, stamps it on the timeline,
        and feeds ``slo_requests_total{tenant,verdict}`` + the goodput
        counters (docs/OBSERVABILITY.md "Performance telemetry")."""
        self.metrics = metrics
        self.slo = slo
        self.max_requests = max_requests
        self.max_events = max_events
        self.block_tokens = max(1, block_tokens)
        self._lock = threading.Lock()
        self._timelines: "OrderedDict[Any, _Timeline]" = OrderedDict()
        self._evicted = 0
        # fleet-level hops (rerole, fault arm/disarm): one bounded
        # window shared by every timeline that overlaps it
        self._global: Deque[Tuple[float, str, Dict[str, Any]]] = deque(
            maxlen=max_global_events)

    # -- recording (any thread) --------------------------------------------

    def admit(self, request_id, **attrs) -> None:
        """The request entered the spine (handler submit). ``trace_id``
        in ``attrs`` links the timeline to its stitched trace."""
        now = time.monotonic()
        with self._lock:
            tl = self._get_or_create_locked(request_id, now)
            tl.attrs.update(attrs)
            tid = attrs.get("trace_id")
            if tid:
                tl.trace_id = str(tid)
            self._append_locked(tl, now, "admit", attrs)

    def note(self, request_id, name: str, **attrs) -> None:
        """One structured lifecycle event. Recognized names feed the
        phase model: ``route_plan``/``schedule`` anchor the dispatch
        instant; a ``seconds`` attr on ``prefix_fetch`` accumulates the
        peer_fetch window; a ``stall_s`` attr (handoff events)
        accumulates the handoff_stall window."""
        now = time.monotonic()
        with self._lock:
            tl = self._get_or_create_locked(request_id, now)
            if name in ("route_plan", "schedule") and tl.dispatch_at is None:
                tl.dispatch_at = now
            if name == "prefix_fetch" and "seconds" in attrs:
                try:
                    tl.peer_fetch_s += max(0.0, float(attrs["seconds"]))
                except (TypeError, ValueError):
                    pass
            if "stall_s" in attrs:
                try:
                    tl.handoff_stall_s += max(0.0, float(attrs["stall_s"]))
                except (TypeError, ValueError):
                    pass
            self._append_locked(tl, now, name, attrs)

    def token(self, request_id, n: int = 1) -> None:
        """The per-token hot path: counter bumps plus one aggregated
        ``decode_block`` event per ``block_tokens`` tokens."""
        now = time.monotonic()
        with self._lock:
            # auto-create: requests submitted straight to a runner
            # (chaos harness, tests) still get a usable timeline
            tl = self._get_or_create_locked(request_id, now)
            if tl.terminal_at is not None:
                return
            if tl.first_token_at is None:
                tl.first_token_at = now
                self._append_locked(tl, now, "first_token", {})
            tl.last_token_at = now
            tl.tokens += n
            if tl.tokens - tl._block_anchor >= self.block_tokens:
                count = tl.tokens - tl._block_anchor
                tl._block_anchor = tl.tokens
                self._append_locked(tl, now, "decode_block",
                                    {"tokens": count,
                                     "total": tl.tokens})

    def finish(self, request_id, status: str,
               code: Optional[str] = None) -> Optional[Dict[str, float]]:
        """The request terminated (done XOR error — first call wins,
        matching the sink contract). Derives and returns the phase
        attribution, and exports it as request_phase_seconds."""
        now = time.monotonic()
        with self._lock:
            tl = self._get_or_create_locked(request_id, now)
            if tl.terminal_at is not None:
                return None
            tl.terminal_at = now
            tl.status = status
            tl.code = code
            if tl.tokens > tl._block_anchor:
                self._append_locked(
                    tl, tl.last_token_at or now, "decode_block",
                    {"tokens": tl.tokens - tl._block_anchor,
                     "total": tl.tokens})
                tl._block_anchor = tl.tokens
            # the terminal event always lands, bounded or not
            tl.events.append(
                (now, "terminal",
                 {"status": status, **({"code": code} if code else {})}))
            phases = self._phases_locked(tl, now)
            # SLO inputs, exactly from the phase model: TTFT is the
            # admit->first-token span (queue_wait + prefill +
            # peer_fetch, exactly), TBT the mean first->last inter-token
            # gap (decode + handoff stalls — the client observes the
            # stall, so the objective charges it)
            ttft_s = (tl.first_token_at - tl.admitted_at
                      if tl.first_token_at is not None else None)
            tbt_s = None
            if (tl.tokens > 1 and tl.first_token_at is not None
                    and tl.last_token_at is not None):
                tbt_s = ((tl.last_token_at - tl.first_token_at)
                         / (tl.tokens - 1))
            tenant = str(tl.attrs.get("tenant") or "default")
            tokens = tl.tokens
        if code == "admission_shed":
            # the request was never ADMITTED (serving/health.py): its
            # timeline is the whole artifact. Exporting its ~0s
            # queue_wait would drag the very estimate that shed it back
            # under the deadline (admission oscillates open under a
            # standing backlog), and an SLO verdict would burn the
            # objective for work the fleet declined in microseconds —
            # both signals must track admitted traffic only.
            return phases
        verdict = None
        if self.slo is not None:
            verdict = slo_verdict(self.slo, tenant, ttft_s, tbt_s, status)
            if verdict is not None:
                # single assignment after the terminal landed: the
                # request has exactly one finisher (first call wins
                # above), so no second writer exists
                tl.slo = verdict
        if self.metrics is not None:
            self.metrics.record_request_phases(phases, tbt_s=tbt_s)
            if verdict is not None:
                self.metrics.record_slo(tenant, verdict["verdict"],
                                        tokens=tokens)
        return phases

    def note_global(self, name: str, **attrs) -> None:
        """A fleet-level hop (rerole, fault arm/disarm) — merged into
        every overlapping timeline at render time."""
        with self._lock:
            self._global.append((time.monotonic(), name, attrs))

    # -- internals (lock held) ---------------------------------------------

    def _get_or_create_locked(self, request_id, now: float) -> _Timeline:
        tl = self._timelines.get(request_id)
        if tl is not None:
            return tl
        tl = _Timeline(request_id, now)
        self._timelines[request_id] = tl
        while len(self._timelines) > self.max_requests:
            self._timelines.popitem(last=False)
            self._evicted += 1
        return tl

    def _append_locked(self, tl: _Timeline, now: float, name: str,
                       attrs: Dict[str, Any]) -> None:
        if len(tl.events) >= self.max_events:
            tl.events_dropped += 1
            return
        tl.events.append((now, name, dict(attrs)))

    def _phases_locked(self, tl: _Timeline,
                       now: float) -> Dict[str, float]:
        """Partition [admit, terminal] into the six phases. Windowed
        costs (peer fetch, handoff stall) are clamped to the span that
        contains them, so the partition stays exact."""
        t0 = tl.admitted_at
        tt = tl.terminal_at if tl.terminal_at is not None else now
        tf = tl.first_token_at
        tlast = tl.last_token_at
        if tl.dispatch_at is not None:
            td = tl.dispatch_at
        elif tf is not None:
            # dispatched without a schedule note (direct runner submit):
            # the timeline opened at the submit, so admit->token is real
            # engine time, not queueing
            td = t0
        else:
            # NEVER dispatched (queue_timeout / no_workers): the whole
            # window is queue_wait — calling it prefill would invert the
            # "where did the latency go" answer for exactly the requests
            # that starved in the queue
            td = tt
        queue_wait = max(0.0, td - t0)
        first = tf if tf is not None else tt
        fetch = min(tl.peer_fetch_s, max(0.0, first - td))
        prefill = max(0.0, first - td - fetch)
        if tf is not None and tlast is not None:
            stall = min(tl.handoff_stall_s, max(0.0, tlast - tf))
            decode = max(0.0, tlast - tf - stall)
            detok = max(0.0, tt - tlast)
        else:
            stall = decode = detok = 0.0
        return {
            "queue_wait": queue_wait,
            "prefill": prefill,
            "peer_fetch": fetch,
            "handoff_stall": stall,
            "decode": decode,
            "detok": detok,
        }

    # -- introspection (any thread) ----------------------------------------

    def timeline(self, request_id) -> Optional[Dict[str, Any]]:
        """The ``GET /server/requests/<id>`` JSON: the event timeline,
        derived phases (provisional while live), TTFT/TBT breakdown,
        and any overlapping fleet-level events."""
        now = time.monotonic()
        with self._lock:
            tl = self._timelines.get(request_id)
            if tl is None:
                # ids arrive as strings over HTTP; timelines may be
                # keyed by RequestId objects
                for key, cand in self._timelines.items():
                    if str(key) == str(request_id):
                        tl = cand
                        break
                if tl is None:
                    return None
            t0 = tl.admitted_at
            tt = tl.terminal_at if tl.terminal_at is not None else now
            phases = self._phases_locked(tl, now)
            events = [
                {"t_ms": round((t - t0) * 1000.0, 3), "name": n,
                 **({"attributes": a} if a else {})}
                for t, n, a in tl.events
            ]
            fleet_events = [
                {"t_ms": round((t - t0) * 1000.0, 3), "name": n,
                 **({"attributes": a} if a else {})}
                for t, n, a in self._global if t0 <= t <= tt
            ]
            ttft = (tl.first_token_at - t0
                    if tl.first_token_at is not None else None)
            tbt = None
            if (tl.tokens > 1 and tl.first_token_at is not None
                    and tl.last_token_at is not None):
                tbt = ((tl.last_token_at - tl.first_token_at)
                       / (tl.tokens - 1))
            out = {
                "request_id": str(tl.request_id),
                "status": tl.status,
                "tokens": tl.tokens,
                "wall_s": round(tt - t0, 6),
                "phases": {k: round(v, 6) for k, v in phases.items()},
                "events": events,
                "events_dropped": tl.events_dropped,
                "attributes": dict(tl.attrs),
            }
            if tl.code:
                out["code"] = tl.code
            if tl.trace_id:
                out["trace_id"] = tl.trace_id
            if tl.slo is not None:
                out["slo"] = dict(tl.slo)
            if ttft is not None:
                out["ttft_s"] = round(ttft, 6)
            if tbt is not None:
                out["tbt_avg_s"] = round(tbt, 6)
            if fleet_events:
                out["fleet_events"] = fleet_events
            return out

    def recent(self, n: int = 50,
               verdict: Optional[str] = None) -> List[Dict[str, Any]]:
        """Newest-first summaries for ``GET /server/requests``.
        ``verdict`` ("ok" | "violated") keeps only timelines whose SLO
        verdict matches — the operator's "show me what burned the SLO"
        query (docs/OBSERVABILITY.md)."""
        with self._lock:
            items = list(self._timelines.values())
            if verdict is not None:
                items = [tl for tl in items
                         if tl.slo is not None
                         and tl.slo.get("verdict") == verdict]
            items = items[-n:]
        return [
            {"request_id": str(tl.request_id), "status": tl.status,
             "tokens": tl.tokens,
             **({"trace_id": tl.trace_id} if tl.trace_id else {}),
             **({"verdict": tl.slo["verdict"]}
                if tl.slo is not None else {})}
            for tl in reversed(items)
        ]

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            live = sum(1 for tl in self._timelines.values()
                       if tl.terminal_at is None)
            dropped = sum(tl.events_dropped
                          for tl in self._timelines.values())
            return {
                "tracked": len(self._timelines),
                "live": live,
                "evicted": self._evicted,
                "events_dropped": dropped,
            }
