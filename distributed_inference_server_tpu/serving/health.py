"""Gray-failure defense: latency-scored health, circuit breakers,
deadline-aware admission, and the shared retry budget
(docs/RESILIENCE.md "Gray failures and overload").

The fleet's health model before this module was BINARY liveness:
``EngineStatus.healthy`` plus the registry's alive → suspect → dead
aging. A replica that heartbeats while serving 10× slower, an engine
whose step clock has stalled under queued work, or a member behind a
congested KV wire stayed fully routable until requests burned their
whole deadline and died as ``queue_timeout``. PR 12 built exactly the
signals needed to do better (windowed TTFT/TBT digests, per-member
telemetry frames, the engine step clock); this module closes the loop
from *observe* to *act* in four coupled pieces:

- **HealthScorer** — a periodic evaluator demoting engines through
  ``healthy → degraded → ejected`` on telemetry evidence, with
  two-sided hysteresis (``health.demote_after`` consecutive bad
  evaluations to demote one level, ``health.recover_after`` clean ones
  to promote back — the same shape as the rerole balancer's band).
  Signals: **wedge** (the engine's step-clock dispatch counter stops
  moving while work is queued for ``health.stall_s`` — only after the
  engine has made progress at least once, so a cold replica mid-compile
  never reads as wedged), **latency** (a member's windowed TTFT/TBT p99
  exceeds ``health.latency_ratio`` × the median of the OTHER sources'
  p99s, from the same mergeable digests ``GET /server/perf`` serves),
  and **wire** (``health.wire_failures`` consecutive send failures on a
  member's control wire, or its KV data channel's circuit breaker
  open). Routing consumes the verdicts through ``stamp()``:
  ``AdaptiveScheduler.statuses()`` overlays ``EngineStatus.health`` and
  every strategy prefers healthy replicas, falls back to degraded, and
  admits ejected ones only when nothing else exists — Property 20
  ("never strand a request if any replica is admissible") is preserved
  absolutely.
- **CircuitBreaker** — the classic closed → open (on
  ``health.wire_failures`` consecutive failures) → half-open (one probe
  after ``health.breaker_open_s``) → closed machine, owned by each
  member's KV data channel (serving/fleet_kv.py) so cross-host handoff
  and peer fetch stop ELECTING targets behind a broken wire instead of
  discovering it one failed stream at a time.
- **AdmissionControl** — deadline-aware admission shedding: a request's
  deadline derives from its (per-tenant) TTFT SLO
  (``admission.deadline_factor`` × the applicable ``slo.ttft_ms`` /
  ``slo.tenant_ttft_ms``, or the explicit ``admission.deadline_ms``);
  when the windowed queue-wait estimate (the ``queue_wait_ms`` digest's
  p90) already blows it, the dispatcher sheds AT ADMISSION — failing
  fast with 503 + ``Retry-After`` + the distinct ``admission_shed``
  code instead of queueing doomed work toward a ``queue_timeout``.
  Brownout ordering rides the DRR weights (core/queue.py): a tenant
  with weight ``w`` sheds once the estimate exceeds
  ``deadline × w / w_max``, so the lowest-weight tenants brown out
  first while the highest-weight tenant sheds only when its own
  deadline is genuinely blown.
- **RetryBudget** — redispatch, the disagg handoff retry, and KV
  data-channel reconnects share one windowed budget (a fraction of
  recent admits, ``health.retry_budget_ratio``, floored at
  ``health.retry_budget_min``), so a sick fleet cannot amplify its own
  load; exhaustion degrades each consumer to its existing exactly-once
  fallback (sink failure, decode-in-place, recompute).

Everything here is advisory on top of the existing exactly-once and
zero-leak machinery — no transition creates or destroys a terminal
event, which is what the ``slow_member_brownout`` / ``breaker_flap`` /
``overload_shed`` chaos scenarios pin (tools/chaos_fleet.py).
"""

from __future__ import annotations

import logging
import math
import statistics
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Deque, Dict, List, Mapping, Optional, Tuple

from distributed_inference_server_tpu.core.errors import QueueFull
from distributed_inference_server_tpu.serving.teledigest import (
    SloSettings,
    window_stats,
)

logger = logging.getLogger(__name__)

HEALTH_HEALTHY = "healthy"
HEALTH_DEGRADED = "degraded"
HEALTH_EJECTED = "ejected"
HEALTH_STATES = (HEALTH_HEALTHY, HEALTH_DEGRADED, HEALTH_EJECTED)
_RANK = {HEALTH_HEALTHY: 0, HEALTH_DEGRADED: 1, HEALTH_EJECTED: 2}


def health_rank(state: str) -> int:
    """healthy=0 < degraded=1 < ejected=2 (routing sort key)."""
    return _RANK.get(state, 0)


@dataclass(frozen=True)
class HealthSettings:
    """Knobs of the gray-failure control plane (config section
    ``health``; docs/RESILIENCE.md "Gray failures and overload")."""

    enabled: bool = True
    interval_s: float = 1.0
    # wedge detection: no step-clock dispatch progress while work is
    # queued for this long (after at least one prior dispatch)
    stall_s: float = 5.0
    # latency demotion: a source's windowed p99 exceeds this multiple of
    # the median of the OTHER sources' p99s...
    latency_ratio: float = 3.0
    # ...and recovers below this multiple (two-sided hysteresis band)
    recover_ratio: float = 1.5
    # consecutive bad/clean evaluations to move one level down/up
    demote_after: int = 3
    recover_after: int = 3
    # minimum windowed samples before a latency comparison is trusted
    min_window_requests: int = 8
    # consecutive wire failures before a member's engines eject (also
    # the KV data channel's breaker close→open threshold)
    wire_failures: int = 3
    # breaker open → half-open probe delay
    breaker_open_s: float = 5.0
    # shared retry budget: retries allowed per window as a fraction of
    # admits, floored at retry_budget_min
    retry_budget_ratio: float = 0.1
    retry_budget_min: int = 3
    retry_window_s: float = 10.0
    # SLO burn-rate escalation input to the degradation ladder
    # (serving/degradation.py): burn >= slo_burn_high escalates to
    # REJECT_LOW_PRIORITY, >= slo_burn_high/2 to REDUCED_BATCH_SIZE,
    # once the window holds slo_burn_min_requests verdicts
    slo_burn_high: float = 0.5
    slo_burn_min_requests: int = 20


@dataclass(frozen=True)
class AdmissionSettings:
    """Knobs of deadline-aware admission (config section
    ``admission``)."""

    shed_enabled: bool = True
    # explicit deadline; 0 = derive from the applicable TTFT SLO
    deadline_ms: float = 0.0
    # deadline = factor × the (per-tenant) slo.ttft_ms objective
    deadline_factor: float = 1.0
    # weight-scaled early shed (lowest DRR weight sheds first)
    brownout: bool = True
    # don't trust a cold estimator: no shedding until the window holds
    # this many queue-wait samples
    min_window_requests: int = 8
    retry_after_cap_s: float = 30.0


class AdmissionShed(QueueFull):
    """Raised by ``Dispatcher.submit`` when deadline-aware admission
    sheds the request (serving/health.py AdmissionControl). A subclass
    of QueueFull so every existing backpressure handler keeps working;
    carries the shed reason and the Retry-After hint."""

    def __init__(self, reason: str, retry_after_s: float,
                 estimate_ms: float, deadline_ms: float):
        super().__init__()
        self.reason = reason
        self.retry_after_s = retry_after_s
        self.estimate_ms = estimate_ms
        self.deadline_ms = deadline_ms


# ---------------------------------------------------------------------------
# Circuit breaker
# ---------------------------------------------------------------------------

BREAKER_CLOSED = "closed"
BREAKER_OPEN = "open"
BREAKER_HALF_OPEN = "half_open"


class CircuitBreaker:
    """closed → open (``threshold`` consecutive failures) → half-open
    (one probe after ``open_s``) → closed (probe succeeded) / open
    (probe failed). Thread-safe; ``on_transition(new_state)`` runs
    outside the lock (it counts metrics)."""

    def __init__(self, threshold: int = 3, open_s: float = 5.0,
                 on_transition: Optional[Callable[[str], None]] = None):
        self.threshold = max(1, threshold)
        self.open_s = open_s
        self.on_transition = on_transition
        self._lock = threading.Lock()
        self._state = BREAKER_CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probe_inflight = False
        self._probe_at = 0.0
        self._transitions = 0
        # bounded transition timeline: the hysteresis PROPERTY (no
        # half-open probe before the cooldown elapsed) is asserted off
        # this by the breaker_flap chaos scenario
        self._history: Deque[Tuple[float, str]] = deque(maxlen=64)

    def state(self, now: Optional[float] = None) -> str:
        with self._lock:
            return self._state_locked(time.monotonic()
                                      if now is None else now)

    def _state_locked(self, now: float) -> str:
        if (self._state == BREAKER_OPEN
                and now - self._opened_at >= self.open_s):
            self._set_locked(BREAKER_HALF_OPEN)
        if (self._state == BREAKER_HALF_OPEN and self._probe_inflight
                and now - self._probe_at >= self.open_s):
            # the probe's stream was sent but NEVER answered — the
            # wedged-member gray failure itself. Without this bound the
            # breaker sits half-open forever with the probe consumed
            # (no failure, no success), keeping the member in election
            # while every stream fails fast. An unanswered probe IS a
            # failure: re-open with a fresh cooldown.
            self._probe_inflight = False
            self._opened_at = now
            self._set_locked(BREAKER_OPEN)
        return self._state

    def available(self, now: Optional[float] = None) -> bool:
        """Election gate (non-consuming): False only while OPEN inside
        the cooldown. Half-open reads available so the next attempt can
        be the probe — a member behind a broken wire leaves the
        handoff-target / fetch-source pool for exactly the cooldown."""
        return self.state(now) != BREAKER_OPEN

    def try_acquire(self, now: Optional[float] = None) -> bool:
        """Attempt gate (consuming): closed admits; half-open admits ONE
        probe (further attempts fail fast until it resolves); open
        inside the cooldown fails fast."""
        now = time.monotonic() if now is None else now
        with self._lock:
            state = self._state_locked(now)
            if state == BREAKER_CLOSED:
                return True
            if state == BREAKER_HALF_OPEN and not self._probe_inflight:
                self._probe_inflight = True
                self._probe_at = now
                return True
            return False

    def release(self) -> None:
        """Un-consume a ``try_acquire`` whose attempt never actually ran
        (e.g. the stream window rejected it after the probe was taken) —
        without this, an unused probe would wedge half-open forever."""
        with self._lock:
            self._probe_inflight = False

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._probe_inflight = False
            if self._state != BREAKER_CLOSED:
                self._set_locked(BREAKER_CLOSED)

    def record_failure(self, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        with self._lock:
            state = self._state_locked(now)
            self._failures += 1
            self._probe_inflight = False
            if state == BREAKER_HALF_OPEN or (
                    state == BREAKER_CLOSED
                    and self._failures >= self.threshold):
                self._opened_at = now
                self._set_locked(BREAKER_OPEN)

    def _set_locked(self, state: str) -> None:
        if state == self._state:
            return
        self._state = state
        self._transitions += 1
        self._history.append((time.monotonic(), state))
        cb = self.on_transition
        if cb is not None:
            # fire-and-forget outside the caller's critical section is
            # not possible without dropping the lock; the callback is a
            # counter bump (metrics), safe under it
            try:
                cb(state)
            except Exception:  # noqa: BLE001 — observability isolation
                logger.debug("breaker transition callback failed",
                             exc_info=True)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "state": self._state,
                "consecutive_failures": self._failures,
                "transitions": self._transitions,
            }

    def history(self) -> List[Tuple[float, str]]:
        """(monotonic time, state entered) transition timeline (bounded
        at 64) — what the chaos harness asserts hysteresis against."""
        with self._lock:
            return list(self._history)


# ---------------------------------------------------------------------------
# Shared retry budget
# ---------------------------------------------------------------------------


class RetryBudget:
    """A windowed budget shared by every retry amplifier on the host:
    crash-safe redispatch, the disagg handoff retry loop, and KV
    data-channel reconnects. Allows at most
    ``max(min_retries, ratio × admits_in_window)`` retries per trailing
    window — a sick fleet serving N requests/s cannot generate more
    than ~ratio·N retries/s of extra load on top. Denial is not an
    error: every consumer falls back to its existing exactly-once
    degradation (sink failure / decode-in-place / recompute)."""

    def __init__(self, ratio: float = 0.1, min_retries: int = 3,
                 window_s: float = 10.0, metrics=None):
        self.ratio = ratio
        self.min_retries = min_retries
        self.window_s = window_s
        self.metrics = metrics
        self._lock = threading.Lock()
        self._admits: Deque[Tuple[float, int]] = deque()
        self._retries: Deque[float] = deque()
        self._denied = 0

    def note_admit(self, n: int = 1, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        with self._lock:
            self._admits.append((now, n))
            self._prune_locked(now)

    def acquire(self, site: str, now: Optional[float] = None) -> bool:
        """Take one retry from the budget; False = budget exhausted (the
        caller must degrade, not retry). Denials count into
        ``retry_budget_exhausted_total{site}``."""
        now = time.monotonic() if now is None else now
        with self._lock:
            self._prune_locked(now)
            admits = sum(n for _, n in self._admits)
            allowed = max(self.min_retries,
                          int(math.floor(self.ratio * admits)))
            if len(self._retries) >= allowed:
                self._denied += 1
                denied = True
            else:
                self._retries.append(now)
                denied = False
        if denied and self.metrics is not None:
            self.metrics.record_retry_denied(site)
        return not denied

    def _prune_locked(self, now: float) -> None:
        cutoff = now - self.window_s
        while self._admits and self._admits[0][0] < cutoff:
            self._admits.popleft()
        while self._retries and self._retries[0] < cutoff:
            self._retries.popleft()

    def stats(self) -> Dict[str, Any]:
        now = time.monotonic()
        with self._lock:
            self._prune_locked(now)
            admits = sum(n for _, n in self._admits)
            return {
                "window_admits": admits,
                "window_retries": len(self._retries),
                "allowed": max(self.min_retries,
                               int(math.floor(self.ratio * admits))),
                "denied_total": self._denied,
            }


# ---------------------------------------------------------------------------
# Deadline-aware admission
# ---------------------------------------------------------------------------


class AdmissionControl:
    """Shed-at-admission decision (docs/RESILIENCE.md "Gray failures
    and overload"): compare the windowed queue-wait estimate against
    the request's SLO-derived deadline, weight-scaled per tenant for
    brownout ordering. Called on the submit path, so the estimate is
    cached briefly — shedding must stay O(µs) under exactly the load
    that triggers it."""

    _CACHE_S = 0.25

    def __init__(self, settings: Optional[AdmissionSettings] = None,
                 slo: Optional[SloSettings] = None,
                 metrics=None,
                 tenant_weights: Optional[Mapping[str, float]] = None):
        self.settings = settings or AdmissionSettings()
        self.slo = slo
        self.metrics = metrics
        self.tenant_weights = dict(tenant_weights or {})
        self._w_max = max(self.tenant_weights.values(), default=1.0)
        self._w_max = max(self._w_max, 1.0)  # unlisted tenants weigh 1
        self._lock = threading.Lock()
        self._cached_at = 0.0
        self._cached_estimate: Optional[float] = None
        self._shed_total = 0

    # -- deadline ------------------------------------------------------------

    def deadline_ms(self, tenant: str) -> float:
        """The tenant's admission deadline; 0 = no deadline (shedding
        off for this tenant). Explicit ``admission.deadline_ms`` wins;
        otherwise the applicable TTFT objective × deadline_factor."""
        if self.settings.deadline_ms > 0:
            return self.settings.deadline_ms
        if self.slo is None:
            return 0.0
        ttft_ms, _ = self.slo.limits_for(tenant)
        return ttft_ms * self.settings.deadline_factor if ttft_ms else 0.0

    # -- estimator -----------------------------------------------------------

    def queue_wait_estimate_ms(self,
                               now: Optional[float] = None
                               ) -> Optional[float]:
        """Windowed queue-wait p90 (ms) from the ``queue_wait_ms``
        digest (serving/teledigest.py — the same series /server/perf
        serves), or None while the window holds fewer than
        ``admission.min_window_requests`` samples (a cold estimator
        never sheds). Cached ~250 ms: overload is exactly when this is
        called most."""
        if self.metrics is None:
            return None
        now = time.monotonic() if now is None else now
        with self._lock:
            if now - self._cached_at < self._CACHE_S:
                return self._cached_estimate
        perf = self.metrics.perf_store()
        stats = window_stats(perf.wire_digest("queue_wait_ms"),
                             perf.window_s)
        estimate = None
        if stats.get("count", 0) >= self.settings.min_window_requests:
            estimate = stats.get("p90")
        with self._lock:
            self._cached_at = now
            self._cached_estimate = estimate
        return estimate

    # -- the decision ---------------------------------------------------------

    def check(self, tenant: str) -> Optional[AdmissionShed]:
        """Returns the AdmissionShed to raise, or None to admit.
        Brownout ordering: tenant weight ``w`` sheds at
        ``estimate > deadline × w / w_max`` — the lowest-weight tenants
        shed first as the backlog grows, the heaviest only when its own
        deadline is genuinely blown (reason "deadline" vs "brownout")."""
        if not self.settings.shed_enabled:
            return None
        deadline = self.deadline_ms(tenant)
        if deadline <= 0:
            return None
        estimate = self.queue_wait_estimate_ms()
        if estimate is None:
            return None
        threshold = deadline
        if self.settings.brownout:
            w = self.tenant_weights.get(tenant, 1.0)
            threshold = deadline * min(1.0, w / self._w_max)
        if estimate <= threshold:
            return None
        reason = "deadline" if estimate > deadline else "brownout"
        retry_after = min(self.settings.retry_after_cap_s,
                          max(1.0, math.ceil(estimate / 1000.0)))
        with self._lock:
            self._shed_total += 1
        return AdmissionShed(reason, retry_after, estimate, deadline)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            shed = self._shed_total
            estimate = self._cached_estimate
        return {
            "shed_total": shed,
            "queue_wait_estimate_ms": (round(estimate, 3)
                                       if estimate is not None else None),
        }


# ---------------------------------------------------------------------------
# Latency-scored health
# ---------------------------------------------------------------------------


class _EngineHealth:
    """Per-engine hysteresis state (scorer-thread-owned)."""

    __slots__ = ("state", "bad", "good", "reasons", "since",
                 "last_progress", "progress_t", "seen_progress",
                 "last_queued")

    def __init__(self) -> None:
        self.state = HEALTH_HEALTHY
        self.bad = 0
        self.good = 0
        self.reasons: Tuple[str, ...] = ()
        self.since = time.monotonic()
        # wedge tracking: last observed step-clock dispatch count and
        # when it last moved; seen_progress gates the detector until
        # the engine has dispatched at least once (a cold replica
        # mid-compile must never read as wedged); last_queued restarts
        # the stall clock on the idle→busy transition (idle time is not
        # stall time — a warm engine picking up work after a quiet hour
        # must get the full stall_s before it reads as wedged)
        self.last_progress = -1.0
        self.progress_t = time.monotonic()
        self.seen_progress = False
        self.last_queued = 0


class HealthScorer:
    """Demotes engines healthy → degraded → ejected on telemetry
    evidence, with two-sided hysteresis; routing consumes the verdicts
    via ``stamp()`` (serving/scheduler.py health tiering).

    Thread-shape: ``evaluate`` runs on the scorer thread (or a test
    driver); ``stamp``/``state`` are read from the dispatcher thread
    (one dict lookup per engine against a snapshot replaced atomically);
    wire-failure counters are read off the runners (GIL-atomic ints
    maintained by their own threads)."""

    def __init__(self, settings: Optional[HealthSettings] = None,
                 scheduler=None, metrics=None,
                 telemetry_fn: Optional[Callable[[], Dict[str, Any]]] = None,
                 recorder=None):
        """``telemetry_fn`` (registry hosts: the FleetServer's
        ``telemetry_snapshot``) supplies per-member digest frames for
        the latency comparison; None = local-only (wedge + wire signals
        still run). ``recorder`` (serving/flightrec.py): transitions
        land in the global fleet-event window, so a request's timeline
        shows "the replica was demoted mid-flight"."""
        self.settings = settings or HealthSettings()
        self.scheduler = scheduler
        self.metrics = metrics
        self.telemetry_fn = telemetry_fn
        self.recorder = recorder
        self._lock = threading.Lock()
        self._engines: Dict[str, _EngineHealth] = {}
        # engine_id -> state, replaced wholesale per evaluation; read
        # lock-free by stamp() (dict replace is GIL-atomic)
        self._snapshot: Dict[str, str] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- routing consumption (any thread) ------------------------------------

    def state(self, engine_id: str) -> str:
        return self._snapshot.get(engine_id, HEALTH_HEALTHY)

    def stamp(self, statuses: List) -> List:
        """Overlay health verdicts onto an EngineStatus snapshot
        (AdaptiveScheduler.statuses). Healthy engines pass through
        unchanged — the common case allocates nothing."""
        import dataclasses

        snap = self._snapshot
        if not snap:
            return statuses
        out = []
        for s in statuses:
            state = snap.get(s.engine_id, HEALTH_HEALTHY)
            out.append(s if state == HEALTH_HEALTHY
                       else dataclasses.replace(s, health=state))
        return out

    # -- evaluation (scorer thread) ------------------------------------------

    def evaluate(self, now: Optional[float] = None) -> List[Tuple[str, str, str]]:
        """One scoring pass; returns the transitions applied as
        ``(engine_id, old, new)``."""
        now = time.monotonic() if now is None else now
        if self.scheduler is None:
            return []
        runners = self.scheduler.engines()
        latency_bad = self._latency_verdicts()
        transitions: List[Tuple[str, str, str]] = []
        live_ids = set()
        with self._lock:
            for runner in runners:
                eid = runner.engine_id
                live_ids.add(eid)
                eh = self._engines.get(eid)
                if eh is None:
                    eh = self._engines[eid] = _EngineHealth()
                reasons, hold = self._signals(runner, eh, latency_bad, now)
                transition = self._hysteresis_locked(eid, eh, reasons,
                                                     hold)
                if transition is not None:
                    transitions.append(transition)
            pruned = [eid for eid in self._engines if eid not in live_ids]
            for eid in pruned:
                del self._engines[eid]  # unregistered engine
            self._snapshot = {
                eid: eh.state for eid, eh in self._engines.items()
                if eh.state != HEALTH_HEALTHY
            }
        for eid, old, new in transitions:
            logger.warning("engine %s health: %s -> %s", eid, old, new)
            if self.metrics is not None:
                self.metrics.record_health_transition(eid, new)
            if self.recorder is not None:
                self.recorder.note_global("health_transition",
                                          engine=eid, old=old, new=new)
        if self.metrics is not None:
            for eid in pruned:
                # restarted fleet members mint fresh proxy ids — dead
                # engines must not grow the gauge label set forever
                self.metrics.remove_engine_health(eid)
        return transitions

    def _signals(self, runner, eh: _EngineHealth,
                 latency_bad: Dict[str, str],
                 now: float) -> Tuple[List[str], bool]:
        """The bad-evidence reasons for one engine this evaluation
        (empty = clean) plus a hold flag: True = the latency signal sits
        inside the hysteresis band (above recover_ratio, below
        latency_ratio), so NEITHER streak advances — that band is the
        two-sided hysteresis that keeps a borderline replica from
        flapping. Eject-class evidence is prefixed ``eject:``."""
        reasons: List[str] = []
        eid = runner.engine_id
        # wire: consecutive control-wire send failures (RemoteRunner
        # counts them; local runners have no wire) or the member's KV
        # data channel breaker being open
        wire_fails = getattr(runner, "consecutive_wire_failures", 0)
        if wire_fails >= self.settings.wire_failures:
            reasons.append("eject:wire_failures")
        channel = getattr(runner, "kv_channel", None)
        if channel is not None:
            breaker = getattr(channel, "breaker", None)
            if breaker is not None and breaker.state() == BREAKER_OPEN:
                reasons.append("kv_breaker_open")
        # wedge: the step clock stopped while work is queued. Remote
        # proxies have no local step clock — their wedge shows up as
        # latency through the telemetry comparison instead.
        if not getattr(runner, "is_remote", False):
            try:
                status = runner.status()
            except Exception:  # noqa: BLE001 — status must not kill scoring
                logger.debug("health: status() of %s failed", eid,
                             exc_info=True)
                status = None
            if status is not None:
                progress = self._progress(eid)
                if progress != eh.last_progress:
                    eh.last_progress = progress
                    eh.progress_t = now
                    eh.seen_progress = eh.seen_progress or progress > 0
                queued = status.active_requests + status.waiting_requests
                if queued > 0 and eh.last_queued == 0:
                    # idle→busy: the stall clock starts when work
                    # ARRIVES — counting the idle gap would eject a
                    # healthy warm engine the moment it picks up work
                    eh.progress_t = max(eh.progress_t, now)
                eh.last_queued = queued
                if (eh.seen_progress and queued > 0
                        and now - eh.progress_t > self.settings.stall_s):
                    reasons.append("eject:stalled")
        # latency: the member (or the local process) far above the
        # fleet median
        verdict = latency_bad.get(self._source_of(eid))
        if verdict == "bad":
            reasons.append("latency")
        return reasons, verdict == "band" and not reasons

    def _progress(self, engine_id: str) -> float:
        """Cumulative step-clock dispatch count for one local engine
        (the wedge detector's progress signal)."""
        if self.metrics is None:
            return 0.0
        prefix = f"step.{engine_id}."
        total = 0.0
        for name, value in self.metrics.perf_store().counters().items():
            if name.startswith(prefix) and name.endswith(".dispatches"):
                total += value
        return total

    @staticmethod
    def _source_of(engine_id: str) -> str:
        """Latency-comparison source key: remote proxies group by their
        member id (``<member>:<engine>``), local engines under
        ``local`` (one process = one ttft_ms digest)."""
        if ":" in engine_id:
            return engine_id.rsplit(":", 1)[0]
        return "local"

    def _latency_verdicts(self) -> Dict[str, str]:
        """source -> "bad" | "band" per evaluation, from the windowed
        TTFT/TBT p99s: a source is **bad** when its p99 exceeds
        ``latency_ratio`` × the median of the OTHER sources' p99s,
        clean only below ``recover_ratio`` × it, and **band** (neither
        streak advances) in between — the two-sided hysteresis."""
        p99s: Dict[str, Dict[str, float]] = {}
        min_n = self.settings.min_window_requests
        if self.metrics is not None:
            perf = self.metrics.perf_store()
            local = self._series_p99s(
                {"ttft_ms": perf.wire_digest("ttft_ms"),
                 "tbt_ms": perf.wire_digest("tbt_ms")},
                perf.window_s, min_n)
            if local:
                p99s["local"] = local
        if self.telemetry_fn is not None:
            try:
                members = self.telemetry_fn()
            except Exception:  # noqa: BLE001 — telemetry is advisory
                logger.debug("health: telemetry snapshot failed",
                             exc_info=True)
                members = {}
            window_s = (self.metrics.perf_window_s()
                        if self.metrics is not None else 60.0)
            for member, frame in members.items():
                digests = frame.get("digests", {})
                vals = self._series_p99s(
                    {"ttft_ms": digests.get("ttft_ms", {}),
                     "tbt_ms": digests.get("tbt_ms", {})},
                    window_s, min_n)
                if vals:
                    p99s[member] = vals
        out: Dict[str, str] = {}
        if len(p99s) < 2:
            return out  # a median needs another source to compare to
        for source, vals in p99s.items():
            for series, p99 in vals.items():
                if series == "tbt_ms":
                    # tbt is member-vs-member only: the host's tbt_ms
                    # digest is CLIENT-observed — it includes
                    # remote-served streams' wire-bursty gaps, so using
                    # it as a source (or a baseline) would demote the
                    # host for a slow member's traffic. TTFT has no
                    # such bleed: each process digests only requests it
                    # served (metrics.record_ttft local=).
                    if source == "local":
                        continue
                    others = [v[series] for s, v in p99s.items()
                              if s not in (source, "local")
                              and series in v]
                else:
                    others = [v[series] for s, v in p99s.items()
                              if s != source and series in v]
                if not others:
                    continue
                baseline = statistics.median(others)
                if baseline <= 0:
                    continue
                if p99 > self.settings.latency_ratio * baseline:
                    out[source] = "bad"
                    break
                if p99 > self.settings.recover_ratio * baseline:
                    out.setdefault(source, "band")
        return out

    @staticmethod
    def _series_p99s(wires: Dict[str, Dict[str, Any]], window_s: float,
                     min_n: int) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for series, wire in wires.items():
            if not wire:
                continue
            stats = window_stats(wire, window_s)
            if stats.get("count", 0) >= min_n and "p99" in stats:
                out[series] = stats["p99"]
        return out

    def _hysteresis_locked(self, eid: str, eh: _EngineHealth,
                           reasons: List[str], hold: bool
                           ) -> Optional[Tuple[str, str, str]]:
        """Two-sided hysteresis: ``demote_after`` consecutive bad
        evaluations move one level down (eject-class evidence targets
        EJECTED directly), ``recover_after`` clean ones move one level
        up, and a ``hold`` evaluation (latency in the band between
        recover_ratio and latency_ratio) advances neither streak.
        Returns the transition applied, if any."""
        if hold:
            return None
        if reasons:
            eh.bad += 1
            eh.good = 0
            eh.reasons = tuple(reasons)
        else:
            eh.good += 1
            eh.bad = 0
        old = eh.state
        new = old
        if eh.bad >= self.settings.demote_after:
            target = (HEALTH_EJECTED
                      if any(r.startswith("eject:") for r in reasons)
                      else HEALTH_DEGRADED)
            new = HEALTH_STATES[min(health_rank(target),
                                    health_rank(old) + 1)]
            if health_rank(target) > health_rank(new):
                # eject-class evidence steps through degraded first but
                # keeps the streak alive so the next bad evaluation
                # completes the ejection without a fresh demote_after
                eh.bad = self.settings.demote_after - 1
            else:
                eh.bad = 0
        elif eh.good >= self.settings.recover_after and old != HEALTH_HEALTHY:
            new = HEALTH_STATES[health_rank(old) - 1]
            eh.good = 0
        if new == old:
            return None
        eh.state = new
        eh.since = time.monotonic()
        if new == HEALTH_HEALTHY:
            eh.reasons = ()
        return (eid, old, new)

    # -- introspection (any thread) ------------------------------------------

    def stats(self) -> Dict[str, Any]:
        """The ``health`` block of ``/server/stats``."""
        now = time.monotonic()
        with self._lock:
            engines = {
                eid: {
                    "state": eh.state,
                    "reasons": list(eh.reasons),
                    "for_s": round(now - eh.since, 3),
                }
                for eid, eh in sorted(self._engines.items())
            }
        return {"engines": engines}

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        # lifecycle handle  # distlint: ignore[DL008]
        self._thread = threading.Thread(
            target=self._loop, name="health-scorer", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.settings.interval_s):
            try:
                self.evaluate()
            except Exception:  # noqa: BLE001 — scoring must stay alive
                logger.exception("health evaluation failed; retrying")
