"""Server configuration: file < env < CLI precedence, validation, hot-reload.

Realizes the reference's spec'd config system (S8, ``tasks.md:226-240``
[spec]; behavior ``requirements.md:142-146``):

- **Sources & precedence** (Property 26, design.md:836-840): TOML or YAML
  file, overridden by ``DIS_TPU_*`` environment variables, overridden by
  CLI flags — CLI > env > file > defaults.
- **Validation** (Property 27, design.md:842-846): range checks on load;
  the CLI entry point exits non-zero on invalid values.
- **Hot-reload** (requirements.md:146): a watcher thread polls the config
  file's mtime; on change the *hot-reloadable* subset — batching window and
  size, queue watermarks, scheduling strategy — is re-applied to the running
  server via subscriber callbacks. Everything else needs a restart.

Env naming: ``DIS_TPU_<SECTION>__<FIELD>`` (double underscore between
section and field), e.g. ``DIS_TPU_QUEUE__HIGH_WATERMARK=1500``,
``DIS_TPU_SERVER__PORT=9000``.
"""

from __future__ import annotations

import argparse
import copy
import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from distributed_inference_server_tpu.core.errors import ConfigError
from distributed_inference_server_tpu.core.queue import QueueConfig
from distributed_inference_server_tpu.core.validator import ValidatorConfig
from distributed_inference_server_tpu.serving.batcher import BatcherConfig
from distributed_inference_server_tpu.serving.scheduler import SchedulingStrategy

logger = logging.getLogger(__name__)

ENV_PREFIX = "DIS_TPU_"

# section -> field -> (type, default)
_SCHEMA: Dict[str, Dict[str, Any]] = {
    "server": {
        "host": (str, "0.0.0.0"),
        "port": (int, 8000),
        # gRPC transport next to HTTP (serving/grpc_server.py); 0 = off
        "grpc_port": (int, 0),
        # persistent XLA compilation cache: restarts (and hot-swaps back
        # to a previously-served model) skip the 20-40s compiles. "" = off
        "compile_cache_dir": (
            str, "~/.cache/distributed-inference-server-tpu/xla"
        ),
        "num_engines": (int, 1),
        # disaggregated prefill/decode serving (serving/disagg.py;
        # docs/DISAGG.md): comma-separated role per replica, e.g.
        # "prefill,decode" for num_engines=2. "" = all unified (the
        # monolithic default). Validated against num_engines and for
        # nonsensical topologies (decode with no prefill and vice versa).
        "engine_roles": (str, ""),
        "strategy": (str, "least_loaded"),
        "auto_restart": (bool, True),
        "health_check_interval_s": (float, 1.0),
        # failed auto-restarts back off exponentially (jittered, capped)
        # instead of retrying every health sweep (docs/RESILIENCE.md)
        "restart_backoff_s": (float, 1.0),
        "restart_backoff_max_s": (float, 30.0),
        # crash-safe redispatch budget: how many times a zero-token
        # in-flight request may be moved off a dead engine before it
        # fails to its client; 0 = off (docs/RESILIENCE.md)
        "max_redispatch": (int, 2),
        "drain_timeout_s": (float, 30.0),
    },
    "model": {
        "model_dir": (str, ""),
        "model_name": (str, "tiny"),
        "dtype": (str, "bfloat16"),
        # weight-only quantization: none | int8 | int4 (ops/quant.py; the
        # reference's GGUF quantization levels, design.md:324-332 [spec])
        "quantization": (str, "none"),
        # speculative decoding (Req 12): a draft model configured on the
        # server enables speculation inside the serving engine
        "draft_model_name": (str, ""),
        "draft_model_dir": (str, ""),
    },
    "engine": {
        "tensor_parallel": (int, 1),
        # pipeline stages (parallel/pp.py) and context-parallel ring-
        # prefill width (parallel/cp.py) — per-replica mesh axes alongside
        # tensor_parallel; a replica owns tensor*stage*seq devices
        "pipeline_parallel": (int, 1),
        "pp_microbatches": (int, 1),
        "context_parallel": (int, 1),
        # prompts at least this long take the ring-prefill path when
        # context_parallel > 1 (0 = auto: one past the largest bucket)
        "cp_min_tokens": (int, 0),
        # sequence-parallel attention flavor: ring | ulysses
        "sp_impl": (str, "ring"),
        # continuous-batching decode slots per replica (the north star
        # needs 64-256; 64 measured best on one v5e chip, BENCH r2)
        "max_batch": (int, 64),
        "prefill_buckets": (list, [32, 128, 512]),
        "page_size": (int, 16),
        "num_pages": (int, 2048),
        "max_pages_per_seq": (int, 512),
        # decode-block pipelining (engine/engine.py): device steps (or
        # speculative rounds) per compiled block, and blocks in flight
        "decode_block_size": (int, 8),
        "pipeline_depth": (int, 1),
        "prefill_batch": (int, 16),
        "prefill_token_budget": (int, 8192),
        # ragged mixed-batch stepping (engine/engine.py; docs/PERF.md):
        # > 0 replaces the prefill-quantum + decode-block pair with ONE
        # dispatch over a packed batch of decode rows + prefill chunks
        # whenever prefill work is pending — flat TBT under prompt
        # bursts on a unified replica. The value is the TOTAL packed
        # width (decode slots + prefill budget) and must exceed
        # engine.max_batch. 0 = off (quantum-interleave baseline).
        "mixed_step_tokens": (int, 0),
        # run-to-completion decode blocks (engine/engine.py; docs/PERF.md
        # "Kernel Looping"): decode blocks carry an on-device page
        # free-list and keep stepping inside ONE compiled program until
        # EOS / budget / free-list exhaustion / loop_max_steps, instead
        # of returning to the host every decode_block_size tokens. Also
        # folds the mixed step into K-block form and lets speculation
        # compose with mixed_step_tokens.
        "loop_to_completion": (bool, False),
        # per-launch iteration cap for looped blocks — bounds how long a
        # runaway row can hold the device before admission runs again;
        # degradation rungs shrink the effective cap further
        "loop_max_steps": (int, 256),
        # speculative decoding knobs (Req 12.3-12.5)
        "num_draft_tokens": (int, 4),
        "spec_disable_threshold": (float, 0.5),
        # probation re-enable after auto-disable (Req 12.5 "per request
        # pattern"); <= 0 = stay disabled until an explicit reset
        "spec_reenable_after_s": (float, 30.0),
        # compile all serving programs before a replica reports ready
        "warmup_compile": (bool, True),
        # KV cache quantization: none | int8 (engine/kv_cache.py
        # QuantPool — half the KV HBM traffic, double the context
        # capacity; forces the XLA attention path)
        "kv_quant": (str, "none"),
    },
    "cache": {
        # host-RAM second tier of the prefix cache (docs/CACHING.md;
        # engine/kv_cache.py HostTier): LRU-evicted refcount-0 prefix
        # pages demote to a bounded host pool instead of dropping, and
        # prefix matching falls through HBM misses into it. 0 = off.
        # Pair with server.strategy=cache_aware so repeated-prefix
        # traffic routes to the replica whose tiers are already warm.
        "host_tier_bytes": (int, 0),
        # host-tier storage encoding for float pools: none | int8
        # (per-vector absmax codes + f32 scales — 4x smaller for f32
        # pools, bounded accuracy cost like disagg.wire_quant) |
        # latent | latent_int8 (rank-r latent page codes, needs
        # cache.latent_rank > 0 — docs/CACHING.md "Latent KV pages")
        "host_tier_quant": (str, "none"),
        # latent page codec rank (TPLA stage (a); docs/CACHING.md
        # "Latent KV pages"): per-(layer, kv-head) projection rank the
        # engine calibrates at construction. 0 = no codec; required > 0
        # by the latent/latent_int8 wire and tier encodings. Rule of
        # thumb: head_dim/4 holds greedy token identity on the models
        # benched so far at ~2.5× fewer bytes than int8.
        "latent_rank": (int, 0),
        # chain depth of the published routing digest (first-K page
        # hashes per cached chain): the cache_aware cost model can only
        # score — and peer-fetch — matches it can see, so deep shared
        # prefixes want a deeper digest (docs/CACHING.md); the price is
        # a bigger per-replica EngineStatus snapshot
        "digest_depth": (int, 8),
        # fleet-wide prefix sharing (docs/CACHING.md): let the
        # cache_aware router FETCH a matched prefix from a warm peer
        # onto a cold replica instead of queueing behind the warm one.
        # false = the pre-fetch two-way routing (warm | recompute).
        "peer_fetch": (bool, True),
        # cost-model weights (scheduler.FetchCosts), in pages of prefill
        # recompute: minimum fetchable gain worth a wire transfer,
        # wire cost per fetched page (< 1 or fetching never pays), and
        # the queueing penalty per active/waiting request on a replica
        "fetch_min_pages": (int, 2),
        "fetch_page_cost": (float, 0.25),
        "fetch_load_cost": (float, 4.0),
    },
    "disagg": {
        # migration budget per handoff: past the deadline (or after the
        # retries) the request decodes in place on its prefill engine
        "handoff_timeout_s": (float, 5.0),
        "handoff_retries": (int, 1),
        # transfer backend: "inproc" (zero-copy object pass) or
        # "protowire" (round-trips the KvHandoff protobuf framing —
        # the cross-process wire format, exercised in-process)
        "channel": (str, "inproc"),
        # streamed handoff (docs/DISAGG.md "Streaming handoff"): the
        # immutable prefix serializes in page-group chunks while the
        # sequence keeps decoding on the source; off = the monolithic
        # stop-the-world export (A/B baseline)
        "stream": (bool, True),
        "chunk_pages": (int, 8),
        # per-chunk wire encoding of float KV pools: none | int8
        # (per-vector absmax codes + f32 scales — halves-plus the bytes
        # moved, bounded accuracy cost; quantized pools pass through) |
        # latent | latent_int8 (rank-r latent page codes, needs
        # cache.latent_rank > 0 — several-fold fewer bytes than int8,
        # docs/CACHING.md "Latent KV pages")
        "wire_quant": (str, "none"),
    },
    "faults": {
        # fault injection (serving/faults.py; docs/RESILIENCE.md):
        # semicolon-separated "point:key=val,..." rules, e.g.
        # "disagg.chunk:nth=3;runner.step:prob=0.01". "" = disarmed (the
        # production default — injection points are a global load + None
        # check). Reachable via env as DIS_TPU_FAULTS__SPEC. Never arm
        # in production.
        "spec": (str, ""),
        "seed": (int, 0),
    },
    "tracing": {
        # OTLP/HTTP collector URL for span export (utils/otlp.py), e.g.
        # http://collector:4318/v1/traces; empty = in-memory ring only
        "otlp_endpoint": (str, ""),
        "service_name": (str, "distributed-inference-server-tpu"),
    },
    "distributed": {
        # multi-host data plane (parallel/distributed.py): every process
        # of the fleet runs the same config with its own process_id
        # (-1 = platform auto-detection); num_processes 1 = single host
        "coordinator_address": (str, ""),
        "num_processes": (int, 1),
        "process_id": (int, -1),
    },
    "queue": {
        "high_watermark": (int, 1000),
        "low_watermark": (int, 500),
        "request_timeout_s": (float, 30.0),
        "max_queue_size": (int, 2000),
        # per-tenant fair admission (core/queue.py; docs/FLEET.md):
        # deficit-weighted round robin across tenants within each
        # priority level, so one hot tenant cannot starve the fleet.
        # Requests carry a "tenant" field; absent = "default". Forces
        # the Python queue tier (the native tier has no tenant lanes).
        "tenant_fairness": (bool, False),
        # "tenantA=2,tenantB=1": relative dequeue weights; unlisted
        # tenants weigh 1. "" = all equal.
        "tenant_weights": (str, ""),
    },
    "fleet": {
        # multi-host fleet control plane (serving/fleet.py,
        # serving/remote_runner.py; docs/FLEET.md). enabled=true on the
        # REGISTRY HOST starts the fleet listener; a WORKER process sets
        # connect=host:port instead and joins by heartbeating.
        "enabled": (bool, False),
        "host": (str, "127.0.0.1"),
        "port": (int, 0),  # 0 = ephemeral (tests/smoke)
        "connect": (str, ""),
        "member_id": (str, ""),  # "" = derived hostname:pid
        "heartbeat_interval_s": (float, 0.5),
        # member aging: alive -> suspect after suspect_after_s without a
        # beat (routing avoids it), suspect -> dead after dead_after_s
        # (in-flight requests take the crash-safe redispatch path)
        "suspect_after_s": (float, 2.0),
        "dead_after_s": (float, 5.0),
        # dynamic role rebalancing (RoleBalancer): a unified engine
        # re-roles to prefill when queued+waiting prompts per admission
        # replica crosses rerole_high_ratio, and back below
        # rerole_low_ratio; the band plus rerole_cooldown_s between
        # flips is the hysteresis that stops role flapping
        "rerole": (bool, False),
        "rerole_high_ratio": (float, 4.0),
        "rerole_low_ratio": (float, 1.0),
        "rerole_cooldown_s": (float, 10.0),
        "rerole_interval_s": (float, 0.5),
        # fleet KV data plane (serving/fleet_kv.py; docs/FLEET.md "KV
        # data plane"): workers bind a KV data listener (kv_data_port;
        # 0 = ephemeral) advertised per heartbeat; the registry host
        # dials it lazily for cross-host handoff and peer prefix
        # fetch. kv_enabled=false keeps a worker control-plane-only
        # (no handoff target, no fetch source).
        "kv_enabled": (bool, True),
        "kv_data_port": (int, 0),
        # cost of moving one page from a REMOTE peer, in recompute-page
        # units (scheduler.FetchCosts.remote_page_cost): pricier than
        # cache.fetch_page_cost so the route/fetch/recompute decision
        # stays honest about the slower cross-host wire
        "kv_page_cost": (float, 0.6),
        # bounded in-flight bulk streams per member data channel; the
        # (N+1)th concurrent handoff/fetch fails fast to its local
        # fallback instead of queueing behind multi-MB transfers
        "kv_max_streams": (int, 4),
        "kv_connect_timeout_s": (float, 5.0),
        # member<->member KV mesh (serving/fleet_mesh.py; docs/FLEET.md
        # "KV mesh"): the registry brokers introductions (KvIntro
        # frames) and members dial each other's data listeners
        # directly, so fetch bytes scale with member count instead of
        # relaying through the registry host
        "mesh_enabled": (bool, False),
        # learned wire rates (MeshWireRates): observed chunk
        # bytes/seconds aggregate in a sliding window this wide; a
        # wire with no observation in the window is COLD and charges
        # kv_page_cost as the prior. kv_rate_prior is the byte rate
        # kv_page_cost is assumed to price (default ~1 Gbit/s) — the
        # learned cost is kv_page_cost * prior/learned, clamped.
        # kv_rate_prior=0 disables learned pricing (constant only).
        "kv_rate_window_s": (float, 30.0),
        "kv_rate_prior": (float, 125000000.0),
        # registry HA (serving/fleet_ha.py; docs/FLEET.md "Registry
        # HA"): the ORDERED endpoint list every fleet process agrees
        # on. On a registry host it must contain this host's own
        # host:port (list position breaks election ties); on a worker
        # it is the full set of registries to heartbeat (dual-
        # heartbeat keeps every standby's member table warm). Empty =
        # HA off (single-registry fleet, no behavior change).
        "registries": (tuple, []),
        # lease aging mirrors member aging: a standby treats the
        # primary as suspect after lease_suspect_s without a
        # RegistryLease frame and promotes (epoch+1) after lease_s
        "lease_s": (float, 3.0),
        "lease_suspect_s": (float, 1.5),
        # standby_http=true (default) keeps every registry's HTTP
        # ingress open — multi-ingress serving through any registry;
        # false gates /generate admission to the current primary
        "standby_http": (bool, True),
    },
    "health": {
        # gray-failure defense (serving/health.py HealthScorer;
        # docs/RESILIENCE.md "Gray failures and overload"): a periodic
        # scorer demotes engines healthy -> degraded -> ejected on
        # telemetry evidence (step-clock wedge, windowed p99 far above
        # the fleet median, repeated wire failures) with two-sided
        # hysteresis; routing deprioritizes degraded replicas and
        # excludes ejected ones while any alternative exists.
        "enabled": (bool, True),
        "interval_s": (float, 1.0),
        # wedge: no step-clock dispatch progress while work is queued
        # for this long (armed only after the engine dispatched once)
        "stall_s": (float, 5.0),
        # latency demotion band: bad above latency_ratio x the median
        # of the other sources' p99s, clean below recover_ratio x it
        "latency_ratio": (float, 3.0),
        "recover_ratio": (float, 1.5),
        # consecutive bad/clean evaluations to move one level down/up
        "demote_after": (int, 3),
        "recover_after": (int, 3),
        # windowed samples required before a latency verdict is trusted
        "min_window_requests": (int, 8),
        # consecutive wire failures ejecting a member's engines (also
        # the KV data channel breaker's closed -> open threshold)
        "wire_failures": (int, 3),
        # breaker open -> half-open probe delay
        "breaker_open_s": (float, 5.0),
        # shared retry budget (redispatch / handoff retry / kv
        # reconnect): retries per window as a fraction of admits,
        # floored at retry_budget_min
        "retry_budget_ratio": (float, 0.1),
        "retry_budget_min": (int, 3),
        "retry_window_s": (float, 10.0),
        # SLO burn-rate escalation input to the degradation ladder
        # (serving/degradation.py): burn >= slo_burn_high escalates to
        # REJECT_LOW_PRIORITY (>= half of it to REDUCED_BATCH_SIZE)
        # once the window holds slo_burn_min_requests verdicts
        "slo_burn_high": (float, 0.5),
        "slo_burn_min_requests": (int, 20),
    },
    "admission": {
        # deadline-aware admission shedding (serving/health.py
        # AdmissionControl): requests shed AT ADMISSION — 503 +
        # Retry-After + the distinct admission_shed code — when the
        # windowed queue-wait estimate already blows their deadline,
        # instead of queueing doomed work toward queue_timeout.
        "shed_enabled": (bool, True),
        # explicit deadline (ms); 0 = derive from the applicable
        # (per-tenant) slo.ttft_ms objective
        "deadline_ms": (float, 0.0),
        # deadline = deadline_factor x the applicable TTFT objective
        "deadline_factor": (float, 1.0),
        # brownout ordering on the DRR weights (queue.tenant_weights):
        # tenant weight w sheds at estimate > deadline * w / w_max, so
        # the lowest-weight tenants brown out first
        "brownout": (bool, True),
        # cold-estimator guard: no shedding until the window holds this
        # many queue-wait samples
        "min_window_requests": (int, 8),
        "retry_after_cap_s": (float, 30.0),
    },
    "slo": {
        # SLO / goodput accounting (serving/teledigest.py SloSettings;
        # docs/OBSERVABILITY.md "Performance telemetry"): request-level
        # latency objectives. 0 = that objective unset (requests with
        # no applicable objective get no verdict and never count
        # toward the burn rate). flightrec.finish() derives the verdict
        # from the exact phase partition; violations feed
        # slo_requests_total{tenant,verdict} and the windowed burn rate
        # at GET /server/perf.
        "ttft_ms": (float, 0.0),
        "tbt_p99_ms": (float, 0.0),
        # per-tenant overrides, "tenantA=500,tenantB=250" (ms); an
        # override wins over the global objective for that tenant
        "tenant_ttft_ms": (str, ""),
        "tenant_tbt_ms": (str, ""),
        # windowed-digest geometry shared by /server/perf percentiles,
        # the /server/stats sliding p99, and SLO burn rates: epochs of
        # epoch_s seconds, percentiles over the trailing window_s
        "window_s": (float, 60.0),
        "epoch_s": (float, 5.0),
    },
    "batcher": {
        "window_ms": (float, 50.0),
        "max_batch_size": (int, 32),
    },
    "validator": {
        "max_context_tokens": (int, 8192),
        "max_output_tokens": (int, 4096),
    },
}

# (section, field) pairs that may change at runtime without restart
HOT_RELOADABLE = {
    ("batcher", "window_ms"),
    ("batcher", "max_batch_size"),
    ("queue", "high_watermark"),
    ("queue", "low_watermark"),
    ("queue", "request_timeout_s"),
    ("server", "strategy"),
}


def parse_tenant_weights(spec: str,
                         key: str = "queue.tenant_weights",
                         allow_zero: bool = False) -> Dict[str, float]:
    """Parse a ``"tenantA=2,tenantB=1"`` map — ``queue.tenant_weights``
    (core/queue.py DRR weights) and the per-tenant SLO overrides
    (``slo.tenant_ttft_ms``/``slo.tenant_tbt_ms``, milliseconds) share
    the grammar. Raises ConfigError (attributed to ``key``) on
    malformed entries or out-of-range values. ``allow_zero`` (the SLO
    maps): 0 is a legal override meaning "objective unset for this
    tenant" — the only way to exempt one tenant from a global
    objective; a DRR weight of 0 stays illegal (it would starve the
    tenant entirely)."""
    out: Dict[str, float] = {}
    floor = -1.0 if allow_zero else 0.0
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        name, sep, value = part.partition("=")
        name = name.strip()
        if not sep or not name:
            raise ConfigError(
                f"{key}: {part!r} is not tenant=value"
            )
        try:
            weight = float(value)
        except ValueError:
            raise ConfigError(
                f"{key}: value {value!r} for {name!r} is not a number"
            ) from None
        if weight <= floor:
            raise ConfigError(
                f"{key}: value for {name!r} must be "
                + (">= 0 (0 = objective unset)" if allow_zero
                   else "positive")
            )
        out[name] = weight
    return out


def _defaults() -> Dict[str, Dict[str, Any]]:
    return {
        sec: {k: copy.copy(d) for k, (_, d) in fields.items()}
        for sec, fields in _SCHEMA.items()
    }


def _coerce(section: str, key: str, value: Any) -> Any:
    try:
        typ, _ = _SCHEMA[section][key]
    except KeyError:
        raise ConfigError(f"unknown config key: {section}.{key}") from None
    if typ is bool:
        if isinstance(value, bool):
            return value
        if isinstance(value, str):
            low = value.strip().lower()
            if low in ("1", "true", "yes", "on"):
                return True
            if low in ("0", "false", "no", "off"):
                return False
        raise ConfigError(f"{section}.{key}: expected boolean, got {value!r}")
    if typ is list:
        if isinstance(value, (list, tuple)):
            return [int(v) for v in value]
        if isinstance(value, str):
            return [int(v) for v in value.split(",") if v.strip()]
        raise ConfigError(f"{section}.{key}: expected list, got {value!r}")
    if typ is tuple:
        # string list (e.g. fleet.registries): a YAML/TOML list or a
        # comma-separated string ("hostA:7070,hostB:7070") — the latter
        # is how env/CLI overrides spell it
        if isinstance(value, (list, tuple)):
            return [str(v) for v in value]
        if isinstance(value, str):
            return [v.strip() for v in value.split(",") if v.strip()]
        raise ConfigError(
            f"{section}.{key}: expected list of strings, got {value!r}"
        )
    try:
        return typ(value)
    except (TypeError, ValueError):
        raise ConfigError(
            f"{section}.{key}: expected {typ.__name__}, got {value!r}"
        ) from None


def _load_file(path: str) -> Dict[str, Any]:
    if path.endswith((".yaml", ".yml")):
        import yaml

        with open(path) as f:
            obj = yaml.safe_load(f) or {}
    elif path.endswith(".toml"):
        from distributed_inference_server_tpu.utils.compat import load_toml

        obj = load_toml(path)
    else:
        raise ConfigError(f"unsupported config format: {path} (use .toml/.yaml)")
    if not isinstance(obj, dict):
        raise ConfigError(f"config file {path} must contain a table/mapping")
    return obj


def _env_overrides(environ: Optional[Dict[str, str]] = None) -> Dict[str, Dict[str, Any]]:
    environ = os.environ if environ is None else environ
    out: Dict[str, Dict[str, Any]] = {}
    for name, raw in environ.items():
        if not name.startswith(ENV_PREFIX):
            continue
        rest = name[len(ENV_PREFIX):]
        if "__" not in rest:
            continue
        section, key = rest.split("__", 1)
        out.setdefault(section.lower(), {})[key.lower()] = raw
    return out


@dataclass
class ServerConfig:
    """Typed view over the merged section/field table."""

    raw: Dict[str, Dict[str, Any]] = field(default_factory=_defaults)
    source_file: Optional[str] = None
    # kept so hot-reload re-merges with the SAME CLI overrides (Property 26
    # must survive reloads, not just initial load)
    cli_args: List[str] = field(default_factory=list)

    # -- construction ------------------------------------------------------

    @classmethod
    def load(
        cls,
        file_path: Optional[str] = None,
        cli_args: Optional[List[str]] = None,
        environ: Optional[Dict[str, str]] = None,
    ) -> "ServerConfig":
        """Merge defaults < file < env < CLI (Property 26), then validate
        (Property 27)."""
        merged = _defaults()

        def apply(section: str, key: str, value: Any) -> None:
            if section not in merged or key not in merged[section]:
                raise ConfigError(f"unknown config key: {section}.{key}")
            merged[section][key] = _coerce(section, key, value)

        cli = _parse_cli(cli_args or [])
        # always pop the file key — the apply loop must see only
        # (section, key) tuples, even when file_path was passed directly
        # (hot-reload re-merges with the original --config in cli_args)
        cli_file = cli.pop("_config_file", None)
        file_path = file_path or cli_file

        if file_path:
            for section, fields in _load_file(file_path).items():
                if not isinstance(fields, dict):
                    raise ConfigError(f"config section {section} must be a table")
                for key, value in fields.items():
                    apply(str(section), str(key), value)
        for section, fields in _env_overrides(environ).items():
            for key, value in fields.items():
                apply(section, key, value)
        for (section, key), value in cli.items():
            apply(section, key, value)

        cfg = cls(raw=merged, source_file=file_path,
                  cli_args=list(cli_args or []))
        cfg.validate()
        return cfg

    # -- access ------------------------------------------------------------

    def get(self, section: str, key: str) -> Any:
        return self.raw[section][key]

    def queue_config(self) -> QueueConfig:
        q = self.raw["queue"]
        return QueueConfig(
            high_watermark=q["high_watermark"],
            low_watermark=q["low_watermark"],
            request_timeout_s=q["request_timeout_s"],
            max_queue_size=q["max_queue_size"],
            tenant_fairness=q["tenant_fairness"],
            tenant_weights=parse_tenant_weights(q["tenant_weights"]),
        )

    def batcher_config(self) -> BatcherConfig:
        b = self.raw["batcher"]
        return BatcherConfig(
            window_ms=b["window_ms"], max_batch_size=b["max_batch_size"]
        )

    def validator_config(self) -> ValidatorConfig:
        v = self.raw["validator"]
        return ValidatorConfig(
            max_context_tokens=v["max_context_tokens"],
            max_output_tokens=v["max_output_tokens"],
        )

    def strategy(self) -> SchedulingStrategy:
        return SchedulingStrategy.parse(self.raw["server"]["strategy"])

    def engine_roles(self):
        """Validated per-replica role list (serving/disagg.py). Fleet
        membership (registry host OR joined worker) relaxes the
        single-sided-topology checks — the counterpart role may live on
        another member, reachable over the KV data plane."""
        from distributed_inference_server_tpu.serving.disagg import (
            parse_roles,
        )

        f = self.raw["fleet"]
        return parse_roles(self.raw["server"]["engine_roles"],
                           self.raw["server"]["num_engines"],
                           fleet=bool(f["enabled"] or f["connect"]))

    def disagg_settings(self):
        from distributed_inference_server_tpu.serving.disagg import (
            DisaggSettings,
        )

        d = self.raw["disagg"]
        return DisaggSettings(
            handoff_timeout_s=d["handoff_timeout_s"],
            handoff_retries=d["handoff_retries"],
            channel=d["channel"],
            stream=d["stream"],
            chunk_pages=d["chunk_pages"],
            wire_quant=d["wire_quant"],
        )

    def fleet_settings(self):
        """Fleet control-plane knobs (serving/fleet.py FleetSettings)."""
        from distributed_inference_server_tpu.serving.fleet import (
            FleetSettings,
        )

        f = self.raw["fleet"]
        return FleetSettings(
            enabled=f["enabled"],
            host=f["host"],
            port=f["port"],
            connect=f["connect"],
            member_id=f["member_id"],
            heartbeat_interval_s=f["heartbeat_interval_s"],
            suspect_after_s=f["suspect_after_s"],
            dead_after_s=f["dead_after_s"],
            rerole=f["rerole"],
            rerole_high_ratio=f["rerole_high_ratio"],
            rerole_low_ratio=f["rerole_low_ratio"],
            rerole_cooldown_s=f["rerole_cooldown_s"],
            rerole_interval_s=f["rerole_interval_s"],
            kv_enabled=f["kv_enabled"],
            kv_data_port=f["kv_data_port"],
            kv_max_streams=f["kv_max_streams"],
            kv_connect_timeout_s=f["kv_connect_timeout_s"],
            mesh_enabled=f["mesh_enabled"],
            kv_rate_window_s=f["kv_rate_window_s"],
            kv_rate_prior=f["kv_rate_prior"],
            registries=tuple(f["registries"]),
            lease_s=f["lease_s"],
            lease_suspect_s=f["lease_suspect_s"],
            standby_http=f["standby_http"],
        )

    def slo_settings(self):
        """SLO / performance-telemetry knobs (teledigest.SloSettings);
        always constructed — the window/epoch geometry shapes the
        /server/perf digests even with no objective set."""
        from distributed_inference_server_tpu.serving.teledigest import (
            SloSettings,
        )

        s = self.raw["slo"]
        return SloSettings(
            ttft_ms=s["ttft_ms"],
            tbt_p99_ms=s["tbt_p99_ms"],
            tenant_ttft_ms=parse_tenant_weights(
                s["tenant_ttft_ms"], key="slo.tenant_ttft_ms",
                allow_zero=True),
            tenant_tbt_ms=parse_tenant_weights(
                s["tenant_tbt_ms"], key="slo.tenant_tbt_ms",
                allow_zero=True),
            window_s=s["window_s"],
            epoch_s=s["epoch_s"],
        )

    def health_settings(self):
        """Gray-failure defense knobs (serving/health.py
        HealthSettings; docs/RESILIENCE.md)."""
        from distributed_inference_server_tpu.serving.health import (
            HealthSettings,
        )

        h = self.raw["health"]
        return HealthSettings(
            enabled=h["enabled"],
            interval_s=h["interval_s"],
            stall_s=h["stall_s"],
            latency_ratio=h["latency_ratio"],
            recover_ratio=h["recover_ratio"],
            demote_after=h["demote_after"],
            recover_after=h["recover_after"],
            min_window_requests=h["min_window_requests"],
            wire_failures=h["wire_failures"],
            breaker_open_s=h["breaker_open_s"],
            retry_budget_ratio=h["retry_budget_ratio"],
            retry_budget_min=h["retry_budget_min"],
            retry_window_s=h["retry_window_s"],
            slo_burn_high=h["slo_burn_high"],
            slo_burn_min_requests=h["slo_burn_min_requests"],
        )

    def admission_settings(self):
        """Deadline-aware admission knobs (serving/health.py
        AdmissionSettings)."""
        from distributed_inference_server_tpu.serving.health import (
            AdmissionSettings,
        )

        a = self.raw["admission"]
        return AdmissionSettings(
            shed_enabled=a["shed_enabled"],
            deadline_ms=a["deadline_ms"],
            deadline_factor=a["deadline_factor"],
            brownout=a["brownout"],
            min_window_requests=a["min_window_requests"],
            retry_after_cap_s=a["retry_after_cap_s"],
        )

    def fetch_costs(self):
        """cache_aware three-way cost-model weights (fleet prefix
        sharing, serving/scheduler.py plan_route)."""
        from distributed_inference_server_tpu.serving.scheduler import (
            FetchCosts,
        )

        c = self.raw["cache"]
        return FetchCosts(
            enabled=c["peer_fetch"],
            min_pages=c["fetch_min_pages"],
            page_cost=c["fetch_page_cost"],
            load_cost_pages=c["fetch_load_cost"],
            # cross-host wire rate (fleet KV data plane,
            # serving/fleet_kv.py): the fleet section owns it because
            # it prices the fleet wire, not the cache policy
            remote_page_cost=self.raw["fleet"]["kv_page_cost"],
            wire_frac=self._wire_frac(),
        )

    def _wire_frac(self) -> float:
        """Encoded bytes-per-page fraction of the configured fetch wire
        (kv_cache.encoded_page_fraction): the cost model charges what
        the wire actually moves — int8 is ~3.2× fewer bytes than f32
        raw, latent several-fold fewer still. Falls back to 1.0 when
        the model geometry is not resolvable from the config (custom
        checkpoint dirs) or the pool is natively quantized (QuantPool
        codes pass through whatever the wire setting)."""
        wq = self.raw["disagg"]["wire_quant"]
        if wq == "none" or self.raw["engine"]["kv_quant"] != "none":
            return 1.0
        try:
            from distributed_inference_server_tpu.engine.kv_cache import (
                encoded_page_fraction,
            )
            from distributed_inference_server_tpu.models.configs import (
                get_config,
            )

            head_dim = get_config(self.raw["model"]["model_name"]).head_dim
            itemsize = {"float32": 4, "bfloat16": 2,
                        "float16": 2}[self.raw["model"]["dtype"]]
            return encoded_page_fraction(
                wq, itemsize, head_dim, self.raw["cache"]["latent_rank"]
            )
        except Exception as e:  # noqa: BLE001 — cost scaling is best-effort
            logger.debug("wire_frac: cannot resolve model geometry for "
                         "%r (%s); charging raw pages", wq, e)
            return 1.0

    # -- validation --------------------------------------------------------

    def validate(self) -> None:
        """Range checks (Property 27); raises ConfigError."""
        r = self.raw

        def positive(section: str, key: str) -> None:
            if r[section][key] <= 0:
                raise ConfigError(f"{section}.{key} must be positive")

        for sec, key in (
            ("server", "port"), ("server", "num_engines"),
            ("engine", "tensor_parallel"),
            ("engine", "pipeline_parallel"), ("engine", "pp_microbatches"),
            ("engine", "context_parallel"),
            ("engine", "max_batch"), ("engine", "page_size"),
            ("engine", "num_pages"), ("engine", "max_pages_per_seq"),
            ("queue", "high_watermark"), ("queue", "low_watermark"),
            ("queue", "request_timeout_s"), ("queue", "max_queue_size"),
            ("batcher", "max_batch_size"),
            ("validator", "max_context_tokens"),
            ("validator", "max_output_tokens"),
        ):
            positive(sec, key)
        if not (0 < r["server"]["port"] < 65536):
            raise ConfigError("server.port must be in (0, 65536)")
        if r["queue"]["low_watermark"] >= r["queue"]["high_watermark"]:
            raise ConfigError(
                "queue.low_watermark must be below queue.high_watermark"
            )
        if r["queue"]["high_watermark"] > r["queue"]["max_queue_size"]:
            raise ConfigError(
                "queue.high_watermark must be <= queue.max_queue_size"
            )
        if r["batcher"]["window_ms"] < 0:
            raise ConfigError("batcher.window_ms must be >= 0")
        if r["engine"]["mixed_step_tokens"] < 0:
            raise ConfigError("engine.mixed_step_tokens must be >= 0")
        if (0 < r["engine"]["mixed_step_tokens"]
                <= r["engine"]["max_batch"]):
            raise ConfigError(
                "engine.mixed_step_tokens must exceed engine.max_batch "
                "(the packed width holds every decode slot plus at "
                "least one prefill token)"
            )
        if r["engine"]["loop_max_steps"] < 1:
            raise ConfigError("engine.loop_max_steps must be >= 1")
        if not r["engine"]["prefill_buckets"]:
            raise ConfigError("engine.prefill_buckets must be non-empty")
        if sorted(r["engine"]["prefill_buckets"]) != r["engine"]["prefill_buckets"]:
            raise ConfigError("engine.prefill_buckets must be ascending")
        try:
            SchedulingStrategy.parse(r["server"]["strategy"])
        except ValueError:
            raise ConfigError(
                f"server.strategy must be one of "
                f"{[s.value for s in SchedulingStrategy]}, "
                f"got {r['server']['strategy']!r}"
            ) from None
        if r["model"]["dtype"] not in ("bfloat16", "float32", "float16"):
            raise ConfigError(
                f"model.dtype must be bfloat16/float32/float16, "
                f"got {r['model']['dtype']!r}"
            )
        if r["engine"]["sp_impl"] not in ("ring", "ulysses"):
            raise ConfigError(
                f"engine.sp_impl must be ring/ulysses, "
                f"got {r['engine']['sp_impl']!r}"
            )
        if r["model"]["quantization"] not in ("none", "int8", "int4"):
            raise ConfigError(
                f"model.quantization must be none/int8/int4, "
                f"got {r['model']['quantization']!r}"
            )
        # disaggregated serving: roles parse + topology sanity
        # (decode-with-no-prefill etc.) live in disagg.parse_roles
        self.engine_roles()
        if r["disagg"]["handoff_timeout_s"] <= 0:
            raise ConfigError("disagg.handoff_timeout_s must be positive")
        if r["disagg"]["handoff_retries"] < 0:
            raise ConfigError("disagg.handoff_retries must be >= 0")
        if r["disagg"]["channel"] not in ("inproc", "protowire"):
            raise ConfigError(
                f"disagg.channel must be inproc/protowire, "
                f"got {r['disagg']['channel']!r}"
            )
        if r["disagg"]["chunk_pages"] <= 0:
            raise ConfigError("disagg.chunk_pages must be positive")
        if r["disagg"]["wire_quant"] not in ("none", "int8", "latent",
                                             "latent_int8"):
            raise ConfigError(
                f"disagg.wire_quant must be none/int8/latent/latent_int8, "
                f"got {r['disagg']['wire_quant']!r}"
            )
        if r["server"]["max_redispatch"] < 0:
            raise ConfigError("server.max_redispatch must be >= 0")
        if r["server"]["restart_backoff_s"] <= 0:
            raise ConfigError("server.restart_backoff_s must be positive")
        if (r["server"]["restart_backoff_max_s"]
                < r["server"]["restart_backoff_s"]):
            raise ConfigError(
                "server.restart_backoff_max_s must be >= "
                "server.restart_backoff_s"
            )
        if r["faults"]["spec"]:
            from distributed_inference_server_tpu.serving.faults import (
                FaultSpecError,
                parse_spec,
            )

            try:
                parse_spec(r["faults"]["spec"], r["faults"]["seed"])
            except FaultSpecError as e:
                raise ConfigError(f"faults.spec: {e}") from None
        if r["cache"]["host_tier_bytes"] < 0:
            raise ConfigError("cache.host_tier_bytes must be >= 0")
        if r["cache"]["host_tier_quant"] not in ("none", "int8", "latent",
                                                 "latent_int8"):
            raise ConfigError(
                f"cache.host_tier_quant must be none/int8/latent/"
                f"latent_int8, got {r['cache']['host_tier_quant']!r}"
            )
        if r["cache"]["latent_rank"] < 0:
            raise ConfigError("cache.latent_rank must be >= 0")
        if r["cache"]["latent_rank"] == 0:
            for key, section in (("disagg.wire_quant", r["disagg"]["wire_quant"]),
                                 ("cache.host_tier_quant",
                                  r["cache"]["host_tier_quant"])):
                if section in ("latent", "latent_int8"):
                    raise ConfigError(
                        f"{key}={section!r} needs cache.latent_rank > 0 "
                        "(the engine has no codec to encode with)"
                    )
        if r["cache"]["digest_depth"] <= 0:
            raise ConfigError("cache.digest_depth must be positive")
        if r["cache"]["fetch_min_pages"] < 1:
            raise ConfigError("cache.fetch_min_pages must be >= 1")
        if r["cache"]["fetch_page_cost"] < 0:
            raise ConfigError("cache.fetch_page_cost must be >= 0")
        if r["cache"]["fetch_load_cost"] < 0:
            raise ConfigError("cache.fetch_load_cost must be >= 0")
        # per-tenant fairness: weights parse + positivity
        parse_tenant_weights(r["queue"]["tenant_weights"])
        # SLO / performance telemetry (serving/teledigest.py)
        s = r["slo"]
        if s["ttft_ms"] < 0:
            raise ConfigError("slo.ttft_ms must be >= 0 (0 = unset)")
        if s["tbt_p99_ms"] < 0:
            raise ConfigError("slo.tbt_p99_ms must be >= 0 (0 = unset)")
        parse_tenant_weights(s["tenant_ttft_ms"],
                             key="slo.tenant_ttft_ms", allow_zero=True)
        parse_tenant_weights(s["tenant_tbt_ms"],
                             key="slo.tenant_tbt_ms", allow_zero=True)
        if s["epoch_s"] <= 0:
            raise ConfigError("slo.epoch_s must be positive")
        if s["window_s"] < s["epoch_s"]:
            raise ConfigError(
                "slo.window_s must be >= slo.epoch_s (the window is a "
                "whole number of epochs)"
            )
        # gray-failure defense (serving/health.py)
        h = r["health"]
        for key in ("interval_s", "stall_s", "breaker_open_s",
                    "retry_window_s"):
            if h[key] <= 0:
                raise ConfigError(f"health.{key} must be positive")
        for key in ("demote_after", "recover_after", "wire_failures",
                    "retry_budget_min", "min_window_requests",
                    "slo_burn_min_requests"):
            if h[key] < 1:
                raise ConfigError(f"health.{key} must be >= 1")
        if h["recover_ratio"] <= 1.0:
            raise ConfigError("health.recover_ratio must exceed 1.0")
        if h["latency_ratio"] <= h["recover_ratio"]:
            raise ConfigError(
                "health.latency_ratio must exceed health.recover_ratio "
                "(the two-sided hysteresis band)"
            )
        if not (0.0 <= h["retry_budget_ratio"] <= 1.0):
            raise ConfigError(
                "health.retry_budget_ratio must be in [0, 1]"
            )
        if not (0.0 < h["slo_burn_high"] <= 1.0):
            raise ConfigError("health.slo_burn_high must be in (0, 1]")
        a = r["admission"]
        if a["deadline_ms"] < 0:
            raise ConfigError(
                "admission.deadline_ms must be >= 0 (0 = derive from "
                "the TTFT SLO)"
            )
        if a["deadline_factor"] <= 0:
            raise ConfigError("admission.deadline_factor must be positive")
        if a["min_window_requests"] < 1:
            raise ConfigError(
                "admission.min_window_requests must be >= 1"
            )
        if a["retry_after_cap_s"] < 1:
            raise ConfigError("admission.retry_after_cap_s must be >= 1")
        # fleet control plane (serving/fleet.py)
        f = r["fleet"]
        if f["heartbeat_interval_s"] <= 0:
            raise ConfigError("fleet.heartbeat_interval_s must be positive")
        if f["suspect_after_s"] <= f["heartbeat_interval_s"]:
            raise ConfigError(
                "fleet.suspect_after_s must exceed "
                "fleet.heartbeat_interval_s (one missed beat is jitter, "
                "not suspicion)"
            )
        if f["dead_after_s"] <= f["suspect_after_s"]:
            raise ConfigError(
                "fleet.dead_after_s must exceed fleet.suspect_after_s"
            )
        if not (0 <= f["port"] < 65536):
            raise ConfigError("fleet.port must be in [0, 65536)")
        if f["connect"]:
            from distributed_inference_server_tpu.serving.fleet import (
                parse_connect,
            )

            parse_connect(f["connect"])
        if f["rerole_low_ratio"] >= f["rerole_high_ratio"]:
            raise ConfigError(
                "fleet.rerole_low_ratio must be below "
                "fleet.rerole_high_ratio (the hysteresis band)"
            )
        if f["rerole_low_ratio"] < 0:
            raise ConfigError("fleet.rerole_low_ratio must be >= 0")
        if f["rerole_cooldown_s"] < 0:
            raise ConfigError("fleet.rerole_cooldown_s must be >= 0")
        if f["rerole_interval_s"] <= 0:
            raise ConfigError("fleet.rerole_interval_s must be positive")
        # fleet KV data plane (serving/fleet_kv.py)
        if not (0 <= f["kv_data_port"] < 65536):
            raise ConfigError("fleet.kv_data_port must be in [0, 65536)")
        if f["kv_page_cost"] < 0:
            raise ConfigError("fleet.kv_page_cost must be >= 0")
        if f["kv_max_streams"] < 1:
            raise ConfigError("fleet.kv_max_streams must be >= 1")
        if f["kv_connect_timeout_s"] <= 0:
            raise ConfigError(
                "fleet.kv_connect_timeout_s must be positive"
            )
        # KV mesh learned wire costs (serving/fleet_mesh.py)
        if f["kv_rate_window_s"] <= 0:
            raise ConfigError("fleet.kv_rate_window_s must be positive")
        if f["kv_rate_prior"] < 0:
            raise ConfigError(
                "fleet.kv_rate_prior must be >= 0 (0 disables learned "
                "pricing)"
            )
        # registry HA (serving/fleet_ha.py)
        if f["registries"]:
            from distributed_inference_server_tpu.serving.fleet import (
                parse_connect,
            )

            for ep in f["registries"]:
                try:
                    parse_connect(ep)
                except Exception:
                    raise ConfigError(
                        f"fleet.registries: {ep!r} is not a host:port "
                        "endpoint"
                    ) from None
            if f["lease_suspect_s"] <= f["heartbeat_interval_s"]:
                raise ConfigError(
                    "fleet.lease_suspect_s must exceed "
                    "fleet.heartbeat_interval_s (one missed lease beat "
                    "is jitter, not a dead primary)"
                )
            if f["lease_s"] <= f["lease_suspect_s"]:
                raise ConfigError(
                    "fleet.lease_s must exceed fleet.lease_suspect_s"
                )

    def hot_diff(self, other: "ServerConfig") -> Dict[tuple, Any]:
        """(section, key) -> new value for hot-reloadable keys that differ."""
        out = {}
        for section, key in HOT_RELOADABLE:
            new = other.raw[section][key]
            if self.raw[section][key] != new:
                out[(section, key)] = new
        return out


def _parse_cli(argv: List[str]) -> Dict[Any, Any]:
    """CLI flags: ``--config FILE`` plus ``--<section>-<field>`` per schema
    entry (clap-equivalent surface, Cargo.toml:45)."""
    parser = argparse.ArgumentParser(
        prog="distributed-inference-server-tpu",
        description="TPU-native LLM inference server",
    )
    parser.add_argument("--config", dest="_config_file", default=None,
                        help="TOML/YAML config file")
    for section, fields in _SCHEMA.items():
        for key in fields:
            parser.add_argument(
                f"--{section}-{key}".replace("_", "-"),
                dest=f"{section}.{key}",
                default=None,
            )
    ns = vars(parser.parse_args(argv))
    out: Dict[Any, Any] = {}
    cfg_file = ns.pop("_config_file")
    if cfg_file:
        out["_config_file"] = cfg_file
    for dotted, value in ns.items():
        if value is None:
            continue
        section, key = dotted.split(".", 1)
        out[(section, key)] = value
    return out


class ConfigWatcher:
    """Polls the config file; publishes hot-reloadable changes to
    subscribers (requirements.md:146 watch-channel analogue)."""

    def __init__(self, config: ServerConfig, poll_interval_s: float = 1.0):
        self.current = config
        self._interval = poll_interval_s
        self._subs: List[Callable[[Dict[tuple, Any], ServerConfig], None]] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._mtime = self._stat()

    def subscribe(
        self, callback: Callable[[Dict[tuple, Any], ServerConfig], None]
    ) -> None:
        self._subs.append(callback)

    def _stat(self) -> float:
        path = self.current.source_file
        if not path:
            return 0.0
        try:
            return os.stat(path).st_mtime
        except OSError:
            return 0.0

    def check_once(self) -> bool:
        """Reload if the file changed; returns True if a reload happened.
        Invalid new config is rejected (old config stays active)."""
        path = self.current.source_file
        if not path:
            return False
        mtime = self._stat()
        if mtime == self._mtime:
            return False
        try:
            # re-merge with the original CLI args so CLI > env > file
            # precedence survives the reload (Property 26)
            new = ServerConfig.load(file_path=path,
                                    cli_args=self.current.cli_args)
        except Exception as e:  # noqa: BLE001 — malformed/partial file edits
            # (toml parse errors, ENOENT during atomic replace) must
            # never kill hot-reload; the old config stays active. The
            # recorded mtime is NOT advanced on failure: if the writer
            # completes within the same mtime tick (coarse filesystem
            # timestamps), the next poll still retries instead of
            # treating the torn snapshot as current forever
            logger.warning("config hot-reload: %s rejected (%s); keeping "
                           "the active config", path, e)
            return False
        self._mtime = mtime
        diff = self.current.hot_diff(new)
        self.current = new
        if diff:
            for cb in self._subs:
                try:
                    cb(diff, new)
                except Exception:  # noqa: BLE001 — subscriber isolation
                    logger.exception(
                        "config hot-reload subscriber %r failed; other "
                        "subscribers still run", cb,
                    )
        return True

    def start(self) -> None:
        if self._thread is not None or not self.current.source_file:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="config-watcher", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                self.check_once()
            except Exception:  # noqa: BLE001 — watcher must stay alive
                logger.exception("config watcher poll failed; retrying")
