"""Remote engine runners: the registry-host proxy and the worker-side
executor of the fleet control plane (serving/fleet.py; docs/FLEET.md).

``RemoteRunner`` satisfies the ``EngineRunner`` surface the serving
spine routes on — submit / abort / status / active_count / audit /
shutdown — by forwarding over the ``FleetSubmit`` / ``FleetEvent`` RPC
pair (serving/inference.proto, protowire codec): a submit becomes one
FleetSubmit frame per request on the member's session, and the session
reader pumps FleetEvent frames back into the request's ResultSink. The
scheduler cannot tell it from a local runner, so every existing policy
— strategies, role restriction, the cache_aware cost model scoring the
member's heartbeated digest — routes the federated fleet unchanged.

Remote death maps onto the existing crash-safe redispatch path
(docs/RESILIENCE.md ``_fail_all_of`` semantics): when the member goes
dead (missed beats or connection loss) the proxy pops each in-flight
request FIRST (exactly-once by construction), then zero-token requests
re-dispatch exactly once through ``Dispatcher.redispatch`` while
mid-stream requests fail fast with the distinct ``engine_crashed``
code. A remote-side ``worker_failure`` event for a zero-token request
is treated the same way — the remote fleet couldn't save it, this one
still can.

``FleetWorker`` is the other end: a worker process dials the registry
host, heartbeats its full ``EngineStatus`` replica set (digests
included), executes incoming FleetSubmit frames against its LOCAL
runners through a sink that encodes FleetEvent frames back, and
reconnects with backoff when the registry host bounces. The
``fleet.submit`` fault point fires on both ends: on the proxy it models
the forwarded submit dying on the wire, on the worker it models the
member crashing on receipt (connection dropped, nothing served).
"""

from __future__ import annotations

import dataclasses
import logging
import socket
import threading
import time
from collections import deque
from typing import (Any, Callable, Deque, Dict, List, Optional, Sequence,
                    Tuple)

from distributed_inference_server_tpu.core.models import FinishReason, Usage
from distributed_inference_server_tpu.engine.engine import SamplingParams
from distributed_inference_server_tpu.serving import faults
from distributed_inference_server_tpu.serving.fleet import (
    FleetSettings,
    MEMBER_ALIVE,
    MEMBER_DEAD,
    parse_connect,
    recv_frame,
    send_frame,
    span_to_wire,
    status_to_wire,
)
from distributed_inference_server_tpu.serving.metrics import (
    EngineStatus,
    MetricsCollector,
)
from distributed_inference_server_tpu.serving.runner import ServerRequest

logger = logging.getLogger(__name__)


class RemoteRunner:
    """Registry-host proxy for one engine on a remote fleet member.

    Thread-shape: ``submit``/``abort`` arrive from the dispatcher and
    redispatch paths, ``on_event`` from the member session's reader
    thread, ``detach`` from the reader/sweeper. The in-flight map uses
    the same GIL-atomic pop-first exactly-once protocol as EngineRunner
    (docs/RESILIENCE.md) — every terminal path pops before resolving."""

    #: capability markers the rest of the spine keys on: remote proxies
    #: are never health-loop restarted, never model-swapped, never
    #: KV-handoff targets, and never scale_to victims
    is_remote = True
    supports_restart = False

    def __init__(
        self,
        engine_id: str,
        local_engine_id: str,
        send: Callable[[str, Dict[str, Any]], None],
        metrics: Optional[MetricsCollector] = None,
        recorder=None,
    ):
        """``engine_id`` is the fleet-namespaced proxy id
        (``<member>:<engine>``); ``local_engine_id`` is what the member
        itself calls the engine (what FleetSubmit frames carry);
        ``send(name, obj)`` writes one frame on the member session and
        raises when the transport is gone. ``recorder`` is the host's
        FlightRecorder (serving/flightrec.py): a remote-served request's
        token/terminal instants land in its host-side timeline here —
        the proxy is where the host observes them."""
        self.engine_id = engine_id
        self.local_engine_id = local_engine_id
        self.metrics = metrics
        self.recorder = recorder
        self._send = send
        # wired by the FleetServer to Dispatcher.redispatch
        self.redispatch: Optional[Callable] = None
        # registry HA (serving/fleet_ha.py): the registry's control
        # epoch, stamped on every submit/abort frame so members can
        # fence a partitioned old primary. None/0 = unfenced (single-
        # registry fleets) — the field is simply omitted on the wire.
        self.epoch_fn: Optional[Callable[[], int]] = None
        # fleet KV data plane (serving/fleet_kv.py; docs/FLEET.md "KV
        # data plane"): the member's lazily-dialed data channel, set by
        # the FleetServer when the member advertises a data_port. None =
        # no data plane — this proxy is excluded from handoff targets
        # and fetch sources, the pre-data-plane behavior exactly.
        # Single-writer (the member session's refresh under its lock);
        # readers tolerate one stale check  # distlint: ignore[DL008]
        self.kv_channel = None
        # pop-first exactly-once protocol, GIL-atomic dict ops
        # (docs/RESILIENCE.md)  # distlint: ignore[DL008]
        self._inflight: Dict[Any, ServerRequest] = {}
        # serializes event delivery against failure: a partitioned-but-
        # alive member can stream a late token concurrently with the
        # sweeper failing/redispatching the same request — without this
        # lock the dead member's token and the redispatched copy's
        # stream could interleave on one sink
        self._events_lock = threading.Lock()
        self._status: Optional[EngineStatus] = None
        # liveness flags: GIL-atomic scalar writes from the session
        # reader and registry sweeper; readers (routing, status) tolerate
        # one stale check — the registry re-publishes every beat, and
        # dead is terminal for this proxy instance
        # distlint: ignore[DL008]
        self._member_state = MEMBER_ALIVE
        self._detached = False
        # distlint: ignore[DL008]
        self._last_error: Optional[str] = None
        self._total_processed = 0
        # consecutive control-wire send failures (reset on success):
        # the HealthScorer reads this as eject evidence once it crosses
        # health.wire_failures (serving/health.py). GIL-atomic int,
        # submit-path-owned  # distlint: ignore[DL008]
        self.consecutive_wire_failures = 0

    @property
    def role(self) -> str:
        s = self._status
        return s.role if s is not None else "unified"

    @property
    def supports_kv_import(self) -> bool:
        """True when the member's KV data channel is wired AND its
        circuit breaker is not open (serving/health.py): this proxy can
        then be a handoff TARGET (cross-host prefill→decode migration)
        and a peer-fetch SOURCE (serving/fleet_kv.py). An open breaker
        pulls the member out of election instead of letting every
        handoff discover the broken wire one failed stream at a time."""
        ch = self.kv_channel
        return ch is not None and ch.wire_available()

    # -- registry-side state (session reader / sweeper threads) ------------

    def update_status(self, status: EngineStatus) -> None:
        self._status = status

    def set_member_state(self, state: str) -> None:
        self._member_state = state

    def mark_detached(self, message: str) -> None:
        """Phase 1 of member death: drop out of the routing set
        (is_healthy goes False) WITHOUT failing anything yet — the
        session detaches every sibling proxy first so redispatch cannot
        pick another runner of the same dead member."""
        self._detached = True
        self._member_state = MEMBER_DEAD
        self._last_error = message

    def fail_inflight(self, message: str) -> None:
        """Phase 2: fail every in-flight request onto the crash-safe
        redispatch path. Exactly once per request — pop-first, and a
        detached proxy fails all later submits immediately."""
        self._fail_all_of(list(self._inflight.values()), message)

    def detach(self, message: str) -> None:
        """The member died (or left): both phases for a lone proxy."""
        self.mark_detached(message)
        self.fail_inflight(message)

    # -- EngineRunner surface ----------------------------------------------

    def is_healthy(self) -> bool:
        s = self._status
        return (not self._detached
                and self._member_state == MEMBER_ALIVE
                and s is not None and s.healthy)

    def status(self) -> EngineStatus:
        s = self._status
        if s is None:
            return EngineStatus(
                engine_id=self.engine_id, healthy=False, active_requests=0,
                waiting_requests=0, total_processed=0, remote=True,
            )
        # overlay liveness and THIS host's view of in-flight load: the
        # heartbeat is up to one interval stale, but requests this proxy
        # forwarded are known-inflight right now. data_plane marks the
        # member's KV data channel for the routing cost model
        # (scheduler.plan_route fetches only from data-plane peers).
        return dataclasses.replace(
            s, healthy=self.is_healthy(),
            active_requests=max(s.active_requests, len(self._inflight)),
            # breaker-aware: an open data-channel breaker drops this
            # member from fetch sources too (scheduler.plan_route)
            data_plane=self.supports_kv_import,
        )

    def active_count(self) -> int:
        return len(self._inflight)

    def last_error(self) -> Optional[str]:
        return self._last_error

    def audit(self, timeout_s: float = 0.0) -> List[str]:
        """Remote pools are audited by their own process; the proxy has
        no allocator to conserve."""
        return []

    def evict_cache(self, target_frac: float,
                    drop_host_tier: bool = False) -> None:
        """Degradation-ladder no-op: the member's own ladder manages its
        HBM pressure."""

    def tokenizer(self):
        return None

    def shutdown(self, timeout: float = 0.0) -> None:
        self.detach("fleet detach: registry host shutting down")

    def submit(self, requests: Sequence[ServerRequest],
               fetch_hint: Optional[Dict[str, Any]] = None) -> None:
        """``fetch_hint`` (docs/FLEET.md "KV mesh"): optional mesh
        fetch-delegation fields (fetch_member/fetch_source_engine/
        fetch_hashes/fetch_chunk_pages/fetch_wire_quant) merged into
        each FleetSubmit frame — the member pulls the warm prefix
        directly from the named peer before computing, degrading to
        plain recompute on any mesh failure."""
        reqs = list(requests)
        with self._events_lock:
            for r in reqs:
                self._inflight[r.request_id] = r
        if not self.is_healthy():
            self._fail_all_of(
                reqs, self._last_error or "fleet member unavailable")
            return
        epoch = self.epoch_fn() if self.epoch_fn is not None else 0
        try:
            for r in reqs:
                # forwarded submit dies on the wire (docs/RESILIENCE.md)
                faults.fire("fleet.submit")
                # the control wire wedges/times out on a send — repeated
                # hits are the HealthScorer's wire-failure eject
                # evidence (docs/RESILIENCE.md fleet.wire_timeout)
                faults.fire("fleet.wire_timeout")
                frame = {
                    "request_id": str(r.request_id),
                    "engine_id": self.local_engine_id,
                    "prompt_ids": [int(t) for t in r.prompt_ids],
                    "max_tokens": r.params.max_tokens,
                    "temperature": r.params.temperature,
                    "top_p": r.params.top_p,
                    "stop_sequences": list(r.params.stop_sequences),
                    "tenant": getattr(r, "tenant", "") or "",
                }
                if epoch:
                    frame["epoch"] = epoch
                if fetch_hint:
                    frame.update(fetch_hint)
                span = getattr(r, "span", None)
                if span is not None:
                    # trace context rides the wire: the member parents
                    # its fleet.serve span on it and ships the finished
                    # span back — one stitched cross-process trace
                    # (docs/OBSERVABILITY.md)
                    frame["trace_id"], frame["parent_span_id"] = \
                        span.context()
                self._send("FleetSubmit", frame)
            self.consecutive_wire_failures = 0
        except Exception as e:  # noqa: BLE001 — transport fault domain
            self._last_error = f"fleet submit failed: {e}"
            self.consecutive_wire_failures += 1
            # fail only THIS batch: already-sent requests are popped
            # first, so any events the member still streams for them are
            # dropped as orphans (the redispatched copy owns the sink)
            self._fail_all_of(reqs, self._last_error)

    def abort(self, request_id) -> None:
        with self._events_lock:
            self._inflight.pop(request_id, None)
        if self.kv_channel is not None:
            self.kv_channel.release_request(request_id)
        epoch = self.epoch_fn() if self.epoch_fn is not None else 0
        frame = {
            "request_id": str(request_id),
            "engine_id": self.local_engine_id,
            "abort": True,
        }
        if epoch:
            frame["epoch"] = epoch
        try:
            self._send("FleetSubmit", frame)
        except Exception as e:  # noqa: BLE001 — the member is dying
            # anyway; its requests die with it
            self._absorbed("abort_send", e)

    # -- fleet KV data plane (serving/fleet_kv.py) --------------------------
    #
    # The EngineRunner import/export surface the DisaggController and
    # PrefixFetcher drive, satisfied over the member's data channel.
    # Callback contracts match the local runner exactly: exactly once,
    # from the channel's reader thread — or here, when the channel is
    # missing/full/dead (the caller's fallback then runs immediately).

    def submit_prefix_export(self, request_id, hashes, chunk_pages: int,
                             wire_quant: str,
                             on_done: Callable, trace=None) -> None:
        """Peer-fetch SOURCE over the wire: the member's engine
        serializes its cached chain and streams it back as KvChunks."""
        ch = self.kv_channel
        if ch is None:
            on_done(None, "member has no kv data channel")
            return
        ch.fetch_prefix(request_id, self.local_engine_id, hashes,
                        chunk_pages, wire_quant, trace, on_done)

    def submit_import_open(self, request_id, prefix_pages: int, chunks,
                           on_done: Callable, wire_quant: str = "none",
                           trace=None) -> None:
        """Phase 1 of a cross-host streamed handoff: the prefix chunks
        ship while the source sequence keeps decoding; the member
        reserves pages and validates as they arrive."""
        ch = self.kv_channel
        if ch is None:
            on_done(False, "member has no kv data channel")
            return
        ch.import_open(request_id, self.local_engine_id, prefix_pages,
                       wire_quant, chunks, trace, on_done)

    def submit_import_commit(self, exp, req: ServerRequest,
                             on_done: Callable) -> None:
        """Phase 2: tail + host state cross the wire; on ok the member
        engine owns the sequence and its decode events ride the data
        channel back into this proxy's event pump."""
        self._submit_sequence("import_commit", exp, req, on_done)

    def submit_resume(self, exp, req: ServerRequest,
                      on_done: Callable) -> None:
        """Monolithic cross-host migration (same ownership contract as
        submit_import_commit)."""
        self._submit_sequence("resume", exp, req, on_done)

    def _submit_sequence(self, op: str, exp, req: ServerRequest,
                         on_done: Callable) -> None:
        """Shared commit/resume ownership contract: the request is
        registered in ``_inflight`` FIRST (so a channel death between
        the stream's ok and the first event still fails it exactly
        once), popped again on any failure arm — on_done fires exactly
        once either way."""
        ch = self.kv_channel
        with self._events_lock:
            self._inflight[req.request_id] = req
        if ch is None or not self.is_healthy():
            with self._events_lock:
                self._inflight.pop(req.request_id, None)
            on_done(False, self._last_error
                    or "member has no kv data channel")
            return

        def _done(ok: bool, err, _req=req) -> None:
            if not ok:
                with self._events_lock:
                    self._inflight.pop(_req.request_id, None)
            on_done(ok, err)

        span = getattr(req, "span", None)
        trace = span.context() if span is not None else None
        if op == "import_commit":
            ch.import_commit(exp, self.local_engine_id, trace, _done)
        else:
            ch.resume(exp, self.local_engine_id, trace, _done)

    def submit_import_abort(self, request_id) -> None:
        ch = self.kv_channel
        if ch is not None:
            ch.import_abort(request_id, self.local_engine_id)

    def fail_requests(self, request_ids, message: str) -> None:
        """Fail a specific set of in-flight requests (the data channel
        died under their event stream). Pop-first exactly-once like
        every other terminal path."""
        with self._events_lock:
            reqs = [self._inflight[rid] for rid in request_ids
                    if rid in self._inflight]
        self._fail_all_of(reqs, message)

    # -- event pump (member session reader thread) -------------------------

    def on_event(self, ev: Dict[str, Any]) -> None:
        rid = ev.get("request_id", "")
        kind = ev.get("kind")
        if kind == "error":
            # pop (the ownership transfer) under the events lock; the
            # resolution — which may REDISPATCH, i.e. acquire other
            # runners' state — runs outside it, so two dying members
            # redispatching onto each other can never hold-and-wait
            with self._events_lock:
                req = (None if self._detached
                       else self._inflight.pop(rid, None))
            if req is not None:
                self._resolve_error(req, ev.get("message", "remote error"),
                                    ev.get("code") or "inference_failed")
            return
        with self._events_lock:
            req = self._inflight.get(rid)
            if req is None or self._detached:
                return  # aborted / redispatched / dead: orphan event
            try:
                if kind == "token":
                    if req.first_token_at is None:
                        # single-owner handoff: a request is in exactly
                        # one runner's in-flight map (pop-first), and
                        # the events lock orders this write against
                        # _fail_all_of's ownership snapshot
                        # distlint: ignore[DL008]
                        req.first_token_at = time.monotonic()
                        if self.metrics:
                            # local=False: the member's OWN telemetry
                            # digest carries this request's TTFT — see
                            # record_ttft (double-count + scorer
                            # contamination otherwise)
                            self.metrics.record_ttft(
                                req.first_token_at - req.submitted_at,
                                local=False)
                    if ev.get("token_id") is not None:
                        if self.metrics:
                            self.metrics.record_tokens(1)
                        if self.recorder is not None:
                            self.recorder.token(rid)
                    req.sink.on_token(ev.get("token_id"),
                                      ev.get("text", ""),
                                      ev.get("token_index", 0),
                                      ev.get("logprob"))
                elif kind == "done":
                    if self._inflight.pop(rid, None) is None:
                        return
                    usage = Usage.of(ev.get("prompt_tokens", 0),
                                     ev.get("completion_tokens", 0))
                    try:
                        reason = FinishReason(
                            ev.get("finish_reason") or "stop")
                    except ValueError:
                        reason = FinishReason.STOP
                    self._total_processed += 1
                    if self.recorder is not None:
                        self.recorder.finish(rid, "ok")
                    req.sink.on_done(reason, usage)
            except Exception as e:  # noqa: BLE001 — sink isolation
                self._inflight.pop(rid, None)
                self._absorbed("sink_error", e)

    def _resolve_error(self, req: ServerRequest, message: str,
                       code: str) -> None:
        """A remote-side terminal error. A zero-token ``worker_failure``
        means the member's own fleet ran out of capacity — THIS fleet
        may still have some, so it takes the crash-safe redispatch path
        before the error reaches the client."""
        if (req.first_token_at is None and code == "worker_failure"
                and self.redispatch is not None):
            try:
                if self.redispatch(req, self.engine_id, message):
                    return  # the new owner resolves the sink
            except Exception as e:  # noqa: BLE001 — hook isolation
                self._absorbed("redispatch", e)
        if self.recorder is not None:
            self.recorder.finish(req.request_id, "error", code=code)
        try:
            req.sink.on_error(message, code)
        except Exception as e:  # noqa: BLE001
            self._absorbed("sink_error", e)

    # -- failure (same contract as EngineRunner._fail_all_of) --------------

    def _fail_all_of(self, reqs: Sequence[ServerRequest],
                     message: str) -> None:
        # ownership transfer under the events lock: once a request is
        # popped here, a late event from the (possibly still-streaming)
        # member can no longer reach its sink, and any token the member
        # DID deliver landed before the pop — so the first_token_at
        # snapshot below is the truth the redispatch decision needs.
        # Resolution runs OUTSIDE the lock (redispatch may touch other
        # runners — no cross-member hold-and-wait).
        owned = []
        with self._events_lock:
            for req in reqs:
                if self._inflight.pop(req.request_id, None) is None:
                    continue  # another terminal path owns it
                owned.append((req, req.first_token_at is None))
        for req, zero_tokens in owned:
            if zero_tokens and self.redispatch is not None:
                try:
                    if self.redispatch(req, self.engine_id, message):
                        continue  # the new owner resolves the sink
                except Exception as e:  # noqa: BLE001 — hook isolation
                    self._absorbed("redispatch", e)
            code = "worker_failure" if zero_tokens else "engine_crashed"
            if self.recorder is not None:
                self.recorder.finish(req.request_id, "error", code=code)
            try:
                req.sink.on_error(message, code)
            except Exception as e:  # noqa: BLE001
                self._absorbed("sink_error", e)

    def _absorbed(self, site: str, exc: BaseException) -> None:
        logger.debug("%s: absorbed error at %s: %s: %s", self.engine_id,
                     site, type(exc).__name__, exc)
        if self.metrics:
            self.metrics.record_error(f"remote_runner.{site}")


# ---------------------------------------------------------------------------
# Worker side: heartbeat + submit executor
# ---------------------------------------------------------------------------


class _RemoteSink:
    """ResultSink that encodes FleetEvent frames back to the registry
    host. Runs on the worker's engine-runner threads; send failures are
    absorbed — a dead registry connection means the host has already
    failed the request onto its redispatch path, so there is no one to
    tell. ``span`` is the worker-side ``fleet.serve`` span (parented on
    the wire's trace context); the sink owns finishing it — a finished
    span is what ships back to the host."""

    def __init__(self, worker: "FleetWorker", request_id: str,
                 engine_id: str, span=None, link=None):
        """``link`` (registry HA multi-ingress, serving/fleet_ha.py):
        the registry wire the submit ARRIVED on — events stream back on
        the same wire, so a request submitted through a standby's front
        door resolves on the standby. None = the primary link."""
        self._worker = worker
        self._rid = request_id
        self._eid = engine_id
        self._span = span
        self._link = link

    def _finish_span(self, status: str) -> None:
        span, self._span = self._span, None
        if span is not None and self._worker.tracer is not None:
            self._worker.tracer.finish(span, status=status)

    def _event(self, obj: Dict[str, Any]) -> None:
        obj["request_id"] = self._rid
        obj["engine_id"] = self._eid
        self._worker.send_event(obj, link=self._link)

    def on_token(self, token_id, text, token_index, logprob=None) -> None:
        ev = {"kind": "token", "text": text or "",
              "token_index": token_index or 0}
        if token_id is not None:
            ev["token_id"] = int(token_id)
        if logprob is not None:
            ev["logprob"] = float(logprob)
        self._event(ev)

    def on_done(self, finish_reason, usage) -> None:
        self._finish_span("ok")
        self._event({
            "kind": "done",
            "finish_reason": getattr(finish_reason, "value",
                                     str(finish_reason)),
            "prompt_tokens": getattr(usage, "prompt_tokens", 0),
            "completion_tokens": getattr(usage, "completion_tokens", 0),
        })

    def on_error(self, message, code) -> None:
        self._finish_span("error")
        self._event({"kind": "error", "message": message or "",
                     "code": code or "inference_failed"})


class _RegistryLink:
    """Per-registry connection state of a FleetWorker (registry HA
    dual-heartbeat, serving/fleet_ha.py): socket + send lock, the
    per-connection heartbeat sequence, and a per-link bounded span
    buffer (each registry must see every span — a shared buffer would
    ship each span to whichever link drained first). The FIRST link is
    the worker's legacy single wire: its fields are aliased by the
    worker's historical attributes and its frames route through
    ``FleetWorker._send``."""

    def __init__(self, endpoint: str, primary: bool):
        self.endpoint = endpoint
        self.primary = primary
        self.sock: Optional[socket.socket] = None
        # serializes frame writes: the link loop and every local
        # runner thread's _RemoteSink share the socket
        self.send_lock = threading.Lock()
        self.seq = 0
        self.span_buf: Deque = deque()
        self.span_lock = threading.Lock()
        self.span_dropped = 0
        self.thread: Optional[threading.Thread] = None
        self.reader: Optional[threading.Thread] = None


class FleetWorker:
    """Joins a fleet: dials every registry, heartbeats the local
    replica set to all of them, and serves forwarded requests against
    the local runners. One duplex connection per registry endpoint
    (``fleet.registries``; the legacy single ``fleet.connect`` is just
    a one-link fleet), each reconnecting with backoff independently —
    so every registry holds a warm member table at all times and a
    standby's takeover needs no rejoin (registry HA, serving/
    fleet_ha.py). Submits are accepted on ANY link (multi-ingress) and
    their events return on the wire they arrived on; control frames
    carrying a stale HA epoch are fenced (rejected as
    ``worker_failure``, redispatching on the sender's side)."""

    #: cap on spans buffered between heartbeats and per FleetSpans
    #: frame — the trace channel must never amplify into the data path
    SPAN_BUFFER = 512
    SPANS_PER_FRAME = 256

    def __init__(self, scheduler, settings: FleetSettings,
                 metrics: Optional[MetricsCollector] = None,
                 member_id: Optional[str] = None,
                 tracer=None):
        """``scheduler`` is the worker's own AdaptiveScheduler (the
        local runners to serve against). ``tracer`` (the worker
        process's Tracer) turns on fleet-stitched tracing: forwarded
        requests get a ``fleet.serve`` span parented on the wire's
        trace context, and every span this process finishes ships back
        to the registry host in bounded FleetSpans batches at heartbeat
        cadence (docs/OBSERVABILITY.md)."""
        self.scheduler = scheduler
        self.settings = settings
        self.metrics = metrics
        self.tracer = tracer
        import os

        self.member_id = (member_id or settings.member_id
                          or f"{socket.gethostname()}:{os.getpid()}")
        # fleet KV data plane (serving/fleet_kv.py): the member's data
        # listener, bound at start() and advertised in every heartbeat
        # so the registry host can dial it lazily for cross-host
        # handoff / peer prefix fetch. kv_enabled=False keeps the
        # member control-plane-only (no handoff target, no fetch
        # source — the pre-data-plane behavior).
        self.kv_server = None
        # member->member KV mesh (serving/fleet_mesh.py; docs/FLEET.md
        # "KV mesh"): peer channels dialed from registry KvIntro
        # frames, plus this member's learned wire rates — shipped to
        # the registry as kvwire| perf counters on the telemetry
        # piggyback so plan_route prices the wires it never touches.
        self.mesh_client = None
        self.mesh_rates = None
        # one link per registry endpoint (registry HA dual-heartbeat):
        # the legacy fleet.connect endpoint stays first so the
        # single-registry shape is exactly one primary link; the
        # fleet.registries list adds the rest
        endpoints = []
        if settings.connect:
            endpoints.append(settings.connect)
        for ep in settings.registries:
            if ep not in endpoints:
                endpoints.append(ep)
        if not endpoints:
            # no endpoint configured: one placeholder link so start()
            # fails with the same ConfigError it always raised
            endpoints.append(settings.connect)
        self._links: List[_RegistryLink] = [
            _RegistryLink(ep, primary=(i == 0))
            for i, ep in enumerate(endpoints)
        ]
        self._stop = threading.Event()
        self._crashed = False  # injected fleet.submit crash: stay down
        # registry HA fence: the highest control epoch seen on any
        # link; stale-epoch submits/intros are refused. GIL-atomic int
        # written by reader threads  # distlint: ignore[DL008]
        self._fleet_epoch = 0
        self._epoch_offset_ns = time.time_ns() - time.monotonic_ns()
        if tracer is not None:
            tracer.exporters.append(self._buffer_span)

    @property
    def endpoints(self) -> Tuple[str, ...]:
        """Every registry endpoint this worker heartbeats, primary first."""
        return tuple(link.endpoint for link in self._links)

    # -- legacy single-link surface (aliases of the primary link) ----------
    # The pre-HA worker had exactly one wire and tests/chaos drive that
    # shape through these names; they remain the primary link's truth.

    @property
    def _sock(self) -> Optional[socket.socket]:
        return self._links[0].sock

    @_sock.setter
    def _sock(self, value: Optional[socket.socket]) -> None:
        # test seam: production writes go through _connect_link /
        # _close_link under send_lock  # distlint: ignore[DL008]
        self._links[0].sock = value

    @property
    def _send_lock(self) -> threading.Lock:
        return self._links[0].send_lock

    @property
    def _seq(self) -> int:
        return self._links[0].seq

    @_seq.setter
    def _seq(self, value: int) -> None:
        # test seam: in production only the link's own loop increments
        # its beat counter  # distlint: ignore[DL008]
        self._links[0].seq = value

    @property
    def _span_buf(self) -> Deque:
        return self._links[0].span_buf

    @property
    def _span_lock(self) -> threading.Lock:
        return self._links[0].span_lock

    @property
    def _span_dropped(self) -> int:
        return self._links[0].span_dropped

    @_span_dropped.setter
    def _span_dropped(self, value: int) -> None:
        # test seam: every production write holds the link's span_lock
        # distlint: ignore[DL008]
        self._links[0].span_dropped = value

    @property
    def _beat_thread(self) -> Optional[threading.Thread]:
        return self._links[0].thread

    @_beat_thread.setter
    def _beat_thread(self, value: Optional[threading.Thread]) -> None:
        self._links[0].thread = value

    @property
    def _reader(self) -> Optional[threading.Thread]:
        return self._links[0].reader

    @_reader.setter
    def _reader(self, value: Optional[threading.Thread]) -> None:
        # lifecycle handle  # distlint: ignore[DL008]
        self._links[0].reader = value

    # -- lifecycle ---------------------------------------------------------

    def start(self, connect_timeout_s: float = 10.0) -> None:
        if self.settings.kv_enabled and self.kv_server is None:
            from distributed_inference_server_tpu.serving.fleet_kv import (
                KvDataServer,
            )

            self.kv_server = KvDataServer(
                self.scheduler, port=self.settings.kv_data_port,
                metrics=self.metrics,
            )
            self.kv_server.start()
        if (self.settings.kv_enabled and self.settings.mesh_enabled
                and self.mesh_client is None):
            from distributed_inference_server_tpu.serving.fleet_mesh import (
                MeshClient,
                MeshWireRates,
            )

            self.mesh_rates = MeshWireRates(
                window_s=self.settings.kv_rate_window_s,
                prior_rate=self.settings.kv_rate_prior,
                perf=(self.metrics.perf_store()
                      if self.metrics is not None else None),
            )
            self.mesh_client = MeshClient(
                self.member_id, self.mesh_rates, metrics=self.metrics,
                connect_timeout_s=self.settings.kv_connect_timeout_s,
            )
        errors: List[OSError] = []
        for link in self._links:
            try:
                self._connect_link(link, connect_timeout_s)
            except OSError as e:
                # registry HA: a standby being down must not stop the
                # worker joining the rest of the fleet — the link loop
                # keeps redialing it. With ONE endpoint the legacy
                # contract holds: the join raises.
                errors.append(e)
                logger.warning("fleet worker %s: initial dial of %s "
                               "failed: %s", self.member_id,
                               link.endpoint, e)
        if errors and len(errors) == len(self._links):
            raise errors[0]
        self._stop.clear()
        for link in self._links:
            # lifecycle handle  # distlint: ignore[DL008]
            link.thread = threading.Thread(
                target=self._link_loop, args=(link,),
                name=f"fleet-worker-beat-{link.endpoint}", daemon=True,
            )
            link.thread.start()

    def stop(self) -> None:
        self._stop.set()
        for link in self._links:
            self._close_link(link)
        if self.kv_server is not None:
            self.kv_server.stop()
            self.kv_server = None
        if self.mesh_client is not None:
            self.mesh_client.close()
            self.mesh_client = None
            self.mesh_rates = None
        for link in self._links:
            if link.thread is not None:
                link.thread.join(5.0)
                link.thread = None
        # detach the span exporter: a restarted worker (chaos rebuilds
        # one per crash iteration against the SAME tracer) must not
        # leave dead buffers behind — each would pin 512 spans forever
        # and inflate wire-drop counts on every finished span
        if self.tracer is not None:
            try:
                self.tracer.exporters.remove(self._buffer_span)
            except ValueError:
                pass

    def is_connected(self) -> bool:
        return any(link.sock is not None for link in self._links)

    def _connect(self, timeout_s: float) -> None:
        # class-qualified: tests drive the dial/configure failure arms
        # through minimal stubs that only carry settings.connect
        FleetWorker._connect_link(self, None, timeout_s)

    def _connect_link(self, link: Optional[_RegistryLink],
                      timeout_s: float) -> None:
        # link None = the primary link (resolved AFTER the dial: the
        # dial/configure failure arms must not depend on link state)
        endpoint = link.endpoint if link is not None else \
            self.settings.connect
        host, port = parse_connect(endpoint)
        # worker-side join/reconnect thread: blocking by design with a
        # bounded timeout; never a dispatch or asyncio path
        sock = socket.create_connection(  # distlint: ignore[DL001]
            (host, port), timeout=timeout_s)
        try:
            sock.settimeout(None)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            sock.close()  # a dialed-but-unconfigurable socket leaks its fd
            raise
        if link is None:
            link = self._links[0]
        with link.send_lock:
            # every link.sock write holds this link's send_lock (here and
            # in _close_link); the lint can't see the per-link lock
            link.sock = sock  # distlint: ignore[DL008]
        # fresh reader per connection; the old one exited on its EOF
        link.reader = threading.Thread(
            target=self._read_loop, args=(sock, link),
            name=f"fleet-worker-reader-{link.endpoint}", daemon=True,
        )
        link.reader.start()
        logger.info("fleet worker %s connected to %s:%d", self.member_id,
                    host, port)

    def _close(self) -> None:
        self._close_link(self._links[0])

    def _close_all(self) -> None:
        for link in self._links:
            self._close_link(link)

    def _close_link(self, link: _RegistryLink) -> None:
        with link.send_lock:
            # every link.sock write holds this link's send_lock (here and
            # in _connect_link); the lint can't see the per-link lock
            sock, link.sock = link.sock, None  # distlint: ignore[DL008]
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    # -- sending (heartbeat thread + local runner threads) -----------------

    def _send(self, name: str, obj: Dict[str, Any]) -> None:
        with self._send_lock:
            if self._sock is None:
                raise OSError("fleet worker not connected")
            send_frame(self._sock, name, obj)

    def _send_link(self, link: Optional[_RegistryLink], name: str,
                   obj: Dict[str, Any]) -> None:
        """One frame on ``link`` (None = the primary link). The primary
        link routes through ``_send`` — the seam tests interpose on."""
        if link is None or link.primary:
            self._send(name, obj)
            return
        with link.send_lock:
            if link.sock is None:
                raise OSError("fleet worker not connected")
            send_frame(link.sock, name, obj)

    def send_event(self, obj: Dict[str, Any], link=None) -> None:
        """``link``: the registry wire the request arrived on (registry
        HA multi-ingress) — its events go back the same way."""
        try:
            self._send_link(link, "FleetEvent", obj)
        except Exception as e:  # noqa: BLE001 — registry link fault
            # domain: the host's death path owns the request now
            logger.debug("fleet worker %s: event send failed: %s",
                         self.member_id, e)
            if self.metrics:
                self.metrics.record_error("fleet_worker.event_send")

    def _buffer_span(self, span) -> None:
        """Tracer exporter: queue a finished span for the next shipment
        (any thread; bounded — never blocks the finishing thread). Every
        link buffers its own copy: each registry must see every span
        (registry HA dual-heartbeat), and one link's stall must not
        starve the others. The tracer's local wire-drop counter tracks
        the PRIMARY link only — it counts spans lost to the operator's
        view, not per-wire copies."""
        for link in self._links:
            overflowed = False
            with link.span_lock:
                # every span_buf/span_dropped write holds this link's
                # span_lock; the lint can't see the per-link lock
                if len(link.span_buf) >= self.SPAN_BUFFER:
                    link.span_buf.popleft()  # distlint: ignore[DL008]
                    link.span_dropped += 1  # distlint: ignore[DL008]
                    overflowed = True
                link.span_buf.append(span)  # distlint: ignore[DL008]
            if overflowed and link.primary and self.tracer is not None:
                self.tracer.record_drop("wire")

    def ship_spans_once(self, link: Optional[_RegistryLink] = None) -> bool:
        """Send one FleetSpans frame with everything ``link`` buffered
        (capped at SPANS_PER_FRAME; the overflow counts as dropped).
        Piggybacks on the heartbeat cadence — each link loop calls this
        right after a successful beat. Returns False when the link is
        down (the spans are counted dropped, not retried: a trace is
        advisory, the reconnect path must not grow a replay buffer)."""
        if self.tracer is None:
            return True
        link = self._links[0] if link is None else link
        with link.span_lock:
            if not link.span_buf and not link.span_dropped:
                return True
            # under this link's span_lock, as is every other
            # span_buf/span_dropped write; the lint can't see it
            spans = list(link.span_buf)
            link.span_buf.clear()  # distlint: ignore[DL008]
            dropped, link.span_dropped = link.span_dropped, 0  # distlint: ignore[DL008]
        shipped = spans[:self.SPANS_PER_FRAME]
        dropped += len(spans) - len(shipped)
        try:
            self._send_link(link, "FleetSpans", {
                "member_id": self.member_id,
                "spans": [span_to_wire(s, self._epoch_offset_ns)
                          for s in shipped],
                "dropped": dropped,
            })
            return True
        except Exception as e:  # noqa: BLE001 — link fault domain
            logger.debug("fleet worker %s: span ship failed: %s",
                         self.member_id, e)
            with link.span_lock:
                link.span_dropped += len(shipped) + dropped  # distlint: ignore[DL008]
            if link.primary and self.tracer is not None:
                self.tracer.record_drop("wire", len(shipped))
            return False

    def ship_telemetry_once(self,
                            link: Optional[_RegistryLink] = None) -> bool:
        """Send one FleetTelemetry frame: the full current digest
        windows + cumulative step-clock counters (serving/teledigest.py).
        Piggybacked after each successful beat, like spans. Stateless by
        design — digests are cumulative sliding windows, so a dropped
        frame needs no replay buffer: the NEXT frame carries everything
        the window still remembers (bounded + drop-counted via
        fleet_telemetry_frames_total{outcome=failed}). Returns False
        when the link is down."""
        if self.metrics is None:
            return True
        body = self.metrics.perf_wire()
        if not body["digests"] and not body["counters"]:
            return True
        try:
            self._send_link(link, "FleetTelemetry",
                            {"member_id": self.member_id, **body})
            self.metrics.record_telemetry_frame("sent")
            return True
        except Exception as e:  # noqa: BLE001 — link fault domain
            logger.debug("fleet worker %s: telemetry ship failed: %s",
                         self.member_id, e)
            self.metrics.record_telemetry_frame("failed")
            return False

    def heartbeat_once(self, link: Optional[_RegistryLink] = None) -> bool:
        """Send one heartbeat on ``link`` (None = primary); returns
        False when that link is down."""
        link = self._links[0] if link is None else link
        link.seq += 1
        try:
            self._send_link(link, "FleetHeartbeat", {
                "member_id": self.member_id,
                "seq": link.seq,
                "engines": [status_to_wire(s)
                            for s in self.scheduler.statuses()],
                "data_port": (self.kv_server.bound_port
                              if self.kv_server is not None else 0),
            })
            return True
        except Exception as e:  # noqa: BLE001 — link fault domain
            logger.debug("fleet worker %s: heartbeat failed: %s",
                         self.member_id, e)
            return False

    def _link_loop(self, link: _RegistryLink) -> None:
        """One link's beat + reconnect loop (registry HA: each registry
        endpoint gets its own, so a dead standby cannot slow the
        primary's heartbeat cadence and vice versa)."""
        backoff = self.settings.heartbeat_interval_s
        while not self._stop.wait(self.settings.heartbeat_interval_s):
            if self._crashed:
                return  # injected crash: the process is "dead"
            if (link.sock is None or not self.heartbeat_once(link)
                    or not self.ship_spans_once(link)
                    or not self.ship_telemetry_once(link)):
                self._close_link(link)
                if self._stop.is_set() or self._crashed:
                    return
                try:
                    self._connect_link(link, timeout_s=5.0)
                    backoff = self.settings.heartbeat_interval_s
                except OSError as e:
                    logger.debug("fleet worker %s: reconnect failed: %s",
                                 self.member_id, e)
                    backoff = min(backoff * 2.0, 5.0)
                    if self._stop.wait(backoff):
                        return

    # -- serving (reader thread) -------------------------------------------

    # member->host kinds (heartbeats, events, spans, telemetry) are what
    # this worker SENDS — the host never echoes them back on this wire;
    # registry lease/state frames only cross registry<->registry wires
    # distlint: wire-ignores[FleetHeartbeat, FleetEvent, FleetSpans, FleetTelemetry, RegistryLease, RegistryState]
    def _read_loop(self, sock: socket.socket,
                   link: Optional[_RegistryLink] = None) -> None:
        link = self._links[0] if link is None else link
        try:
            while True:
                frame = recv_frame(sock)
                if frame is None:
                    return
                name, obj = frame
                if name == "FleetSubmit":
                    self._serve_submit(obj, link)
                elif name == "KvIntro":
                    self._on_kv_intro(obj)
                # heartbeats/events only flow worker -> host; ignore
        except OSError:
            return  # connection died; the link loop reconnects
        except faults.InjectedFault:
            # fleet.submit armed on the worker: the member "crashes" on
            # receipt — drop every connection, serve nothing, stay down
            # (the registry hosts redispatch our zero-token in-flight)
            logger.warning("fleet worker %s: injected crash on submit",
                           self.member_id)
            self._crashed = True
            self._close_all()
        except Exception:  # noqa: BLE001 — reader must not die silently
            logger.exception("fleet worker %s reader failed", self.member_id)
            self._close_link(link)

    def _on_kv_intro(self, obj: Dict[str, Any]) -> None:
        """Registry introduction (docs/FLEET.md "KV mesh"): learn —
        or forget, on ``gone`` — a peer member's data endpoint. A
        member with the mesh disabled (or an older build that never
        decodes frame kind 6) just ignores the frame; fetch hints it
        cannot honor degrade to plain recompute."""
        epoch = int(obj.get("epoch") or 0)
        if epoch and epoch < self._fleet_epoch:
            # registry HA fence: a stale-epoch intro (a partitioned old
            # primary still brokering) — ignore; the mesh degrades to
            # recompute, never to a wrong wire
            return
        if epoch > self._fleet_epoch:
            self._fleet_epoch = epoch
        if self.mesh_client is not None:
            self.mesh_client.on_intro(obj)

    def _serve_submit(self, obj: Dict[str, Any],
                      link: Optional[_RegistryLink] = None) -> None:
        rid = obj.get("request_id", "")
        engine_id = obj.get("engine_id", "")
        runner = self.scheduler.get(engine_id)
        if obj.get("abort"):
            # aborts are NOT fenced: a demoted registry may still own
            # requests it routed before losing the lease, and honoring
            # its abort only releases local work
            if runner is not None:
                runner.abort(rid)
            return
        epoch = int(obj.get("epoch") or 0)
        if epoch and epoch < self._fleet_epoch:
            # registry HA fence (serving/fleet_ha.py): control from a
            # lower epoch than the highest seen is a partitioned old
            # primary. Refuse as a zero-token worker_failure ON THE
            # ARRIVING WIRE — the sender's proxy redispatches on its
            # side, bounded by its usual redispatch budget.
            logger.warning("fleet worker %s: fenced submit %s (epoch %d "
                           "< %d)", self.member_id, rid, epoch,
                           self._fleet_epoch)
            self.send_event({
                "request_id": rid, "engine_id": engine_id,
                "kind": "error", "code": "worker_failure",
                "message": f"stale control epoch {epoch} (member has "
                           f"seen {self._fleet_epoch}): fenced",
            }, link=link)
            return
        if epoch > self._fleet_epoch:
            self._fleet_epoch = epoch
        # the member crashing on receipt (fault domain of the REMOTE
        # process): raises InjectedFault through to the read loop
        faults.fire("fleet.submit")
        span = None
        if self.tracer is not None and obj.get("trace_id"):
            # parent on the WIRE's trace context: this span (and the
            # engine.infer child the local runner hangs under it) ships
            # back finished, stitching into the host's request tree
            span = self.tracer.start(
                "fleet.serve",
                parent=(obj["trace_id"],
                        obj.get("parent_span_id") or None),
                request_id=rid, engine_id=engine_id,
                member_id=self.member_id,
            )
        sink = _RemoteSink(self, rid, engine_id, span=span, link=link)
        if runner is None or not runner.is_healthy():
            sink.on_error(
                f"remote engine {engine_id!r} unavailable", "worker_failure"
            )
            return
        req = ServerRequest(
            rid, [int(t) for t in obj.get("prompt_ids", [])],
            SamplingParams(
                max_tokens=obj.get("max_tokens", 0) or 16,
                temperature=obj.get("temperature", 0.0),
                top_p=obj.get("top_p", 1.0) or 1.0,
                stop_sequences=tuple(obj.get("stop_sequences", [])),
            ),
            sink,
            span=span,
            tenant=obj.get("tenant") or "default",
        )
        # gray-failure lever (docs/RESILIENCE.md fleet.slow_member,
        # delay-style): the member serves SLOWLY while heartbeating
        # healthily — fired after the request's arrival clock started,
        # so the member's own TTFT telemetry carries the slowness the
        # host's HealthScorer demotes it on. Head-of-line by design
        # (the reader thread stalls): a gray-failing box is slow for
        # everything behind the slow request too.
        faults.fire("fleet.slow_member")
        if self._mesh_prefetch(runner, req, obj, span):
            return
        runner.submit([req])

    def _mesh_prefetch(self, runner, req: ServerRequest,
                       obj: Dict[str, Any], span) -> bool:
        """Honor a mesh fetch hint (docs/FLEET.md "KV mesh"): pull the
        warm prefix DIRECTLY from the hinted peer member over this
        member's own data channel, seat it in the local engine's
        prefix cache, then submit the request. Returns True when this
        path owns the submit (it happens in a callback); False hands
        the request straight back to the plain-submit path.

        Failure semantics mirror disagg.PrefixFetcher exactly: the
        fetch is an accelerator, never a gate. No intro for the peer,
        a dead/breaker-open wire (``fleet.kv_peer_dial``), a stale or
        empty export, or an import rejection all degrade the request
        to plain recompute HERE, exactly once — each stage's callback
        fires once and every failure arm ends in the same finisher."""
        member = obj.get("fetch_member") or ""
        hashes = [int(h) for h in obj.get("fetch_hashes") or ()]
        if not member or not hashes or self.mesh_client is None:
            return False
        peer = self.mesh_client.peer(
            member, obj.get("fetch_source_engine") or "")
        if peer is None:
            # never introduced (or already retracted): recompute
            if self.metrics:
                self.metrics.record_prefix_fetch("fallback",
                                                 scope="mesh")
            return False
        chunk_pages = int(obj.get("fetch_chunk_pages") or 0) or 1
        wire_quant = obj.get("fetch_wire_quant") or "none"
        t0 = time.monotonic()

        def _finish(outcome: str, nbytes: int = 0) -> None:
            if self.metrics:
                self.metrics.record_prefix_fetch(
                    outcome, seconds=time.monotonic() - t0,
                    nbytes=nbytes, scope="mesh")
            runner.submit([req])

        def _on_import(ok: bool, err, nbytes: int) -> None:
            if not ok:
                logger.debug("mesh prefetch for %s: import rejected "
                             "(%s); recomputing", req.request_id, err)
            _finish("ok" if ok else "fallback", nbytes)

        def _on_export(result, err) -> None:
            # peer channel's reader thread (or this one, fail-fast)
            if result is None:
                logger.debug("mesh prefetch for %s: peer %s export "
                             "failed (%s); recomputing",
                             req.request_id, member, err)
                _finish("fallback")
                return
            depth, chunks = result
            if depth <= 0 or not chunks:
                _finish("fallback")  # peer evicted the chain
                return
            try:
                ps = max(1, getattr(runner.status(), "page_size", 0)
                         or 1)
                tokens = list(req.prompt_ids[: depth * ps])
                nbytes = sum(len(c.payload) for c in chunks)
                runner.submit_prefix_import(
                    req.request_id, tokens, chunks,
                    lambda ok, ierr: _on_import(ok, ierr, nbytes),
                )
            except Exception as e:  # noqa: BLE001 — import fault
                # domain: a torn chunk set must not kill the reader
                logger.debug("mesh prefetch for %s: import failed "
                             "(%s); recomputing", req.request_id, e)
                _finish("fallback")

        try:
            peer.submit_prefix_export(
                req.request_id, hashes, chunk_pages, wire_quant,
                _on_export,
                trace=(span.context() if span is not None else None),
            )
        except Exception as e:  # noqa: BLE001 — channel fault domain
            logger.debug("mesh prefetch for %s: dispatch failed (%s); "
                         "recomputing", req.request_id, e)
            _finish("fallback")
        return True
