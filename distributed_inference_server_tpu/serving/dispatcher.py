"""Dispatcher: the serving spine connecting queue → batcher → scheduler →
engine runners, plus the timeout sweeper.

This is the reference's spec'd batching/scheduling background task
(``tasks.md:70-82`` [spec]; hot loop SURVEY.md §3.4) as one dispatch thread:

    loop:
      sweep expired queued requests → 408 (queue.rs:198-226; Req 3.3)
      poll admission batcher (50 ms / 32, Properties 4-5)
      scheduler picks an engine (round-robin / least-loaded / memory-aware)
      runner admits the batch into its continuous-batching pool

Backpressure (503) surfaces at ``submit()`` via ``QueueFull`` from the
priority queue's hysteresis (Property 7). Graceful shutdown drains the
batcher and stops accepting new work (Req 9.5).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional

from distributed_inference_server_tpu.core.errors import QueueFull
from distributed_inference_server_tpu.core.queue import (
    PriorityQueueManager,
    QueueConfig,
    QueuedRequest,
)
from distributed_inference_server_tpu.core.types import Priority, RequestId
from distributed_inference_server_tpu.serving.batcher import (
    AdmissionBatcher,
    BatcherConfig,
)
from distributed_inference_server_tpu.serving.metrics import MetricsCollector
from distributed_inference_server_tpu.serving.runner import (
    EngineRunner,
    ServerRequest,
)
from distributed_inference_server_tpu.serving.scheduler import (
    AdaptiveScheduler,
    SchedulingStrategy,
)


def _make_queue(queue_config, force: Optional[bool] = None):
    """Pick the queue tier (contracts identical; differential tests in
    tests/test_native.py): the native C++ queue (native/pqueue.cpp) when
    built — the admission hot path runs native, as in the reference's Rust
    serving layer — the Python tier otherwise. ``force``: None = auto,
    True = native or raise, False = Python. The chosen tier is logged.
    Per-tenant fairness (``queue.tenant_fairness``) forces the Python
    tier — the native queue has no tenant lanes."""
    import logging

    log = logging.getLogger(__name__)
    if queue_config is not None and queue_config.tenant_fairness:
        if force is True:
            raise RuntimeError(
                "native_queue=True is incompatible with "
                "queue.tenant_fairness (the native tier has no tenant "
                "lanes)"
            )
        log.info("request queue: Python tier (tenant fairness on)")
        return PriorityQueueManager(queue_config)
    if force is not False:
        from distributed_inference_server_tpu import native

        if native.available():
            log.info("request queue: native C++ tier")
            return native.NativePriorityQueue(queue_config)
        if force is True:
            raise RuntimeError(
                "native_queue=True but the native library is unavailable"
            )
    log.info("request queue: Python tier")
    return PriorityQueueManager(queue_config)


def _make_batcher(queue, batcher_config):
    """Pick the admission-batcher tier to match the queue: when the queue
    is native, the batcher is too (native/batcher.cpp — one native
    batcher_poll drains the queue and manages the window, no Python in
    the per-request admission path); the Python batcher otherwise."""
    from distributed_inference_server_tpu import native

    if isinstance(queue, getattr(native, "NativePriorityQueue", ())):
        return native.NativeAdmissionBatcher(queue, batcher_config)
    return AdmissionBatcher(queue, batcher_config)


class Dispatcher:
    """Owns the queue, batcher, and dispatch/sweep thread."""

    def __init__(
        self,
        scheduler: AdaptiveScheduler,
        queue_config: Optional[QueueConfig] = None,
        batcher_config: Optional[BatcherConfig] = None,
        metrics: Optional[MetricsCollector] = None,
        poll_interval_s: float = 0.002,
        native_queue: Optional[bool] = None,
        tracer=None,
        disagg=None,
        max_redispatch: int = 2,
        prefix_fetcher=None,
        recorder=None,
        admission=None,
        retry_budget=None,
    ):
        """``disagg``: the DisaggController when the topology is
        disaggregated (serving/disagg.py) — its migration queue counts
        toward drain, and aborts reach requests parked there.
        ``max_redispatch``: crash-safe redispatch budget per request
        (docs/RESILIENCE.md) — how many times a zero-token in-flight
        request may be moved off a dead engine before it fails to its
        client; 0 disables redispatch.
        ``prefix_fetcher``: the disagg.PrefixFetcher driving routed-
        ``fetch`` decisions under cache_aware (fleet prefix sharing,
        docs/CACHING.md); its in-flight fetches count toward drain and
        aborts reach requests parked there. None = fetch decisions
        degrade to plain submission.
        ``recorder``: the per-request FlightRecorder
        (serving/flightrec.py) — routing decisions, redispatch hops, and
        queue expiries land in request timelines. None = disabled.
        ``admission``: the health.AdmissionControl driving deadline-
        aware shedding at submit (docs/RESILIENCE.md "Gray failures and
        overload"); None = no shedding. ``retry_budget``: the shared
        health.RetryBudget — admits feed its window, and redispatch
        draws from it before amplifying load; None = unbudgeted."""
        self.scheduler = scheduler
        self.disagg = disagg
        self.prefix_fetcher = prefix_fetcher
        self.tracer = tracer
        self.recorder = recorder
        self.admission = admission
        self.retry_budget = retry_budget
        self.max_redispatch = max_redispatch
        self.queue: PriorityQueueManager[ServerRequest] = _make_queue(
            queue_config, native_queue
        )
        self.batcher: AdmissionBatcher[ServerRequest] = _make_batcher(
            self.queue, batcher_config
        )
        self.metrics = metrics
        self._poll_interval = poll_interval_s
        # lock-free by design: monotonic lifecycle bool, GIL-atomic,
        # readers tolerate one stale poll  # distlint: ignore[DL008]
        self._accepting = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._sweep_every_s = 1.0
        # degradation-ladder gates (serving/degradation.py; design.md:938-941)
        self.reject_low_priority = False
        self.reject_all = False
        # registry HA ingress gate (serving/fleet_ha.py): with
        # fleet.standby_http=false, a standby registry's front door
        # stays closed (QueueFull -> 503) until it holds the lease.
        # Checked at submit() ONLY — redispatch and fleet-internal
        # paths dispatch straight to runners and are never gated.
        self.ingress_gate: Optional[Callable[[], bool]] = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        self._accepting = True
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="dispatcher", daemon=True
        )
        self._thread.start()

    def shutdown(self, drain_timeout_s: float = 30.0) -> None:
        """Stop accepting, drain in-flight work, stop the thread
        (graceful shutdown, Req 9.5 requirements.md:134)."""
        self._accepting = False
        deadline = time.monotonic() + drain_timeout_s
        while time.monotonic() < deadline:
            if (
                self.queue.is_empty()
                and self.batcher.pending_count() == 0
                and not any(
                    r.active_count() for r in self.scheduler.engines()
                )
                and (self.disagg is None
                     or self.disagg.pending_count() == 0)
                and (self.prefix_fetcher is None
                     or self.prefix_fetcher.pending_count() == 0)
            ):
                break
            # interruptible drain poll: a concurrent stop request (another
            # thread setting _stop) ends the wait immediately instead of
            # burning the rest of the 10 ms tick (distlint DL001)
            if self._stop.wait(0.01):
                break
        self._stop.set()
        if self._thread is not None:
            self._thread.join(5.0)
        # anything still pending after the deadline gets dispatched so the
        # engines (which keep running until InferenceServer stops them) can
        # finish it; without this, held requests would hang their clients
        leftover = self.batcher.flush()
        if leftover is not None:
            self._dispatch(leftover.requests)

    def is_accepting(self) -> bool:
        return self._accepting and self.queue.is_accepting()

    # -- submission (any thread) -------------------------------------------

    def submit(self, request: ServerRequest,
               priority: Priority = Priority.NORMAL) -> None:
        """Enqueue; raises QueueFull → 503 when backpressure is active or
        the server is draining, and its AdmissionShed subclass → 503 +
        Retry-After when deadline-aware admission sheds the request
        (serving/health.py; docs/RESILIENCE.md "Gray failures and
        overload") — failing fast instead of queueing work the windowed
        queue-wait estimate says is already doomed to queue_timeout."""
        if not self._accepting or self.reject_all:
            raise QueueFull()
        if self.ingress_gate is not None and not self.ingress_gate():
            raise QueueFull()
        if self.reject_low_priority and priority is Priority.LOW:
            raise QueueFull()
        tenant = getattr(request, "tenant", "") or "default"
        if self.admission is not None:
            shed = self.admission.check(tenant)
            if shed is not None:
                if self.metrics:
                    self.metrics.record_shed(tenant, shed.reason)
                if self.recorder is not None:
                    # the shed IS the request's whole timeline: one
                    # structured event with the decision's inputs, then
                    # the distinct terminal code
                    self.recorder.note(
                        request.request_id, "admission_shed",
                        tenant=tenant, reason=shed.reason,
                        estimate_ms=round(shed.estimate_ms, 3),
                        deadline_ms=round(shed.deadline_ms, 3),
                        retry_after_s=shed.retry_after_s,
                    )
                    self.recorder.finish(request.request_id, "error",
                                         code="admission_shed")
                raise shed
        if self.retry_budget is not None:
            self.retry_budget.note_admit()
        self.queue.enqueue(
            QueuedRequest(id=request.request_id, data=request,
                          priority=priority, tenant=tenant)
        )
        if self.metrics:
            d = self.queue.queue_depth()
            self.metrics.set_queue_depth(d.high, d.normal, d.low)
            self._publish_tenant_depths()

    def redispatch(self, request: ServerRequest, from_engine: str,
                   reason: str) -> bool:
        """Crash-safe redispatch (docs/RESILIENCE.md): a runner died
        with ``request`` in flight having streamed ZERO tokens — re-run
        it from scratch on a healthy replica, invisibly to the client.
        Called from the dead runner's ``_fail_all_of`` (any thread);
        returns True when this dispatcher took ownership (the request
        will reach exactly one terminal event on its new replica), False
        when the caller must fail it to its sink (drain/shutdown,
        attempt budget exhausted, or no healthy replica).

        Exactly-once is structural: the caller already removed the
        request from its own in-flight map, and ``runner.submit``
        re-registers it with exactly one new owner. A submit that races
        the new replica's own crash re-enters here with the attempt
        counter already bumped, so the recursion is bounded by
        ``max_redispatch`` no matter how many replicas fail."""
        if self.max_redispatch <= 0:
            return False  # feature off: not an "exhausted" budget
        if not self._accepting:
            return False  # draining: the crash error is the truth
        if request.redispatches >= self.max_redispatch:
            if self.metrics:
                self.metrics.record_redispatch("exhausted")
            return False
        if (self.retry_budget is not None
                and not self.retry_budget.acquire("redispatch")):
            # the shared retry budget is dry (serving/health.py): a
            # sick fleet must not amplify its own load — degrade to the
            # caller's exactly-once sink failure instead of re-running
            if self.metrics:
                self.metrics.record_redispatch("exhausted")
            return False
        runner = self.scheduler.schedule(request.prompt_ids)
        if runner is None:
            if self.metrics:
                self.metrics.record_redispatch("exhausted")
            return False
        # exactly one thread owns the request here: the dead runner's
        # _fail_all_of popped it before calling in, and the next owner
        # is registered only by submit() below  # distlint: ignore[DL008]
        request.redispatches += 1
        if self.tracer and request.span is not None:
            request.span.set(redispatch_from=from_engine,
                             redispatch_to=runner.engine_id,
                             redispatch_reason=reason)
            request.span.event("redispatched", from_engine=from_engine,
                               to_engine=runner.engine_id, reason=reason)
        if self.recorder is not None:
            self.recorder.note(request.request_id, "redispatch",
                               from_engine=from_engine,
                               to_engine=runner.engine_id, reason=reason,
                               attempt=request.redispatches)
        runner.submit([request])
        # counted only after submit took the request — a submit that
        # raises is NOT an "ok" outcome (the caller fails the sink)
        if self.metrics:
            self.metrics.record_redispatch("ok")
        return True

    def abort(self, request_id: RequestId) -> None:
        """Client disconnect: drop from queue or the batching window if not
        yet dispatched, else tell every engine (only the owner will find
        it) — Req 5.4."""
        if self.queue.cancel(request_id) is not None:
            return
        if self.batcher.cancel(request_id) is not None:
            return
        if (self.prefix_fetcher is not None
                and self.prefix_fetcher.abort(request_id)):
            return
        if self.disagg is not None and self.disagg.abort(request_id):
            return
        for runner in self.scheduler.engines():
            runner.abort(request_id)

    # -- dispatch thread ---------------------------------------------------

    def _loop(self) -> None:
        last_sweep = time.monotonic()
        while not self._stop.is_set():
            now = time.monotonic()
            if now - last_sweep >= self._sweep_every_s:
                self._sweep(now)
                last_sweep = now
            batch = self.batcher.poll(now)
            if batch is None and not self._accepting:
                batch = self.batcher.flush(now)
            if batch is not None:
                self._dispatch(batch.requests)
            else:
                # Event.wait, not time.sleep: shutdown() wakes the loop
                # instantly instead of eating one more poll tick
                # (distlint DL001)
                self._stop.wait(self._poll_interval)

    def _dispatch(self, queued: List[QueuedRequest[ServerRequest]]) -> None:
        requests = [q.data for q in queued]
        if self.metrics:
            lens = [len(r.prompt_ids) for r in requests]
            pad = (max(lens) * len(lens) / max(sum(lens), 1) - 1.0) if lens else 0.0
            self.metrics.record_batch(len(requests), max(0.0, pad))
        # cache-aware routing (ISSUE 5) is per REQUEST, not per batch —
        # two requests in one admission window may have their prefixes
        # warm on different engines; route the window against one fleet
        # snapshot with the three-way cost model (schedule_batch_plans:
        # route-to-warm / fetch-to-cold / recompute, docs/CACHING.md),
        # peel routed-``fetch`` requests off to the PrefixFetcher (the
        # warm peer's pages land on the cold replica before the request
        # does), group the rest by chosen engine, and submit each group.
        # With no fetcher wired the pre-fetch two-way routing applies —
        # planning with fetch options and then not fetching would both
        # mislabel kv_prefix_route_total and route to a cold replica the
        # model only chose because a fetch would make it cheap. Every
        # other strategy keeps the one-engine-per-batch fast path.
        strategy = self.scheduler.strategy()
        if (strategy is SchedulingStrategy.CACHE_AWARE
                and self.prefix_fetcher is not None):
            plans = self.scheduler.schedule_batch_plans(
                [r.prompt_ids for r in requests]
            )
            by_engine: dict = {}
            for r, (runner, plan) in zip(requests, plans):
                decision = plan.decision if plan is not None else "recompute"
                if self.recorder is not None and plan is not None:
                    # the schedule decision with its plan_route cost
                    # terms — the timeline's "why did it go THERE"
                    self.recorder.note(
                        r.request_id, "route_plan",
                        strategy="cache_aware", decision=decision,
                        engine=plan.engine_id, depth=plan.depth,
                        peer_depth=plan.peer_depth,
                        **({"peer": plan.peer_id} if plan.peer_id else {}),
                    )
                if decision == "fetch" and runner is not None:
                    peer = self.scheduler.get(plan.peer_id)
                    if peer is not None:
                        if self.metrics:
                            self.metrics.record_prefix_route("fetch")
                        if self.tracer and r.span is not None:
                            # the dispatch breadcrumb for the fetch path
                            # (fetch requests never reach _submit_group)
                            r.span.set(prefix_fetch_from=peer.engine_id,
                                       prefix_fetch_to=runner.engine_id)
                            r.span.event("prefix_fetch")
                        self.prefix_fetcher.fetch_then_submit(
                            runner, peer, r, plan
                        )
                        continue
                    # peer unregistered since the snapshot: the chosen
                    # replica still serves, just without the fetch
                    decision = "warm" if plan.depth else "recompute"
                if self.metrics and runner is not None:
                    self.metrics.record_prefix_route(decision)
                key = runner.engine_id if runner is not None else None
                if key not in by_engine:
                    by_engine[key] = (runner, [])
                by_engine[key][1].append(r)
            pairs = list(by_engine.values())
        elif strategy is SchedulingStrategy.CACHE_AWARE:
            runners = self.scheduler.schedule_batch(
                [r.prompt_ids for r in requests]
            )
            by_engine = {}
            for r, runner in zip(requests, runners):
                key = runner.engine_id if runner is not None else None
                if key not in by_engine:
                    by_engine[key] = (runner, [])
                by_engine[key][1].append(r)
            pairs = list(by_engine.values())
        else:
            pairs = [(self.scheduler.schedule(), requests)]
        for runner, reqs in pairs:
            self._submit_group(runner, reqs)
        if self.metrics:
            d = self.queue.queue_depth()
            self.metrics.set_queue_depth(d.high, d.normal, d.low)
            self._publish_tenant_depths()

    def _publish_tenant_depths(self) -> None:
        """Per-tenant queue occupancy gauge (queue_tenant_depth). The
        native tier has no tenant lanes, hence the hasattr gate."""
        if hasattr(self.queue, "tenant_depths"):
            self.metrics.set_tenant_depths(self.queue.tenant_depths())

    def _submit_group(self, runner: Optional[EngineRunner],
                      requests: List[ServerRequest]) -> None:
        if runner is None:
            # no healthy engine: fail the batch (Property 20 — graceful,
            # not silent)
            if self.tracer:
                for r in requests:
                    if r.span is not None:
                        r.span.set(dispatch_failed="no_workers")
                        r.span.event("dispatch_failed",
                                     reason="no_workers")
            for r in requests:
                if self.recorder is not None:
                    self.recorder.finish(r.request_id, "error",
                                         code="no_workers")
                r.sink.on_error("no healthy inference engine available",
                                "no_workers")
            return
        if self.recorder is not None:
            for r in requests:
                self.recorder.note(
                    r.request_id, "schedule",
                    engine=runner.engine_id,
                    strategy=self.scheduler.strategy().value,
                )
        if self.tracer:
            # batching-phase event (S12): one per admission batch; recorded
            # only for batches that actually reach an engine
            with self.tracer.span(
                "batch.dispatch",
                size=len(requests),
                engine_id=runner.engine_id,
                request_ids=[str(r.request_id) for r in requests],
            ):
                for r in requests:
                    if r.span is not None:
                        r.span.event("dispatched")
        runner.submit(requests)

    def _sweep(self, now: float) -> None:
        """Expire queued requests older than the timeout → 408
        (Property 8; Req 3.3 requirements.md:59). The sink code is the
        DISTINCT ``queue_timeout`` — "the fleet never even started your
        request" is actionable (retry elsewhere / shed load) in a way a
        generic failure is not — and every expiry counts into
        ``requests_expired_total``."""
        expired = self.queue.remove_expired(now)
        for q in expired:
            if self.recorder is not None:
                self.recorder.finish(q.data.request_id, "error",
                                     code="queue_timeout")
            q.data.sink.on_error(
                "request expired in queue before dispatch", "queue_timeout"
            )
        if expired and self.metrics:
            self.metrics.record_expired(len(expired))
