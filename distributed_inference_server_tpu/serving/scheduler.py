"""Adaptive scheduler: routes admission batches to engine replicas.

TPU-native realization of the reference's spec'd ``Scheduler`` trait and
strategies (``design.md:269-307`` [spec]; behavior ``requirements.md:92-98``):

- **round-robin** — rotate over healthy engines;
- **least-loaded** — fewest active+waiting requests (design.md:277);
- **memory-aware** — most free KV pages, i.e. the estimated batch memory
  fits where the most page capacity remains (design.md:278-280);
- runtime strategy switching (``set_strategy``, design.md:306);
- register/unregister engines at runtime (elastic scaling,
  requirements.md:110);
- health checking: unhealthy engines leave the routing set and are
  reinstated on recovery (requirements.md:97-98; Properties 18-19), with
  optional automatic restart (requirements.md:109,133).

Pure-logic core (strategy choice over ``EngineStatus`` vectors) is separated
from the threaded health loop so scheduler properties are testable without
engines, mirroring the reference's test approach (SURVEY.md §4.3).
"""

from __future__ import annotations

import enum
import logging
import threading
import time
from typing import Dict, List, Optional, Sequence

from distributed_inference_server_tpu.serving.metrics import EngineStatus
from distributed_inference_server_tpu.serving.runner import EngineRunner

logger = logging.getLogger(__name__)


class SchedulingStrategy(str, enum.Enum):
    ROUND_ROBIN = "round_robin"
    LEAST_LOADED = "least_loaded"
    MEMORY_AWARE = "memory_aware"

    @classmethod
    def parse(cls, value: str) -> "SchedulingStrategy":
        return cls(value.strip().lower())


def choose_engine(
    strategy: SchedulingStrategy,
    statuses: Sequence[EngineStatus],
    rr_counter: int,
    roles: Optional[Sequence[str]] = None,
) -> Optional[str]:
    """Pure strategy core: pick an engine id from healthy statuses.

    Property 16: only healthy engines are eligible. Property 17:
    least-loaded picks a minimum-load engine. Deterministic given inputs.
    ``roles`` (disaggregated serving, serving/disagg.py) restricts the
    eligible set to engines carrying one of those roles; None = all.
    """
    healthy = [s for s in statuses if s.healthy]
    if roles is not None:
        healthy = [
            s for s in healthy if getattr(s, "role", "unified") in roles
        ]
    if not healthy:
        return None
    if strategy is SchedulingStrategy.ROUND_ROBIN:
        return healthy[rr_counter % len(healthy)].engine_id
    if strategy is SchedulingStrategy.LEAST_LOADED:
        return min(
            healthy, key=lambda s: (s.active_requests + s.waiting_requests,
                                    s.engine_id)
        ).engine_id
    # memory-aware: most free pages; tie-break on load then id
    return min(
        healthy,
        key=lambda s: (
            -(s.memory_total_pages - s.memory_used_pages),
            s.active_requests + s.waiting_requests,
            s.engine_id,
        ),
    ).engine_id


class AdaptiveScheduler:
    """Thread-safe scheduler over registered ``EngineRunner`` replicas."""

    def __init__(
        self,
        strategy: SchedulingStrategy = SchedulingStrategy.LEAST_LOADED,
        health_check_interval_s: float = 1.0,
        auto_restart: bool = False,
    ):
        self._strategy = strategy
        self._engines: Dict[str, EngineRunner] = {}
        self._lock = threading.Lock()
        self._rr = 0
        self._interval = health_check_interval_s
        self._auto_restart = auto_restart
        self._stop = threading.Event()
        self._health_thread: Optional[threading.Thread] = None
        self._restarting: set = set()

    # -- registration ------------------------------------------------------

    def register(self, runner: EngineRunner) -> None:
        with self._lock:
            self._engines[runner.engine_id] = runner

    def unregister(self, engine_id: str) -> Optional[EngineRunner]:
        with self._lock:
            return self._engines.pop(engine_id, None)

    def engines(self) -> List[EngineRunner]:
        with self._lock:
            return list(self._engines.values())

    def get(self, engine_id: str) -> Optional[EngineRunner]:
        with self._lock:
            return self._engines.get(engine_id)

    # -- strategy ----------------------------------------------------------

    def strategy(self) -> SchedulingStrategy:
        return self._strategy

    def set_strategy(self, strategy: SchedulingStrategy) -> None:
        self._strategy = strategy

    # -- routing -----------------------------------------------------------

    def statuses(self) -> List[EngineStatus]:
        return [r.status() for r in self.engines()]

    def schedule(self) -> Optional[EngineRunner]:
        """Pick an engine for the next admission batch, or None if no
        healthy engine exists (graceful failure, Property 20).

        Role-aware routing (disaggregated serving): decode-role engines
        never take admission batches — prompts go to prefill/unified
        replicas and reach decode replicas via KV handoff. If only
        decode engines are healthy (prefill fleet down), they take
        admissions anyway: a unified-decoding decode engine beats a 503.
        """
        statuses = self.statuses()
        roles = None
        if any(getattr(s, "role", "unified") == "decode" and s.healthy
               for s in statuses):
            non_decode = ("prefill", "unified")
            if any(s.healthy and getattr(s, "role", "unified") in non_decode
                   for s in statuses):
                roles = non_decode
        with self._lock:
            engine_id = choose_engine(self._strategy, statuses, self._rr,
                                      roles=roles)
            if engine_id is None:
                return None
            self._rr += 1
            return self._engines.get(engine_id)

    def schedule_decode(self, exclude: Optional[str] = None
                        ) -> Optional[EngineRunner]:
        """Pick the migration target for a finished prefill: the least-
        loaded healthy decode-role engine (``exclude`` drops the source,
        relevant only if an engine is both). None = no decode capacity —
        the caller falls back to decoding in place."""
        statuses = [s for s in self.statuses() if s.engine_id != exclude]
        engine_id = choose_engine(
            SchedulingStrategy.LEAST_LOADED, statuses, 0, roles=("decode",)
        )
        if engine_id is None:
            return None
        with self._lock:
            return self._engines.get(engine_id)

    # -- health loop -------------------------------------------------------

    def start_health_loop(self) -> None:
        if self._health_thread is not None:
            return
        self._stop.clear()
        self._health_thread = threading.Thread(
            target=self._health_loop, name="scheduler-health", daemon=True
        )
        self._health_thread.start()

    def stop_health_loop(self) -> None:
        self._stop.set()
        if self._health_thread is not None:
            self._health_thread.join(5.0)
            self._health_thread = None

    def _health_loop(self) -> None:
        while not self._stop.wait(self._interval):
            for runner in self.engines():
                if runner.is_healthy() or not self._auto_restart:
                    continue
                if runner.engine_id in self._restarting:
                    continue
                self._restarting.add(runner.engine_id)
                t = threading.Thread(
                    target=self._restart_one, args=(runner,), daemon=True
                )
                t.start()

    def _restart_one(self, runner: EngineRunner) -> None:
        try:
            runner.restart(wait_ready=True)
        except Exception:  # noqa: BLE001 — keep retrying on next sweep
            logger.exception(
                "engine %s restart failed; retrying on the next health "
                "sweep", runner.engine_id,
            )
        finally:
            self._restarting.discard(runner.engine_id)
