"""Adaptive scheduler: routes admission batches to engine replicas.

TPU-native realization of the reference's spec'd ``Scheduler`` trait and
strategies (``design.md:269-307`` [spec]; behavior ``requirements.md:92-98``):

- **round-robin** — rotate over healthy engines;
- **least-loaded** — fewest active+waiting requests (design.md:277);
- **memory-aware** — most free KV pages, i.e. the estimated batch memory
  fits where the most page capacity remains (design.md:278-280);
- runtime strategy switching (``set_strategy``, design.md:306);
- register/unregister engines at runtime (elastic scaling,
  requirements.md:110);
- health checking: unhealthy engines leave the routing set and are
  reinstated on recovery (requirements.md:97-98; Properties 18-19), with
  optional automatic restart (requirements.md:109,133).

Pure-logic core (strategy choice over ``EngineStatus`` vectors) is separated
from the threaded health loop so scheduler properties are testable without
engines, mirroring the reference's test approach (SURVEY.md §4.3).
"""

from __future__ import annotations

import enum
import logging
import random
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from distributed_inference_server_tpu.serving import faults
from distributed_inference_server_tpu.serving.health import health_rank
from distributed_inference_server_tpu.serving.metrics import (
    EngineStatus,
    MetricsCollector,
)
from distributed_inference_server_tpu.serving.runner import EngineRunner

logger = logging.getLogger(__name__)


class SchedulingStrategy(str, enum.Enum):
    ROUND_ROBIN = "round_robin"
    LEAST_LOADED = "least_loaded"
    MEMORY_AWARE = "memory_aware"
    CACHE_AWARE = "cache_aware"

    @classmethod
    def parse(cls, value: str) -> "SchedulingStrategy":
        return cls(value.strip().lower())


def health_tier(statuses: Sequence[EngineStatus]) -> List[EngineStatus]:
    """Gray-failure tiering (serving/health.py; docs/RESILIENCE.md
    "Gray failures and overload"): keep only the best health tier
    present — healthy replicas when any exist, else degraded, else
    ejected. Strict preference (not a tie-break) so a degraded replica
    takes NO new traffic while a healthy one can serve, yet Property 20
    holds absolutely: when every admissible replica is ejected they are
    all re-admitted — a possibly-sick replica beats a certain 503."""
    pool = list(statuses)
    if not pool:
        return pool
    best = min(health_rank(getattr(s, "health", "healthy")) for s in pool)
    if best == 0 and all(
            getattr(s, "health", "healthy") == "healthy" for s in pool):
        return pool  # common case: nothing demoted, no filtering cost
    return [s for s in pool
            if health_rank(getattr(s, "health", "healthy")) == best]


def prefix_match_depth(status: EngineStatus,
                       prefix_hashes: Optional[Sequence[int]]) -> int:
    """Consecutive-from-the-head pages of ``prefix_hashes`` present in an
    engine's published digest (EngineStatus.prefix_digest). A chain is
    only reusable from its head, so the first miss ends the match."""
    digest = getattr(status, "prefix_digest", None)
    if not digest or not prefix_hashes:
        return 0
    depth = 0
    for h in prefix_hashes:
        if h not in digest:
            break
        depth += 1
    return depth


@dataclass(frozen=True)
class FetchCosts:
    """Weights of the cache_aware three-way cost model (``plan_route``),
    all in PAGE units — one page of prefill recompute is the unit cost.
    Config section ``cache`` (``peer_fetch`` / ``fetch_min_pages`` /
    ``fetch_page_cost`` / ``fetch_load_cost``).

    With the defaults, fetch-to-cold beats route-to-warm exactly when
    ``load_cost_pages * (load_warm - load_cold) >
    page_cost * peer_depth`` — i.e. the warm replica is busier than
    the cold one by enough queued work to outweigh moving the chain
    over the wire — and beats recompute whenever ``page_cost < 1`` (a
    page on the wire is cheaper than re-prefilling it), which is what
    turns N per-engine caches into one fleet cache (docs/CACHING.md).
    The wire term charges the WHOLE chain (``peer_depth`` pages), not
    just the target's missing suffix: the import path needs a
    contiguous head-first tiling, so head pages the target already
    holds still cross the wire (they are dropped at publish-dedup)."""

    enabled: bool = True
    # minimum fetchable gain (pages) worth a wire transfer: tiny
    # prefixes recompute faster than they round-trip
    min_pages: int = 2
    # wire cost of moving one page, in recompute-page units (< 1 or
    # fetching never pays; int8 wire quant justifies lowering it)
    page_cost: float = 0.25
    # load penalty: one active/waiting request on the target replica
    # costs this many pages of queueing delay
    load_cost_pages: float = 4.0
    # wire cost of moving one page from a REMOTE (cross-host) peer over
    # the fleet KV data channel (config ``fleet.kv_page_cost``): the
    # member wire is slower than an in-process fetch, and the cost
    # model must stay honest about it — a remote fetch wins only when
    # the recompute/queueing gap exceeds the pricier wire term
    # (serving/fleet_kv.py; docs/FLEET.md "KV data plane")
    remote_page_cost: float = 0.6
    # encoded bytes-per-page as a fraction of raw pool bytes for the
    # configured wire encoding (kv_cache.encoded_page_fraction): the
    # wire term must charge what actually crosses the wire — an int8
    # wire already moves 3.2× fewer bytes than f32 raw and a latent
    # wire several-fold fewer still, so pricing every encoding at raw
    # pages systematically under-fetches. Scales BOTH the learned
    # (bytes/s-derived) and prior per-page costs: the learned rate is
    # raw wire throughput, so fewer bytes per page means
    # proportionally less wire time per page.
    wire_frac: float = 1.0


@dataclass(frozen=True)
class PrefixRoutePlan:
    """One cache_aware routing decision (``plan_route``): where the
    request goes and whether the target should peer-fetch the matched
    prefix first (serving/disagg.py PrefixFetcher)."""

    engine_id: str
    decision: str  # "warm" | "fetch" | "recompute"
    peer_id: Optional[str] = None  # fetch source (decision == "fetch")
    depth: int = 0  # target's own matched depth, pages
    peer_depth: int = 0  # deepest fleet match, pages
    page_size: int = 0  # page size the hashes were computed with
    prefix_hashes: Optional[Tuple[int, ...]] = None


def plan_route(
    statuses: Sequence[EngineStatus],
    prefix_hashes: Optional[Sequence[int]],
    roles: Optional[Sequence[str]] = None,
    costs: Optional[FetchCosts] = None,
    page_size: int = 0,
    wire_cost: Optional[Callable] = None,
    mesh_route: Optional[Callable] = None,
) -> Optional[PrefixRoutePlan]:
    """Three-way cache_aware routing: route-to-warm vs fetch-to-cold vs
    recompute, scored per admissible engine in page units —

        cost_route(e) = load_cost * load(e) + (pages - depth(e))
        cost_fetch(e) = load_cost * load(e) + (pages - peer_depth)
                        + page_cost * peer_depth

    where ``peer_depth`` is the deepest match anywhere in the healthy
    fleet (any role — the peer only exports, it never takes the
    request). The cheapest option wins; ties prefer route over fetch
    (no wire work for equal cost), then load, then engine id —
    deterministic given inputs, like choose_engine. The
    ``sched.fetch_decision`` fault flag (docs/RESILIENCE.md) forces the
    cheapest FETCH option when one exists, so chaos scenarios can drive
    the fetch path deterministically under random load. Returns None
    when no healthy admissible engine exists.

    ``wire_cost(target_status, peer_status) -> Optional[float]``
    (serving/fleet_mesh.py MeshWireRates via the server wiring): the
    LEARNED per-page cost of the specific (src, dst) wire a fetch
    would cross; None = the wire is cold, charge the static constant
    (``page_cost`` / ``remote_page_cost``) as the prior. A congested
    wire prices itself out of the fetch option instead of being
    guessed at the constant. ``mesh_route(target_status, peer_status)
    -> bool`` additionally admits REMOTE fetch targets when the mesh
    has introduced the (target member, peer member) pair — the member
    then pulls the chunks over its own direct wire (FleetSubmit fetch
    hint), so fetch capacity scales with member count instead of
    terminating every stream on this host."""
    costs = costs or FetchCosts()
    healthy = [s for s in statuses if s.healthy]
    admissible = (healthy if roles is None else
                  [s for s in healthy
                   if getattr(s, "role", "unified") in roles])
    if not admissible:
        return None
    # gray-failure tiering (serving/health.py): degraded replicas are
    # deprioritized, ejected ones excluded while any alternative exists
    admissible = health_tier(admissible)

    def load(s: EngineStatus) -> int:
        return s.active_requests + s.waiting_requests

    n_pages = len(prefix_hashes) if prefix_hashes else 0
    depths = {s.engine_id: prefix_match_depth(s, prefix_hashes)
              for s in healthy}
    # peer-fetch needs an engine that can SERVE an export: any local
    # replica, or a remote one whose member carries a KV data channel
    # (serving/fleet_kv.py — EngineStatus.data_plane). Control-plane-
    # only remote replicas still take warm/recompute routes (their
    # heartbeated digests score like anyone's) but never source a
    # fetch. The fetch TARGET stays local: the import seats pages into
    # this host's engine for the request this host is about to run.
    # ejected peers never source a fetch either: their wire (or their
    # engine) is exactly what the scorer judged broken
    fetchable = [s for s in healthy
                 if (not getattr(s, "remote", False)
                     or getattr(s, "data_plane", False))
                 and health_rank(getattr(s, "health", "healthy")) < 2]
    # deepest match wins; a LOCAL peer beats a remote one at equal
    # depth (cheaper wire), then load/id tie-breaks — deterministic
    peer = (min(fetchable,
                key=lambda s: (-depths[s.engine_id],
                               1 if getattr(s, "remote", False) else 0,
                               load(s), s.engine_id))
            if fetchable else None)
    peer_depth = depths[peer.engine_id] if peer is not None else 0
    peer_page_cost = (costs.remote_page_cost
                      if peer is not None
                      and getattr(peer, "remote", False)
                      else costs.page_cost)
    # warm depth anywhere ADMISSIBLE (a remote replica's heartbeated
    # digest counts for routing even though it can never source a fetch)
    best_depth = max((depths[s.engine_id] for s in admissible), default=0)
    if n_pages == 0 or (peer_depth == 0 and best_depth == 0):
        eng = min(admissible, key=lambda s: (load(s), s.engine_id))
        return PrefixRoutePlan(eng.engine_id, "recompute",
                               page_size=page_size)
    hashes = tuple(prefix_hashes)
    # (cost, route-first tie-break, load, engine_id, kind, status, depth)
    options: List[tuple] = []
    for s in admissible:
        d = depths[s.engine_id]
        base = costs.load_cost_pages * load(s)
        options.append((base + (n_pages - d), 0, load(s), s.engine_id,
                        "route", s, d))
        # remote fetch TARGETS are admissible only through the mesh:
        # the member must hold (or be introduced into) a direct wire to
        # the peer member, or the chunks would relay through this host
        target_ok = (not getattr(s, "remote", False)
                     or (mesh_route is not None and peer is not None
                         and getattr(peer, "remote", False)
                         and getattr(s, "data_plane", False)
                         and mesh_route(s, peer)))
        if (costs.enabled and peer is not None
                and s.engine_id != peer.engine_id
                and target_ok
                and peer_depth - d >= costs.min_pages):
            # the wire term charges the WHOLE chain: the fetch moves
            # pages 0..peer_depth (head-first contiguous tiling), not
            # just the target's missing suffix. The learned (src, dst)
            # wire rate prices the move when warm (wire_cost); cold
            # wires charge the configured prior — peer_page_cost: the
            # in-process rate for a local peer, fleet.kv_page_cost for
            # a cross-host one.
            per_page = (wire_cost(s, peer)
                        if wire_cost is not None else None)
            if per_page is None:
                per_page = peer_page_cost
            # charge ENCODED bytes per page: the configured wire
            # encoding (int8/latent) moves a fraction of the raw
            # bytes, and the fetch term must price that fraction or
            # the model under-fetches on every compressed wire
            per_page *= costs.wire_frac
            options.append((
                base + (n_pages - peer_depth)
                + per_page * peer_depth,
                1, load(s), s.engine_id, "fetch", s, d,
            ))
    if faults.flag("sched.fetch_decision"):
        forced = [o for o in options if o[4] == "fetch"]
        if forced:
            options = forced
    best = min(options, key=lambda o: o[:4])
    _, _, _, _, kind, s, d = best
    if kind == "fetch":
        return PrefixRoutePlan(s.engine_id, "fetch",
                               peer_id=peer.engine_id, depth=d,
                               peer_depth=peer_depth, page_size=page_size,
                               prefix_hashes=hashes)
    return PrefixRoutePlan(s.engine_id, "warm" if d > 0 else "recompute",
                           depth=d, peer_depth=peer_depth,
                           page_size=page_size, prefix_hashes=hashes)


def choose_engine(
    strategy: SchedulingStrategy,
    statuses: Sequence[EngineStatus],
    rr_counter: int,
    roles: Optional[Sequence[str]] = None,
    prefix_hashes: Optional[Sequence[int]] = None,
) -> Optional[str]:
    """Pure strategy core: pick an engine id from healthy statuses.

    Property 16: only healthy engines are eligible. Property 17:
    least-loaded picks a minimum-load engine. Deterministic given inputs.
    ``roles`` (disaggregated serving, serving/disagg.py) restricts the
    eligible set to engines carrying one of those roles; None = all.
    ``prefix_hashes`` (cache_aware; ISSUE 5) is the request's content-
    hash chain (kv_cache.chain_hashes): engines are scored by matched-
    prefix depth against their published digests, least-loaded breaking
    ties; with no digest match anywhere the strategy degrades to
    least-loaded exactly.
    """
    healthy = [s for s in statuses if s.healthy]
    if roles is not None:
        healthy = [
            s for s in healthy if getattr(s, "role", "unified") in roles
        ]
    if not healthy:
        return None
    # gray-failure tiering (serving/health.py): prefer healthy, fall
    # back to degraded, admit ejected only when nothing else exists
    healthy = health_tier(healthy)
    if strategy is SchedulingStrategy.ROUND_ROBIN:
        return healthy[rr_counter % len(healthy)].engine_id
    if strategy is SchedulingStrategy.CACHE_AWARE:
        depths = {
            s.engine_id: prefix_match_depth(s, prefix_hashes)
            for s in healthy
        }
        if any(depths.values()):
            return min(
                healthy,
                key=lambda s: (
                    -depths[s.engine_id],
                    s.active_requests + s.waiting_requests,
                    s.engine_id,
                ),
            ).engine_id
        strategy = SchedulingStrategy.LEAST_LOADED  # no warm engine
    if strategy is SchedulingStrategy.LEAST_LOADED:
        return min(
            healthy, key=lambda s: (s.active_requests + s.waiting_requests,
                                    s.engine_id)
        ).engine_id
    # memory-aware: most effectively-free pages. Cached (refcount-0
    # prefix) pages are reclaimable on demand, so they count as free
    # capacity: score on used - cached. Tie-break on load then id.
    return min(
        healthy,
        key=lambda s: (
            -(s.memory_total_pages
              - (s.memory_used_pages - getattr(s, "pages_cached", 0))),
            s.active_requests + s.waiting_requests,
            s.engine_id,
        ),
    ).engine_id


class AdaptiveScheduler:
    """Thread-safe scheduler over registered ``EngineRunner`` replicas."""

    def __init__(
        self,
        strategy: SchedulingStrategy = SchedulingStrategy.LEAST_LOADED,
        health_check_interval_s: float = 1.0,
        auto_restart: bool = False,
        metrics: Optional[MetricsCollector] = None,
        restart_backoff_s: float = 1.0,
        restart_backoff_max_s: float = 30.0,
        fetch_costs: Optional[FetchCosts] = None,
    ):
        """``restart_backoff_s``/``restart_backoff_max_s``: after a
        FAILED restart the next attempt waits ``backoff`` (doubling per
        consecutive failure, jittered, capped at the max) instead of
        retrying every health sweep — a crash-looping engine factory
        must not hot-spin the health loop (docs/RESILIENCE.md).
        ``fetch_costs``: weights of the cache_aware three-way cost model
        (``plan_route``; None = defaults)."""
        self._strategy = strategy
        self._fetch_costs = fetch_costs or FetchCosts()
        self._engines: Dict[str, EngineRunner] = {}
        self._lock = threading.Lock()
        self._rr = 0
        self._interval = health_check_interval_s
        self._auto_restart = auto_restart
        self.metrics = metrics
        self._backoff_base = restart_backoff_s
        self._backoff_cap = restart_backoff_max_s
        # engine_id -> (not_before monotonic time, last delay); guarded
        # by _lock (written from restart threads, read by the health
        # loop)
        self._backoff: Dict[str, Tuple[float, float]] = {}
        self._stop = threading.Event()
        self._health_thread: Optional[threading.Thread] = None
        # engines with a restart worker in flight; guarded by _lock
        # (health loop adds, restart threads discard — distlint DL008)
        self._restarting: set = set()
        # gray-failure scorer (serving/health.py), wired by the server:
        # statuses() stamps its verdicts so every strategy applies the
        # health tiering. Single-writer (server boot), read per snapshot
        # distlint: ignore[DL008]
        self.health_scorer = None
        # learned wire pricing + mesh routing (serving/fleet_mesh.py),
        # wired by the server on the registry host: wire_cost prices
        # the (src, dst) wire a fetch/handoff would cross, mesh_route
        # admits remote fetch targets whose member holds a direct wire
        # to the peer. Single-writer (server boot)
        # distlint: ignore[DL008]
        self.wire_cost = None
        # distlint: ignore[DL008]
        self.mesh_route = None

    # -- registration ------------------------------------------------------

    def register(self, runner: EngineRunner) -> None:
        with self._lock:
            self._engines[runner.engine_id] = runner

    def unregister(self, engine_id: str) -> Optional[EngineRunner]:
        with self._lock:
            return self._engines.pop(engine_id, None)

    def unregister_if(self, engine_id: str,
                      runner: EngineRunner) -> Optional[EngineRunner]:
        """Unregister ``engine_id`` only while it still maps to THIS
        runner object — a detach racing a reconnect must not evict the
        fresh proxy a new session just registered under the same id
        (serving/fleet.py member sessions)."""
        with self._lock:
            if self._engines.get(engine_id) is runner:
                return self._engines.pop(engine_id)
            return None

    def engines(self) -> List[EngineRunner]:
        with self._lock:
            return list(self._engines.values())

    def get(self, engine_id: str) -> Optional[EngineRunner]:
        with self._lock:
            return self._engines.get(engine_id)

    # -- strategy ----------------------------------------------------------

    def strategy(self) -> SchedulingStrategy:
        return self._strategy

    def set_strategy(self, strategy: SchedulingStrategy) -> None:
        self._strategy = strategy

    # -- routing -----------------------------------------------------------

    def statuses(self) -> List[EngineStatus]:
        out = [r.status() for r in self.engines()]
        if self.health_scorer is not None:
            out = self.health_scorer.stamp(out)
        return out

    def schedule(self, prompt_ids: Optional[Sequence[int]] = None
                 ) -> Optional[EngineRunner]:
        """Pick an engine for the next admission batch, or None if no
        healthy engine exists (graceful failure, Property 20).

        Role-aware routing (disaggregated serving): decode-role engines
        never take admission batches — prompts go to prefill/unified
        replicas and reach decode replicas via KV handoff. If only
        decode engines are healthy (prefill fleet down), they take
        admissions anyway: a unified-decoding decode engine beats a 503.

        ``prompt_ids`` (cache_aware routing, ISSUE 5): the request's
        token ids — its content-hash chain is scored against each
        engine's published prefix digest, so a request lands where its
        prefix is already warm. Disagg role restriction composes: the
        warm engine is picked among prefill/unified candidates.
        """
        return self.schedule_batch([prompt_ids])[0]

    def _admission_roles(
        self, statuses: Sequence[EngineStatus]
    ) -> Optional[Tuple[str, ...]]:
        """Role restriction for admission batches (disaggregated
        serving): decode-role engines never take admissions while a
        prefill/unified replica is healthy."""
        if any(getattr(s, "role", "unified") == "decode" and s.healthy
               for s in statuses):
            non_decode = ("prefill", "unified")
            if any(s.healthy and getattr(s, "role", "unified") in non_decode
                   for s in statuses):
                return non_decode
        return None

    def schedule_batch(
        self, prompts: Sequence[Optional[Sequence[int]]]
    ) -> List[Optional["EngineRunner"]]:
        """One pick per prompt against ONE fleet snapshot. Cache-aware
        admission routes per request, and a per-request ``statuses()``
        (engine cache/host-tier/spec stats plus metrics gauge writes,
        per runner) would scale requests × replicas on the dispatch hot
        path; choose_engine is pure, so every request in the window
        scores against the same snapshot."""
        statuses = self.statuses()
        roles = self._admission_roles(statuses)
        hash_ps = digest_depth = 0
        if self._strategy is SchedulingStrategy.CACHE_AWARE:
            from distributed_inference_server_tpu.engine.kv_cache import (
                DIGEST_DEPTH,
                chain_hashes,
            )

            # hash with the fleet's page size and published digest depth
            # (replicas share one engine config; a 0 page_size means no
            # engine has reported yet) — a cache.digest_depth deeper
            # than the default must widen THIS path's scoring window
            # too, or redispatch/fetcher-less routing flattens exactly
            # the deep matches the config asked to see
            hash_ps = next(
                (s.page_size for s in statuses
                 if s.healthy and getattr(s, "page_size", 0) > 0), 0,
            )
            digest_depth = next(
                (s.digest_depth for s in statuses
                 if s.healthy and getattr(s, "digest_depth", 0) > 0),
                DIGEST_DEPTH,
            )
        out: List[Optional["EngineRunner"]] = []
        with self._lock:
            for prompt_ids in prompts:
                prefix_hashes = None
                if hash_ps > 0 and prompt_ids:
                    prefix_hashes = chain_hashes(prompt_ids, hash_ps,
                                                 max_pages=digest_depth)
                engine_id = choose_engine(self._strategy, statuses,
                                          self._rr, roles=roles,
                                          prefix_hashes=prefix_hashes)
                if engine_id is None:
                    out.append(None)
                    continue
                self._rr += 1
                out.append(self._engines.get(engine_id))
        return out

    def schedule_batch_plans(
        self, prompts: Sequence[Optional[Sequence[int]]]
    ) -> List[Tuple[Optional["EngineRunner"], Optional[PrefixRoutePlan]]]:
        """Cache-aware dispatch with the three-way cost model
        (``plan_route``): one ``(runner, plan)`` per prompt against ONE
        fleet snapshot. ``plan.decision == "fetch"`` tells the
        dispatcher to peer-fetch the matched prefix chain onto the
        chosen (cold) replica before submitting (docs/CACHING.md
        "Fleet-wide prefix sharing"); "warm"/"recompute" submit
        directly. Prompt hashing is capped at the fleet's published
        digest depth and at the prompt's own penultimate page (at least
        one token is always recomputed, so a whole-prompt fetch would
        seat a page the prefill can never share)."""
        statuses = self.statuses()
        roles = self._admission_roles(statuses)
        from distributed_inference_server_tpu.engine.kv_cache import (
            DIGEST_DEPTH,
            chain_hashes,
        )

        hash_ps = next(
            (s.page_size for s in statuses
             if s.healthy and getattr(s, "page_size", 0) > 0), 0,
        )
        digest_depth = next(
            (s.digest_depth for s in statuses
             if s.healthy and getattr(s, "digest_depth", 0) > 0),
            DIGEST_DEPTH,
        )
        out: List[Tuple[Optional["EngineRunner"],
                        Optional[PrefixRoutePlan]]] = []
        with self._lock:
            for prompt_ids in prompts:
                prefix_hashes = None
                if hash_ps > 0 and prompt_ids:
                    cap = (len(prompt_ids) - 1) // hash_ps
                    prefix_hashes = chain_hashes(
                        prompt_ids, hash_ps,
                        max_pages=min(digest_depth, cap),
                    )
                plan = plan_route(statuses, prefix_hashes, roles=roles,
                                  costs=self._fetch_costs,
                                  page_size=hash_ps,
                                  wire_cost=self.wire_cost,
                                  mesh_route=self.mesh_route)
                if plan is None:
                    out.append((None, None))
                    continue
                out.append((self._engines.get(plan.engine_id), plan))
        return out

    def schedule_decode(self, exclude: Optional[str] = None,
                        pages: int = 0) -> Optional[EngineRunner]:
        """Pick the migration target for a finished prefill: the least-
        loaded healthy decode-role engine (``exclude`` drops the source,
        relevant only if an engine is both). None = no decode capacity —
        the caller falls back to decoding in place. Remote replicas
        qualify when their member carries a KV data channel
        (``supports_kv_import``, serving/fleet_kv.py) — the two-phase
        import stream then runs over the wire; control-plane-only
        remotes stay excluded (no way to move the pages).

        With ``wire_cost`` wired and ``pages`` known (the handoff's
        prefix size), the election charges each remote candidate the
        LEARNED cost of moving the pages over its wire (serving/
        fleet_mesh.py) in the same page units ``plan_route`` uses —
        a congested wire loses the election to a slightly-busier local
        engine instead of being picked at the static constant."""
        candidates = [
            r for r in self.engines()
            if r.engine_id != exclude
            and (not getattr(r, "is_remote", False)
                 or getattr(r, "supports_kv_import", False))
        ]
        statuses = [r.status() for r in candidates]
        if self.health_scorer is not None:
            # health tiering applies to migration targets too — and
            # supports_kv_import above already excludes members whose
            # data-channel breaker is OPEN (serving/health.py)
            statuses = self.health_scorer.stamp(statuses)
        if self.wire_cost is not None and pages > 0:
            decode = [s for s in statuses if s.healthy
                      and getattr(s, "role", "unified") == "decode"]
            if not decode:
                return None
            costs = self._fetch_costs

            def score(s: EngineStatus):
                wire_pages = 0.0
                if getattr(s, "remote", False):
                    # this host is the handoff source: the wire is
                    # (registry -> member); cold wires charge the prior
                    per_page = self.wire_cost(s, None)
                    if per_page is None:
                        per_page = costs.remote_page_cost
                    # the handoff wire ships encoded pages too — the
                    # election charges encoded bytes, like plan_route
                    wire_pages = per_page * costs.wire_frac * pages
                return (health_rank(getattr(s, "health", "healthy")),
                        costs.load_cost_pages
                        * (s.active_requests + s.waiting_requests)
                        + wire_pages,
                        s.engine_id)

            engine_id = min(decode, key=score).engine_id
        else:
            engine_id = choose_engine(
                SchedulingStrategy.LEAST_LOADED, statuses, 0,
                roles=("decode",)
            )
        if engine_id is None:
            return None
        with self._lock:
            return self._engines.get(engine_id)

    # -- health loop -------------------------------------------------------

    def start_health_loop(self) -> None:
        if self._health_thread is not None:
            return
        self._stop.clear()
        # lifecycle handle: start/stop are orchestrator calls, not
        # concurrent paths  # distlint: ignore[DL008]
        self._health_thread = threading.Thread(
            target=self._health_loop, name="scheduler-health", daemon=True
        )
        self._health_thread.start()

    def stop_health_loop(self) -> None:
        self._stop.set()
        if self._health_thread is not None:
            self._health_thread.join(5.0)
            self._health_thread = None

    def _health_loop(self) -> None:
        while not self._stop.wait(self._interval):
            for runner in self.engines():
                if not getattr(runner, "supports_restart", True):
                    # RemoteRunner proxies (serving/remote_runner.py):
                    # their member's own health loop restarts the real
                    # engine; the registry ages the proxy out instead
                    continue
                healthy = runner.is_healthy()
                if healthy and self._auto_restart and faults.flag(
                        "sched.health_flap"):
                    # injected health flap (docs/RESILIENCE.md): the
                    # loop sees a live replica as down for one sweep and
                    # restarts it — the chaos path for "monitoring lied"
                    logger.warning("injected health flap: restarting "
                                   "healthy engine %s", runner.engine_id)
                    healthy = False
                if healthy or not self._auto_restart:
                    continue
                with self._lock:
                    # membership check and add under one lock hold: a
                    # restart worker's discard must not interleave with
                    # the check-then-add (distlint DL008)
                    if runner.engine_id in self._restarting:
                        continue
                    not_before = self._backoff.get(
                        runner.engine_id, (0.0, 0.0))[0]
                    if time.monotonic() < not_before:
                        continue  # backing off after a failed restart
                    self._restarting.add(runner.engine_id)
                t = threading.Thread(
                    target=self._restart_one, args=(runner,), daemon=True
                )
                t.start()

    def _restart_one(self, runner: EngineRunner) -> None:
        eid = runner.engine_id
        if self.metrics:
            self.metrics.record_engine_restart(eid)
        try:
            runner.restart(wait_ready=True)
        except Exception:  # noqa: BLE001 — retry after backoff
            with self._lock:
                last = self._backoff.get(eid, (0.0, 0.0))[1]
                delay = (self._backoff_base if last <= 0.0
                         else min(last * 2.0, self._backoff_cap))
                # jitter up to +25% so a fleet of replicas that died
                # together does not retry (and re-fail) in lockstep
                wake = delay * (1.0 + 0.25 * random.random())
                self._backoff[eid] = (time.monotonic() + wake, delay)
            logger.exception(
                "engine %s restart failed; next attempt in %.1fs "
                "(backoff %.1fs, cap %.1fs)", eid, wake, delay,
                self._backoff_cap,
            )
        else:
            with self._lock:
                self._backoff.pop(eid, None)
        finally:
            with self._lock:
                self._restarting.discard(eid)
