"""Registry HA: a warm-standby control plane with lease-fenced failover.

The registry host used to be the fleet's single point of failure: it
ingests heartbeats, merges telemetry and spans, pumps remote events,
brokers mesh intros, and fronts admission — one host dying took the
whole fleet dark. This module removes that: ``fleet.registries`` names
an ORDERED list of registry endpoints, every worker dual-heartbeats all
of them (serving/remote_runner.py), and the registries heartbeat EACH
OTHER over the same fleet wire to elect a lease-fenced primary.

Three mechanisms, each deliberately reusing existing machinery:

**Dual-heartbeat.** Workers keep one fleet connection per registry and
ship heartbeats + telemetry + spans to all of them, so every registry
holds a live member table, materialized RemoteRunner proxies, and
learned wire rates at all times. A standby is WARM: takeover re-arms
nothing about the data path because the data path never went cold.

**Lease + epoch fencing.** The primary sends a ``RegistryLease`` beat
(fleet-wire frame kind 7) to every peer each tick; standbys answer with
``RegistryState`` echoes (kind 8). A standby ages the primary's lease
through the SAME alive -> suspect -> dead machinery used on members (an
embedded :class:`~.fleet.FleetRegistry` with ``lease_suspect_s`` /
``lease_s`` as its aging windows) and promotes itself when the lease
dies — bumping a monotonic EPOCH. Every control frame a registry sends
(FleetSubmit routing, aborts, KvIntro brokering) carries its epoch, and
members accept control only from the highest epoch they have seen: a
partitioned old primary's submits bounce as ``worker_failure`` errors
(redispatching on ITS side, bounded by the usual budget), and the
moment it sees the higher epoch it demotes to standby — fenced, never
split-brained. Ties at the same epoch break on list order (the lower
index wins), and a standby only promotes when no fresher lower-index
standby is visible, so a cold-started cluster elects ``registries[0]``.

**Multi-ingress.** Any registry — primary or standby — serves HTTP
against its own federated view; members execute ``FleetSubmit`` frames
arriving on any registry wire and stream events back on the wire they
arrived on. Losing either front door loses no capacity. (Set
``fleet.standby_http=false`` to keep standbys' front doors closed until
they hold the lease — the dispatcher then rejects ingress as QueueFull.)

Fault points (docs/RESILIENCE.md): ``fleet.lease_beat`` drops
registry->registry lease beats before the wire (the partition model —
arming it with prob=1.0 manufactures a split-brain without killing
anyone); ``fleet.takeover`` crashes a standby mid-promotion — BEFORE
any state changed, so the promotion simply retries next tick (the
takeover is atomic-or-absent).

Verified by: tests/test_fleet.py (lease expiry promotion, epoch
fencing, index tie-breaks), the ``registry_failover`` /
``registry_split_brain`` chaos scenarios (tools/chaos_fleet.py), and
the live three-process HA leg of tools/fleet_smoke.py (SIGKILL the
primary mid-traffic; docs/FLEET.md "Registry HA").
"""

from __future__ import annotations

import logging
import socket
import threading
import time
from typing import Any, Dict, List, Optional

from distributed_inference_server_tpu.serving import faults
from distributed_inference_server_tpu.serving.fleet import (
    MEMBER_DEAD,
    FleetRegistry,
    FleetSettings,
    FleetWireError,
    parse_connect,
    send_frame,
)
from distributed_inference_server_tpu.serving.metrics import MetricsCollector

logger = logging.getLogger(__name__)

ROLE_PRIMARY = "primary"
ROLE_STANDBY = "standby"


class _PeerLink:
    """One outbound registry->registry wire, send-only. The peer's
    member listener accepts it like any member connection; our lease /
    state frames route to its HA module via ``on_registry_frame`` (the
    session never claims a member id, so peer wires cannot fabricate
    fleet members). Send-only on purpose: the peer's frames to US
    arrive on OUR listener the same way, so neither side ever blocks a
    tick reading. Dials lazily with per-link backoff — a dead peer
    costs one failed send per tick, never a stall."""

    def __init__(self, endpoint: str, dial_timeout_s: float = 1.0):
        self.endpoint = endpoint
        self.host, self.port = parse_connect(endpoint)
        self.dial_timeout_s = dial_timeout_s
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()
        self._backoff_s = 0.25
        self._next_dial = 0.0

    def send(self, name: str, obj: Dict[str, Any]) -> bool:
        """Best-effort frame send; False = not delivered (dead peer in
        dial backoff, or the write failed and the wire was dropped).
        Only the HA tick thread calls this; ``close`` (stop path) joins
        that thread first, so the dial below never races a close."""
        with self._lock:
            sock = self._sock
            if sock is None and time.monotonic() < self._next_dial:
                return False
        if sock is None:
            try:
                # short-timeout dial on the HA tick thread, outside the
                # lock: bounded by dial_timeout_s, one peer set deep
                sock = socket.create_connection(  # distlint: ignore[DL001]
                    (self.host, self.port), timeout=self.dial_timeout_s)
            except OSError:
                with self._lock:
                    self._next_dial = time.monotonic() + self._backoff_s
                    self._backoff_s = min(self._backoff_s * 2, 2.0)
                return False
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                sock.close()
                return False
            with self._lock:
                self._backoff_s = 0.25
                self._sock = sock
        try:
            send_frame(sock, name, obj)
            return True
        except (OSError, FleetWireError):
            try:
                sock.close()
            except OSError:
                pass
            with self._lock:
                if self._sock is sock:
                    self._sock = None
            return False

    def connected(self) -> bool:
        with self._lock:
            return self._sock is not None

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None


class RegistryHA:
    """The per-registry HA state machine: role, epoch, the lease watch,
    and the registry<->registry beat loop. Owned by the server when
    ``fleet.registries`` is configured; the FleetServer routes inbound
    peer frames here (``on_peer_frame``) and reads ``epoch`` /
    ``is_primary`` for control-frame stamping and primary-only gates.

    Every registry BOOTS as standby — including a restarted old
    primary, which therefore rejoins fenced (epoch 0 < cluster epoch)
    and only ever re-promotes by winning a real election. ``start`` /
    ``stop`` are restartable and reset all election state, modeling a
    process restart."""

    def __init__(
        self,
        fleet_server,
        settings: Optional[FleetSettings] = None,
        metrics: Optional[MetricsCollector] = None,
        recorder=None,
    ):
        self.fleet_server = fleet_server
        self.settings = settings or FleetSettings()
        self.metrics = metrics
        self.recorder = recorder
        self.registry_id = ""
        self.role = ROLE_STANDBY
        self.epoch = 0
        self._index = len(self.settings.registries)
        self._endpoint_index: Dict[str, int] = {}
        self._seq = 0
        self._lease_holder: Optional[str] = None
        self._lease_rx_at = time.monotonic()
        self._peers: List[_PeerLink] = []
        # peer registry id -> {role, epoch, at, index}: the freshest
        # frame seen from each peer (any kind), for election deferral
        # and the /server/stats registry block
        self._peer_states: Dict[str, Dict[str, Any]] = {}
        self._takeovers: Dict[str, int] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # the lease watch: the ISSUE's "reuse the aging machinery on
        # the primary itself" — a private FleetRegistry whose only
        # member is the current lease holder, aged alive -> suspect
        # (lease_suspect_s) -> dead (lease_s) by our own tick
        self._lease_watch = FleetRegistry(FleetSettings(
            heartbeat_interval_s=self.settings.heartbeat_interval_s,
            suspect_after_s=self.settings.lease_suspect_s,
            dead_after_s=self.settings.lease_s,
        ))

    # -- lifecycle ---------------------------------------------------------

    def start(self, self_endpoint: str) -> None:
        """Begin the beat loop. ``self_endpoint`` is this registry's
        fleet listener as "host:port" (the BOUND port — known only
        after FleetServer.start). Matched against fleet.registries to
        find our election priority; an endpoint not on the list still
        works, at the lowest priority."""
        if self._thread is not None:
            return
        me = parse_connect(self_endpoint)
        endpoints = list(self.settings.registries)
        with self._lock:
            # a (re)start models a process restart: all election state
            # resets, and the cluster epoch is re-learned from peers
            self.registry_id = self_endpoint
            self.role = ROLE_STANDBY
            self.epoch = 0
            self._seq = 0
            self._lease_holder = None
            self._lease_rx_at = time.monotonic()
            self._peer_states.clear()
            self._takeovers.clear()
            self._index = len(endpoints)
            self._endpoint_index = {ep: i for i, ep in enumerate(endpoints)}
            peers = []
            for i, ep in enumerate(endpoints):
                if parse_connect(ep) == me:
                    self._index = i
                    self.registry_id = ep  # canonical config-list form
                else:
                    peers.append(_PeerLink(ep))
            self._peers = peers
        self._lease_watch = FleetRegistry(FleetSettings(
            heartbeat_interval_s=self.settings.heartbeat_interval_s,
            suspect_after_s=self.settings.lease_suspect_s,
            dead_after_s=self.settings.lease_s,
        ))
        self._publish()
        self._stop.clear()
        # lifecycle handle  # distlint: ignore[DL008]
        self._thread = threading.Thread(
            target=self._loop, name="fleet-ha", daemon=True
        )
        self._thread.start()
        logger.info("registry HA %s: standby (priority %d of %d)",
                    self.registry_id, self._index, len(endpoints))

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None
        for link in self._peers:
            link.close()

    def _loop(self) -> None:
        while not self._stop.wait(self.settings.heartbeat_interval_s):
            try:
                self._tick()
            except faults.InjectedFault:
                # fleet.takeover: crashed mid-promotion. The fault
                # fires BEFORE any state changes, so nothing to unwind
                # — the standby simply retries next tick
                logger.warning("registry HA %s: injected takeover crash; "
                               "retrying", self.registry_id)
            except Exception:  # noqa: BLE001 — the beat loop must live
                logger.exception("registry HA tick failed; retrying")

    # -- the beat (tick thread) --------------------------------------------

    def _tick(self, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        if self.role == ROLE_PRIMARY:
            with self._lock:
                self._seq += 1
                frame = {"registry_id": self.registry_id,
                         "epoch": self.epoch, "seq": self._seq,
                         "role": ROLE_PRIMARY}
            for link in self._peers:
                try:
                    # injected registry<->registry partition: the beat
                    # is dropped before the wire (RESILIENCE.md
                    # fleet.lease_beat) — fired per peer, per tick
                    faults.fire("fleet.lease_beat")
                except faults.InjectedFault:
                    continue
                link.send("RegistryLease", frame)
        else:
            with self._lock:
                frame = {"registry_id": self.registry_id,
                         "epoch": self.epoch, "role": ROLE_STANDBY}
            for link in self._peers:
                link.send("RegistryState", frame)
            self._lease_watch.sweep(now)
            self._maybe_promote(now)

    def _lease_expired(self, now: float) -> bool:
        with self._lock:
            holder = self._lease_holder
            rx_at = self._lease_rx_at
        if holder is None:
            # never held since (re)start: the boot grace is one full
            # lease window, so a healthy primary always beats first
            return now - rx_at > self.settings.lease_s
        state = self._lease_watch.member_state(holder)
        return state is None or state == MEMBER_DEAD

    def _maybe_promote(self, now: float) -> None:
        if not self._lease_expired(now):
            return
        with self._lock:
            # election deferral: a FRESH lower-index peer (frame seen
            # within one lease window) outranks us — it will promote;
            # if it's actually dead its frames age out and we stop
            # deferring. registries[0] defers to nobody.
            for st in self._peer_states.values():
                if (st["index"] < self._index
                        and now - st["at"] <= self.settings.lease_s):
                    return
        self._promote("lease_expired")

    def _promote(self, reason: str) -> None:
        # the injected mid-promotion crash (RESILIENCE.md
        # fleet.takeover) fires BEFORE any state changes: promotion is
        # atomic-or-absent, and the next tick retries it
        faults.fire("fleet.takeover")
        with self._lock:
            peer_max = max(
                (st.get("epoch", 0) for st in self._peer_states.values()),
                default=0)
            self.epoch = max(self.epoch, peer_max) + 1
            self.role = ROLE_PRIMARY
            self._lease_holder = None
            self._seq = 0
            self._takeovers[reason] = self._takeovers.get(reason, 0) + 1
            epoch = self.epoch
        logger.warning("registry HA %s: PROMOTED to primary (epoch %d, "
                       "%s)", self.registry_id, epoch, reason)
        self._publish()
        if self.metrics is not None:
            self.metrics.record_registry_takeover(reason)
        if self.recorder is not None:
            self.recorder.note_global("registry_takeover", reason=reason,
                                      epoch=epoch)
        # re-arm the primary-only machinery from our already-warm
        # state: re-broker every known mesh endpoint at the NEW epoch
        # (admission, routing, and the event pump were never gated)
        self.fleet_server.on_ha_promote()

    def _demote_locked(self, peer_epoch: int, reason: str) -> int:
        """Fencing: a higher epoch (or a same-epoch, higher-priority
        primary) exists — step down. Caller holds ``_lock``; returns
        the new epoch (0 = no demotion happened)."""
        self.epoch = max(self.epoch, peer_epoch)
        self.role = ROLE_STANDBY
        self._takeovers[reason] = self._takeovers.get(reason, 0) + 1
        return self.epoch

    # -- inbound peer frames (member-session reader threads) ---------------

    def on_peer_frame(self, name: str, obj: Dict[str, Any]) -> None:
        """One RegistryLease / RegistryState frame from a peer registry
        (routed here by FleetServer.on_registry_frame)."""
        rid = obj.get("registry_id", "")
        if not rid or rid == self.registry_id:
            return
        epoch = int(obj.get("epoch") or 0)
        role = obj.get("role", "")
        now = time.monotonic()
        accepted = False
        demoted = 0
        with self._lock:
            idx = self._endpoint_index.get(rid, len(self._endpoint_index))
            self._peer_states[rid] = {"role": role, "epoch": epoch,
                                      "at": now, "index": idx}
            if name == "RegistryLease":
                if self.role == ROLE_PRIMARY and (
                        epoch > self.epoch
                        or (epoch == self.epoch and idx < self._index)):
                    # fenced: a newer (or same-epoch, higher-priority)
                    # primary exists — we were the partitioned one
                    demoted = self._demote_locked(epoch, "fenced")
                if self.role == ROLE_STANDBY and epoch >= self.epoch:
                    # accept the lease (possibly the one that just
                    # fenced us): refresh the watch and learn the epoch
                    self.epoch = epoch
                    self._lease_holder = rid
                    self._lease_rx_at = now
                    accepted = True
                # a STALE lease (epoch < ours) is ignored entirely: the
                # old primary demotes when our frames reach it
            else:  # RegistryState
                if epoch > self.epoch:
                    if self.role == ROLE_PRIMARY:
                        # a standby already saw a newer primary than us
                        demoted = self._demote_locked(epoch, "fenced")
                    else:
                        self.epoch = epoch
        if accepted:
            # the lease watch is the member-aging machinery verbatim:
            # each accepted beat is an observe(), our tick sweeps
            self._lease_watch.observe(rid, [])
        if demoted:
            logger.warning("registry HA %s: FENCED by %s — demoted to "
                           "standby (epoch %d)", self.registry_id, rid,
                           demoted)
            self._publish()
            if self.metrics is not None:
                self.metrics.record_registry_takeover("fenced")
            if self.recorder is not None:
                self.recorder.note_global("registry_fenced", peer=rid,
                                          epoch=demoted)

    # -- reads (any thread) ------------------------------------------------

    def is_primary(self) -> bool:
        return self.role == ROLE_PRIMARY

    def _publish(self) -> None:
        if self.metrics is not None:
            self.metrics.set_registry_role(self.role)
            self.metrics.set_registry_epoch(self.epoch)

    def stats(self) -> Dict[str, Any]:
        """The ``registry`` block of ``/server/stats``: role, epoch,
        lease age + holder state, peer-registry views, and takeover
        counts (docs/FLEET.md "Registry HA")."""
        now = time.monotonic()
        with self._lock:
            holder = self._lease_holder
            peers = {
                rid: {"role": st["role"], "epoch": st["epoch"],
                      "age_s": round(now - st["at"], 3)}
                for rid, st in sorted(self._peer_states.items())
            }
            out = {
                "registry_id": self.registry_id,
                "role": self.role,
                "epoch": self.epoch,
                "lease": {
                    "holder": holder,
                    "age_s": round(now - self._lease_rx_at, 3),
                },
                "peers": peers,
                "takeovers": dict(self._takeovers),
            }
        out["lease"]["state"] = (
            self._lease_watch.member_state(holder) if holder else None)
        return out
