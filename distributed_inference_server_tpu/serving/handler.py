"""Inference handler: the endpoint-facing request lifecycle.

Realizes the reference's spec'd ``InferenceHandler`` trait — ``generate``,
``generate_stream``, ``chat``, ``chat_stream``, ``embeddings``
(``design.md:147-155`` [spec]) — over the serving spine:

    parse JSON → validate (400) → tokenize → submit to dispatcher
    (503 on backpressure) → await sink (408 on queue timeout) → build
    OpenAI-style response (SURVEY.md §3.2-3.3 call stacks)

Transport-agnostic: the aiohttp layer (serving/app.py) only does HTTP/SSE
framing around these coroutines, so conformance tests drive the handler
directly without sockets.
"""

from __future__ import annotations

import asyncio
import time
from typing import AsyncIterator, List, Optional, Tuple

from distributed_inference_server_tpu.core.errors import (
    AdmissionShedApiError,
    ApiError,
    InternalApiError,
    QueueFull,
    QueueFullApiError,
    RequestTimeoutApiError,
    ValidationApiError,
    ValidationError,
)
from distributed_inference_server_tpu.serving.health import AdmissionShed
from distributed_inference_server_tpu.core.models import (
    ChatMessage,
    ChatChoice,
    ChatRequest,
    ChatResponse,
    EmbeddingData,
    EmbeddingsRequest,
    EmbeddingsResponse,
    GenerateChoice,
    GenerateRequest,
    GenerateResponse,
    Role,
    TokenEvent,
    Usage,
)
from distributed_inference_server_tpu.core.types import (
    Priority,
    RequestId,
    new_request_id,
)
from distributed_inference_server_tpu.core.validator import RequestValidator
from distributed_inference_server_tpu.engine.engine import SamplingParams
from distributed_inference_server_tpu.models.tokenizer import (
    Tokenizer,
    chat_template_family,
    render_chat,
)
from distributed_inference_server_tpu.serving.dispatcher import Dispatcher
from distributed_inference_server_tpu.serving.metrics import MetricsCollector
from distributed_inference_server_tpu.serving.runner import ServerRequest
from distributed_inference_server_tpu.serving.streamer import (
    CollectingSink,
    StreamingSink,
)


def _tenant_of(obj: dict) -> str:
    """Per-tenant fair admission key (core/queue.py DRR): the request
    body's optional ``tenant`` field; absent/blank = "default". A
    non-string value is coerced — admission fairness must never 400 a
    request that validated."""
    tenant = obj.get("tenant") if isinstance(obj, dict) else None
    if not tenant:
        return "default"
    return str(tenant)[:128]


def _error_to_api(message: str, code: str) -> ApiError:
    if code in ("request_timeout", "queue_timeout"):
        # queue_timeout: the dispatcher sweep expired the request before
        # any engine started it (serving/dispatcher.py _sweep) — same
        # 408 surface, distinct code on the error body
        return RequestTimeoutApiError()
    return InternalApiError(message)


class InferenceHandler:
    """Endpoint logic shared by HTTP and test drivers."""

    def __init__(
        self,
        dispatcher: Dispatcher,
        tokenizer: Tokenizer,
        model_name: str,
        validator: Optional[RequestValidator] = None,
        metrics: Optional[MetricsCollector] = None,
        tracer=None,
        recorder=None,
    ):
        """``recorder``: the per-request FlightRecorder
        (serving/flightrec.py) — admission opens the request's timeline
        here; the rest of the spine notes into it. None = disabled."""
        self.dispatcher = dispatcher
        self.tok = tokenizer
        self.model_name = model_name
        self.validator = validator or RequestValidator()
        self.metrics = metrics
        self.tracer = tracer
        self.recorder = recorder
        # request_id -> (span, monotonic insert time). Entries are popped on
        # completion; the TTL sweep in _submit covers streaming generators
        # that are created but never iterated (their finally never runs).
        self._spans_by_request = {}
        self._span_ttl_s = 3600.0

    # -- shared internals --------------------------------------------------

    @property
    def chat_family(self) -> str:
        """Chat-template family the FALLBACK path would use for the
        current model name. Introspection only — the request path goes
        through render_chat, which prefers the checkpoint's own template
        (carried on the tokenizer) and re-derives the family itself."""
        return chat_template_family(self.model_name)

    def _params(self, max_tokens: int, temperature: float, top_p: float,
                stop_sequences: List[str]) -> SamplingParams:
        return SamplingParams(
            max_tokens=max_tokens,
            temperature=temperature,
            top_p=top_p,
            stop_sequences=tuple(stop_sequences),
        )

    def _submit(
        self,
        prompt_ids: List[int],
        params: SamplingParams,
        sink,
        priority: Priority,
        endpoint: str = "generate",
        tenant: str = "default",
    ) -> RequestId:
        request_id = new_request_id()
        span = None
        if self.tracer:
            # request-lifecycle root span (S12, requirements.md:122);
            # finished by _finish_span at completion/stream end
            span = self.tracer.start(
                f"request.{endpoint}", request_id=str(request_id),
                prompt_tokens=len(prompt_ids), priority=priority.name,
            )
        req = ServerRequest(request_id, prompt_ids, params, sink, span=span,
                            tenant=tenant)
        if self.metrics:
            self.metrics.request_started()
        try:
            self.dispatcher.submit(req, priority)
            if span is not None:
                span.event("queued")
        except AdmissionShed as e:
            # deadline-aware shed (serving/health.py): 503 with the
            # DISTINCT admission_shed code and a Retry-After hint — the
            # dispatcher already recorded the flight-recorder terminal
            # and requests_shed_total{tenant,reason}
            if self.metrics:
                self.metrics.request_finished()
            if span is not None:
                self.tracer.finish(span, status="shed")
            raise AdmissionShedApiError(e.retry_after_s) from None
        except QueueFull:
            if self.metrics:
                self.metrics.request_finished()
            if span is not None:
                self.tracer.finish(span, status="rejected")
            raise QueueFullApiError() from None
        if span is not None:
            self._sweep_stale_spans()
            self._spans_by_request[request_id] = (span, time.monotonic())
        if self.recorder is not None:
            # the flight-recorder timeline opens at admission; the
            # trace_id links it to the stitched span tree
            self.recorder.admit(
                request_id, endpoint=endpoint,
                prompt_tokens=len(prompt_ids), priority=priority.name,
                tenant=tenant,
                **({"trace_id": span.trace_id} if span is not None else {}),
            )
        return request_id

    def _sweep_stale_spans(self) -> None:
        """Finish spans whose request outlived the TTL (e.g. a streaming
        generator that was created but never iterated — its finally block
        never runs, so the span would otherwise leak forever)."""
        cutoff = time.monotonic() - self._span_ttl_s
        stale = [rid for rid, (_, t) in self._spans_by_request.items()
                 if t < cutoff]
        for rid in stale:
            span, _ = self._spans_by_request.pop(rid)
            self.tracer.finish(span, status="orphaned")

    def _finish_span(self, request_id: RequestId, status: str) -> None:
        if not self.tracer:
            return
        entry = self._spans_by_request.pop(request_id, None)
        if entry is not None:
            self.tracer.finish(entry[0], status=status)

    async def _await_completion(self, sink: CollectingSink, request_id: RequestId):
        try:
            text, reason, usage, err, code = await sink.future
        except asyncio.CancelledError:
            # client disconnected mid-generation: abort upstream (Req 5.4)
            self.dispatcher.abort(request_id)
            self._finish_span(request_id, "cancelled")
            raise
        finally:
            if self.metrics:
                self.metrics.request_finished()
        self._finish_span(request_id, "ok" if err is None else "error")
        if err is not None:
            raise _error_to_api(err, code)
        return text, reason, usage

    # -- /generate ---------------------------------------------------------

    def parse_generate(self, obj: dict) -> GenerateRequest:
        try:
            req = GenerateRequest.from_dict(obj)
            self.validator.validate_generate(req)
            return req
        except ValidationError as e:
            raise ValidationApiError(e) from None

    async def generate(self, obj: dict) -> GenerateResponse:
        ids, params, prio = self._parse_one(obj, chat=False)
        loop = asyncio.get_running_loop()
        sink = CollectingSink(loop)
        request_id = self._submit(ids, params, sink, prio,
                                  tenant=_tenant_of(obj))
        text, reason, usage = await self._await_completion(sink, request_id)
        return GenerateResponse(
            id=f"cmpl-{request_id}",
            object="text_completion",
            created=int(time.time()),
            model=self.model_name,
            choices=(GenerateChoice(text=text, index=0, finish_reason=reason),),
            usage=usage,
        )

    async def generate_stream(
        self, obj: dict
    ) -> Tuple[RequestId, AsyncIterator[TokenEvent]]:
        """Validate + enqueue; returns (request_id, async TokenEvent
        iterator). Caller aborts via dispatcher on client disconnect
        (Req 5.4)."""
        ids, params, prio = self._parse_one(obj, chat=False)
        loop = asyncio.get_running_loop()
        sink = StreamingSink(loop)
        request_id = self._submit(ids, params, sink, prio,
                                  tenant=_tenant_of(obj))
        return request_id, self._finalize_stream(sink, request_id)

    async def _finalize_stream(self, sink: StreamingSink,
                               request_id: RequestId):
        status = "ok"
        try:
            async for event in sink.events():
                yield event
        except BaseException:
            status = "error"
            raise
        finally:
            if self.metrics:
                self.metrics.request_finished()
            self._finish_span(request_id, status)

    # -- /chat -------------------------------------------------------------

    def parse_chat(self, obj: dict) -> ChatRequest:
        try:
            req = ChatRequest.from_dict(obj)
            self.validator.validate_chat(req)
            return req
        except ValidationError as e:
            raise ValidationApiError(e) from None

    def _chat_ids(self, req: ChatRequest) -> List[int]:
        # the template carries its own BOS marker text; HF tokenizers encode
        # it as a literal, so skip the extra BOS id. render_chat prefers the
        # checkpoint's own chat_template (attached to the tokenizer at load,
        # so hot-swap retargeting carries it) over model-name sniffing.
        return self.tok.encode(
            render_chat(req.messages, self.tok, self.model_name),
            add_bos=False,
        )

    async def chat(self, obj: dict) -> ChatResponse:
        ids, params, prio = self._parse_one(obj, chat=True)
        loop = asyncio.get_running_loop()
        sink = CollectingSink(loop)
        request_id = self._submit(ids, params, sink, prio, endpoint="chat",
                                  tenant=_tenant_of(obj))
        text, reason, usage = await self._await_completion(sink, request_id)
        return ChatResponse(
            id=f"chatcmpl-{request_id}",
            object="chat.completion",
            created=int(time.time()),
            model=self.model_name,
            choices=(
                ChatChoice(
                    index=0,
                    message=ChatMessage(role=Role.ASSISTANT, content=text),
                    finish_reason=reason,
                ),
            ),
            usage=usage,
        )

    async def chat_stream(
        self, obj: dict
    ) -> Tuple[RequestId, AsyncIterator[TokenEvent]]:
        ids, params, prio = self._parse_one(obj, chat=True)
        loop = asyncio.get_running_loop()
        sink = StreamingSink(loop)
        request_id = self._submit(ids, params, sink, prio, endpoint="chat",
                                  tenant=_tenant_of(obj))
        return request_id, self._finalize_stream(sink, request_id)

    # -- /v1 multi-choice fan-out ------------------------------------------

    def _parse_one(self, obj: dict, chat: bool):
        """Validate once, then share (prompt_ids, params, priority) across
        every fanned-out choice."""
        if chat:
            req = self.parse_chat(obj)
            ids = self._chat_ids(req)
            prio = Priority.NORMAL
        else:
            req = self.parse_generate(obj)
            ids = self.tok.encode(req.prompt)
            prio = req.priority or Priority.NORMAL
        params = self._params(req.max_tokens, req.temperature, req.top_p,
                              req.stop_sequences)
        return ids, params, prio

    def _abort_submitted(self, request_ids) -> None:
        """Clean up submitted requests whose sink path will never run:
        ``dispatcher.abort`` drops a request with NO sink callback, so
        the metrics/span bookkeeping the sink path would have done
        happens here."""
        for rid in request_ids:
            self.dispatcher.abort(rid)
            if self.metrics:
                self.metrics.request_finished()
            self._finish_span(rid, "aborted")

    def release_unstarted(self, request_ids) -> None:
        """Abort streams whose consumer never started iterating (client
        disconnected between submit and SSE prepare): the stream
        generator's finally will never run, so its per-request
        bookkeeping happens here instead."""
        self._abort_submitted(request_ids)

    def _submit_fanout(self, obj: dict, chat: bool, n: int, make_sink):
        ids, params, prio = self._parse_one(obj, chat)
        endpoint = "chat" if chat else "generate"
        sinks, rids = [], []
        try:
            for _ in range(n):
                sink = make_sink()
                rids.append(
                    self._submit(ids, params, sink, prio,
                                 endpoint=endpoint,
                                 tenant=_tenant_of(obj))
                )
                sinks.append(sink)
        except ApiError:
            self._abort_submitted(rids)
            raise
        return sinks, rids

    async def complete_many(self, obj: dict, *, chat: bool, n: int = 1):
        """Non-streaming /v1 path: one validated request fanned into ``n``
        engine sequences sharing the prompt (the reference schema carries
        multi-choice responses, models.rs:147-171; the prefix cache makes
        the shared-prompt prefill nearly free). Returns ``(request_id,
        choices, usage)``: ``choices[i]`` has text / finish_reason /
        token_ids / token_logprobs, and usage aggregates OpenAI-style —
        prompt counted once, completion tokens summed over choices."""
        loop = asyncio.get_running_loop()
        sinks, rids = self._submit_fanout(
            obj, chat, n, lambda: CollectingSink(loop)
        )
        # every choice runs to completion even if a sibling errors (each
        # _await_completion settles its own metrics/span bookkeeping);
        # the first error is re-raised after the gather. Cancelling the
        # enclosing task cancels every child, and each child aborts its
        # own engine request.
        results = await asyncio.gather(
            *(self._await_completion(s, rid)
              for s, rid in zip(sinks, rids)),
            return_exceptions=True,
        )
        errs = [r for r in results if isinstance(r, BaseException)]
        if errs:
            raise errs[0]
        choices = [
            {
                "text": text,
                "finish_reason": reason,
                "token_ids": list(sink.token_ids),
                "token_logprobs": list(sink.token_logprobs),
            }
            for sink, (text, reason, _) in zip(sinks, results)
        ]
        prompt_tokens = results[0][2].prompt_tokens
        completion = sum(r[2].completion_tokens for r in results)
        return rids[0], choices, Usage.of(prompt_tokens, completion)

    async def stream_many(self, obj: dict, *, chat: bool, n: int = 1):
        """Streaming /v1 path: fan one validated request into ``n``
        sequences and merge their TokenEvent streams into
        ``(choice_index, event)`` pairs (OpenAI chunks name their choice
        by index, so interleaving order is free). Returns
        ``(request_ids, async iterator)``."""
        loop = asyncio.get_running_loop()
        sinks, rids = self._submit_fanout(
            obj, chat, n, lambda: StreamingSink(loop)
        )
        if n == 1:
            # common case: no pump task / merge queue on the hot path —
            # consume the sink directly, just tagged with choice index 0
            return rids, self._indexed_stream(sinks[0], rids[0])
        return rids, self._merge_streams(sinks, rids)

    async def _indexed_stream(self, sink: StreamingSink, rid):
        async for ev in self._finalize_stream(sink, rid):
            yield 0, ev

    async def _merge_streams(self, sinks, rids):
        q: asyncio.Queue = asyncio.Queue()

        async def pump(idx: int, sink: StreamingSink, rid) -> None:
            status = "ok"
            try:
                async for ev in sink.events():
                    q.put_nowait((idx, ev))
            except BaseException:
                status = "error"
                raise
            finally:
                # per-choice analogue of _finalize_stream's bookkeeping
                # (put_nowait: awaiting in a finally during cancellation
                # would swallow the CancelledError)
                if self.metrics:
                    self.metrics.request_finished()
                self._finish_span(rid, status)
                q.put_nowait((idx, None))

        tasks = [
            asyncio.ensure_future(pump(i, s, rid))
            for i, (s, rid) in enumerate(zip(sinks, rids))
        ]
        done = 0
        try:
            while done < len(sinks):
                idx, ev = await q.get()
                if ev is None:
                    done += 1
                    continue
                yield idx, ev
        finally:
            for t in tasks:
                t.cancel()

    # -- /embeddings -------------------------------------------------------

    async def embeddings(self, obj: dict) -> EmbeddingsResponse:
        try:
            req = EmbeddingsRequest.from_dict(obj)
            self.validator.validate_embeddings(req)
        except ValidationError as e:
            raise ValidationApiError(e) from None

        inputs = req.input_list()
        ids_list = [self.tok.encode(text) for text in inputs]
        runner = self.dispatcher.scheduler.schedule()
        if runner is None:
            raise InternalApiError("no healthy inference engine available")

        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()

        def _on_result(array, error):
            def _set():
                if fut.done():
                    return
                if error is not None:
                    fut.set_exception(InternalApiError(error))
                else:
                    fut.set_result(array)

            loop.call_soon_threadsafe(_set)

        if self.metrics:
            self.metrics.request_started()
        try:
            runner.submit_embed(ids_list, _on_result)
            array = await fut
        finally:
            if self.metrics:
                self.metrics.request_finished()

        prompt_tokens = sum(len(ids) for ids in ids_list)
        return EmbeddingsResponse(
            object="list",
            data=tuple(
                EmbeddingData(object="embedding", embedding=row.tolist(), index=i)
                for i, row in enumerate(array)
            ),
            model=req.model or self.model_name,
            usage=Usage.of(prompt_tokens, 0),
        )
