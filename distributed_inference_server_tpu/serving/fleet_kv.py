"""Fleet KV data plane: cross-host handoff and peer prefix fetch over
per-member data channels (docs/FLEET.md "KV data plane").

The fleet control plane (serving/fleet.py) federated routing, but both
KV byte paths — the disagg prefill→decode handoff and the peer prefix
fetch — stayed in-process: remote members were excluded from handoff
targets and fetch sources because the import session and the chunk
channel needed a local engine object on both ends. This module is the
missing data plane:

- **KvDataChannel** (registry-host side, one per member): a SECOND
  protowire TCP connection, dialed lazily at the member's heartbeat-
  advertised ``data_port`` and kept apart from the heartbeat wire on
  purpose — a multi-megabyte chunk stream must never head-of-line-block
  control frames (heartbeats aging members, submit/event traffic). It
  carries ``KvHandoffHeader``/``KvChunk``/``KvHandoff``/``KvPrefixFetch``
  streams host→member and chunk/``KvStreamResult``/``FleetEvent`` frames
  back, with a bounded in-flight stream window
  (``fleet.kv_max_streams`` — the (N+1)th concurrent stream fails fast
  to its local fallback instead of queueing unboundedly behind bulk
  transfers), per-stream exactly-once resolution, and lazy
  reconnect-with-backoff after a connection death.
- **KvDataServer** (member side): a listener the ``FleetWorker`` binds
  at startup and advertises in every heartbeat. Each accepted
  connection gets a reader thread (stream reassembly → local runner
  calls) and a writer thread (bounded queue → socket), so an engine
  thread's export callback only ever ENQUEUES frames — serializing a
  chunk chain must not stall the decode loop of exactly the replica
  that was picked as a fetch source because it is warm (and therefore
  busy). Migrated sequences decode on the member with a sink that
  encodes ``FleetEvent`` frames back over the data channel; the host's
  RemoteRunner proxy pumps them into the request's real sink — the
  same exactly-once event path remote submits already use.

Failure semantics (docs/RESILIENCE.md): every stream resolves exactly
once. A dial failure (``fleet.kv_connect``), a frame death mid-stream
(``fleet.kv_chunk``, one hit per chunk), a torn connection, or a crc/
validation reject on the member all resolve the stream as failed on the
host — which degrades a handoff to decode-in-place and a fetch to
recompute, exactly as the in-process paths do. A member-side crash
resolves the pending runner callbacks through the runner's ``_fail_all``
(the same ``_pending_opens``/``_pending_fetches`` pop-first protocol),
so the failure ships back as a ``KvStreamResult`` instead of wedging the
host. A data-channel death AFTER a commit fails the migrated requests
fast (``engine_crashed`` — they already streamed tokens and can never be
silently re-run) and aborts the member-side orphans.
"""

from __future__ import annotations

import logging
import queue
import socket
import struct
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from distributed_inference_server_tpu.engine.engine import SequenceExport
from distributed_inference_server_tpu.engine.kv_cache import KvChunk
from distributed_inference_server_tpu.serving import faults, protowire
from distributed_inference_server_tpu.serving.metrics import MetricsCollector

logger = logging.getLogger(__name__)

#: data-channel frame kinds — a table of its own so the bulk wire can
#: never be confused with (or parsed as) the heartbeat wire
KV_FRAME_KINDS: Dict[int, str] = {
    1: "KvHandoffHeader",
    2: "KvChunk",
    3: "KvHandoff",
    4: "KvPrefixFetch",
    5: "KvStreamResult",
    # decode tokens of a cross-host-migrated request, member -> host
    6: "FleetEvent",
}
_KV_KIND_BY_NAME = {name: kind for kind, name in KV_FRAME_KINDS.items()}

#: a KvChunk payload is chunk_pages full KV pages — tens of MB at large
#: geometries; anything bigger than this is a torn/foreign stream
MAX_KV_FRAME_BYTES = 256 * 1024 * 1024


class KvWireError(RuntimeError):
    """A malformed frame on a KV data channel; the connection dies and
    every in-flight stream resolves as failed."""


def send_kv_frame(sock: socket.socket, name: str,
                  obj: Dict[str, Any]) -> int:
    """Encode and write one data-channel frame; returns bytes written.
    Callers serialize sends per socket (one writer thread per side)."""
    payload = protowire.encode(name, obj)
    frame = struct.pack(">IB", len(payload), _KV_KIND_BY_NAME[name]) + payload
    sock.sendall(frame)
    return len(frame)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(n - len(buf), 1 << 20))
        if not chunk:
            return None  # orderly EOF
        buf += chunk
    return bytes(buf)


def recv_kv_frame(sock: socket.socket
                  ) -> Optional[Tuple[str, Dict[str, Any]]]:
    """Read one frame; None on EOF, KvWireError on a malformed frame."""
    header = _recv_exact(sock, 5)
    if header is None:
        return None
    length, kind = struct.unpack(">IB", header)
    name = KV_FRAME_KINDS.get(kind)
    if name is None or length > MAX_KV_FRAME_BYTES:
        raise KvWireError(f"bad kv data frame (kind={kind}, len={length})")
    payload = _recv_exact(sock, length)
    if payload is None:
        return None
    try:
        return name, protowire.decode(name, payload)
    except Exception as e:  # noqa: BLE001 — wire fault domain
        raise KvWireError(f"undecodable {name} frame: {e}") from e


def chunk_to_wire(handoff_id: str, c: KvChunk) -> Dict[str, Any]:
    return {
        "handoff_id": handoff_id,
        "index": c.index,
        "total": c.total,
        "page_start": c.page_start,
        "page_count": c.page_count,
        "crc32": c.crc32,
        "payload": c.payload,
    }


def chunk_from_wire(d: Dict[str, Any]) -> KvChunk:
    return KvChunk(
        index=d["index"], total=d["total"], page_start=d["page_start"],
        page_count=d["page_count"], payload=d["payload"], crc32=d["crc32"],
    )


def _export_state_to_wire(exp: SequenceExport) -> Dict[str, Any]:
    """SequenceExport host state -> KvHandoff wire dict (the chunks
    travel as their own frames; ``kv`` carries the monolithic payload
    only when there are no chunks)."""
    obj: Dict[str, Any] = {
        "request_id": str(exp.request_id),
        "token_ids": [int(t) for t in exp.token_ids],
        "prompt_len": exp.prompt_len,
        "seq_len": exp.seq_len,
        "next_token": int(exp.next_token),
        "emitted_tokens": exp.emitted_tokens,
        "output_text": exp.output_text,
        "emitted_upto": exp.emitted_upto,
        "pending_ids": [int(t) for t in exp.pending_ids],
        "max_tokens": exp.params.max_tokens,
        "temperature": exp.params.temperature,
        "top_p": exp.params.top_p,
        "stop_sequences": list(exp.params.stop_sequences),
        "kv": exp.kv if exp.kv_chunks is None else b"",
        "source_engine": exp.source_engine,
    }
    if exp.draft_kv is not None:
        obj["draft_kv"] = exp.draft_kv
    return obj


def _export_state_from_wire(d: Dict[str, Any]) -> SequenceExport:
    from distributed_inference_server_tpu.engine.engine import SamplingParams

    return SequenceExport(
        request_id=d["request_id"],
        token_ids=list(d["token_ids"]),
        prompt_len=d["prompt_len"],
        seq_len=d["seq_len"],
        next_token=d["next_token"],
        params=SamplingParams(
            max_tokens=d["max_tokens"],
            temperature=d["temperature"],
            top_p=d["top_p"],
            stop_sequences=tuple(d["stop_sequences"]),
        ),
        output_text=d["output_text"],
        emitted_upto=d["emitted_upto"],
        emitted_tokens=d["emitted_tokens"],
        pending_ids=list(d["pending_ids"]),
        kv=d["kv"],
        draft_kv=d.get("draft_kv"),
        source_engine=d["source_engine"],
    )


# ---------------------------------------------------------------------------
# Host side: one lazily-dialed data channel per member
# ---------------------------------------------------------------------------


class _KvStream:
    """One in-flight host-side stream: registered before the first frame
    goes out, resolved exactly once — by its KvStreamResult, by a send/
    connect failure, or by the connection dying under it."""

    __slots__ = ("key", "op", "rid", "cb", "chunks", "started_at",
                 "result_depth", "wire_bytes", "wire_chunks")

    def __init__(self, op: str, rid: str, cb: Callable):
        self.key = f"{op}:{rid}"
        self.op = op
        self.rid = rid
        self.cb = cb
        self.chunks: List[KvChunk] = []  # fetch-response reassembly
        self.started_at = time.monotonic()
        self.result_depth = 0  # fetch: depth the member actually served
        # bulk payload accounting for the learned wire-rate model
        # (serving/fleet_mesh.py): bytes/chunks this stream moved in
        # EITHER direction — sent (handoff/import) or received (fetch)
        self.wire_bytes = 0
        self.wire_chunks = 0


class KvDataChannel:
    """Registry-host end of one member's KV data channel.

    Thread-shape: public ops arrive from the disagg worker, the
    dispatcher (fetch routing), and runner callbacks; they register the
    stream and enqueue a send job. ONE wire worker thread owns the
    socket's send half (dial-on-first-use included — a lazy connect may
    block up to ``kv_connect_timeout_s`` and must never run on a
    dispatch path); one reader thread per live connection owns the
    receive half. Stream resolution is exactly-once by pop-first on
    ``_streams`` under ``_lock``."""

    def __init__(
        self,
        member_id: str,
        host: str,
        port: int,
        max_streams: int = 4,
        connect_timeout_s: float = 5.0,
        metrics: Optional[MetricsCollector] = None,
        on_event: Optional[Callable[[Dict[str, Any]], None]] = None,
        on_lost_requests: Optional[Callable[[List[str], str], None]] = None,
        breaker_threshold: int = 3,
        breaker_open_s: float = 5.0,
        retry_budget=None,
        rate_estimator=None,
        peer_wire: bool = False,
    ):
        """``on_event(obj)`` receives FleetEvent frames (decode tokens
        of migrated requests) on the reader thread. ``on_lost_requests``
        fires when the connection dies with migrated requests still
        streaming — the caller fails them fast (engine_crashed).
        ``breaker_threshold``/``breaker_open_s`` (serving/health.py
        CircuitBreaker; config ``health.wire_failures`` /
        ``health.breaker_open_s``): consecutive wire failures open the
        breaker — new streams fail fast and handoff/fetch election
        skips this member (``wire_available``) until a half-open probe
        succeeds. ``retry_budget`` (health.RetryBudget): reconnects
        after a failure draw from the shared budget, so a fleet of
        broken wires cannot amplify dial load. ``rate_estimator``
        (serving/fleet_mesh.py WireRateEstimator): each completed
        stream's bulk bytes/seconds feed the learned per-wire transfer
        rate the routing cost model prices fetches with; None = no
        observation (the wire stays priced at the configured prior).
        ``peer_wire`` marks a member-to-member mesh channel (dialed
        from a KvIntro, not by the registry host): the dial-death
        fault point is then ``fleet.kv_peer_dial`` instead of
        ``fleet.kv_connect`` (docs/RESILIENCE.md)."""
        from distributed_inference_server_tpu.serving.health import (
            CircuitBreaker,
        )

        self.member_id = member_id
        self.address = (host, port)
        self.max_streams = max(1, max_streams)
        self.connect_timeout_s = connect_timeout_s
        self.metrics = metrics
        self.on_event = on_event
        self.on_lost_requests = on_lost_requests
        self.retry_budget = retry_budget
        self.rate_estimator = rate_estimator
        self.peer_wire = peer_wire
        self.breaker = CircuitBreaker(
            threshold=breaker_threshold, open_s=breaker_open_s,
            on_transition=(metrics.record_breaker_transition
                           if metrics is not None else None),
        )
        # a failed dial/send happened since the last good connect: the
        # NEXT dial is a retry and must draw from the shared budget.
        # GIL-atomic bool, wire-worker-owned  # distlint: ignore[DL008]
        self._reconnecting = False
        self._lock = threading.Lock()
        # distlint: registry
        self._streams: Dict[str, _KvStream] = {}
        # request ids of migrated sequences whose decode events ride
        # THIS connection; failed fast if the channel dies under them
        self._event_rids: set = set()
        self._sock: Optional[socket.socket] = None
        self._jobs: "queue.Queue" = queue.Queue()
        self._worker: Optional[threading.Thread] = None
        self._closed = False
        # reconnect backoff after a connection death: the next dial
        # waits out _not_before instead of hammering a dead member
        self._not_before = 0.0
        self._backoff_s = 0.25
        self._bytes_sent = 0
        self._bytes_received = 0

    # -- public ops (any thread) --------------------------------------------

    def fetch_prefix(self, rid, engine_id: str, hashes: Sequence[int],
                     chunk_pages: int, wire_quant: str,
                     trace: Optional[tuple],
                     cb: Callable[[Optional[tuple], Optional[str]], None]
                     ) -> None:
        """Ask the member's ``engine_id`` for its cached prefix chain;
        ``cb((depth, chunks), None)`` or ``cb(None, err)`` exactly once
        (the submit_prefix_export callback contract)."""
        def _resolve(ok: bool, err: Optional[str], s: _KvStream) -> None:
            if not ok:
                cb(None, err or "fetch failed")
                return
            cb((s.result_depth, sorted(s.chunks, key=lambda c: c.index)),
               None)

        stream = _KvStream("fetch", str(rid), _resolve)
        msg = {
            "request_id": str(rid),
            "hashes": [int(h) for h in hashes],
            "chunk_pages": chunk_pages,
            "wire_quant": wire_quant,
            "engine_id": engine_id,
        }
        if trace:
            msg["trace_id"], msg["parent_span_id"] = trace
        self._start_stream(stream, [("KvPrefixFetch", msg)])

    def import_open(self, rid, engine_id: str, prefix_pages: int,
                    wire_quant: str, chunks: Sequence[KvChunk],
                    trace: Optional[tuple],
                    cb: Callable[[bool, Optional[str]], None]) -> None:
        """Phase 1 of a cross-host streamed handoff: ship the prefix
        chunks and open an import session on the member's engine."""
        stream = _KvStream(
            "open", str(rid), lambda ok, err, s: cb(ok, err))
        frames = [("KvHandoffHeader", self._header(
            rid, "open", engine_id, wire_quant, trace,
            prefix_pages=prefix_pages, total_chunks=len(chunks)))]
        frames += [("KvChunk", chunk_to_wire(str(rid), c)) for c in chunks]
        self._start_stream(stream, frames)

    def import_commit(self, exp: SequenceExport, engine_id: str,
                      trace: Optional[tuple],
                      cb: Callable[[bool, Optional[str]], None]) -> None:
        """Phase 2: the switchover tail (``exp.kv_chunks``) plus the
        host state. On ok the member's engine owns the sequence and its
        decode events start riding this channel."""
        self._sequence_stream("commit", exp, engine_id, trace, cb)

    def resume(self, exp: SequenceExport, engine_id: str,
               trace: Optional[tuple],
               cb: Callable[[bool, Optional[str]], None]) -> None:
        """A monolithic cross-host migration: chunks (if the export was
        streamed) or the single ``kv`` payload, plus the host state."""
        self._sequence_stream("resume", exp, engine_id, trace, cb)

    def _sequence_stream(self, op: str, exp: SequenceExport,
                         engine_id: str, trace: Optional[tuple],
                         cb: Callable[[bool, Optional[str]], None]) -> None:
        """Commit and resume share one shape: header + chunks + the
        terminal KvHandoff state frame, and on ok the request's decode
        events start riding this channel (failure-tracked so a channel
        death fails the migrated request fast)."""
        rid = str(exp.request_id)
        chunks = list(exp.kv_chunks or [])

        def _resolve(ok: bool, err: Optional[str], s: _KvStream) -> None:
            if ok:
                with self._lock:
                    self._event_rids.add(rid)
            cb(ok, err)

        stream = _KvStream(op, rid, _resolve)
        frames = [("KvHandoffHeader", self._header(
            rid, op, engine_id, exp.wire_quant, trace,
            total_chunks=len(chunks)))]
        frames += [("KvChunk", chunk_to_wire(rid, c)) for c in chunks]
        frames.append(("KvHandoff", _export_state_to_wire(exp)))
        self._start_stream(stream, frames)

    def import_abort(self, rid, engine_id: str) -> None:
        """Drop an opened-but-uncommitted member import session (stream
        cancelled / client abort): fire-and-forget, no reply."""
        self._enqueue_frames(None, [("KvStreamResult", {
            "stream_id": str(rid), "op": "abort", "ok": True,
            "engine_id": engine_id,
        })])

    def release_request(self, rid) -> None:
        """The migrated request resolved (done/error/abort observed by
        the proxy): stop failure-tracking its events."""
        with self._lock:
            self._event_rids.discard(str(rid))

    def wire_available(self) -> bool:
        """Election gate (serving/health.py): False while the breaker is
        OPEN — handoff targets and fetch sources skip this member
        instead of discovering the broken wire one failed stream at a
        time (RemoteRunner.supports_kv_import / EngineStatus.data_plane)."""
        return self.breaker.available()

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            out = {
                "connected": self._sock is not None,
                "streams": len(self._streams),
                "event_requests": len(self._event_rids),
                "bytes_sent": self._bytes_sent,
                "bytes_received": self._bytes_received,
            }
        out["breaker"] = self.breaker.stats()
        if self.rate_estimator is not None:
            out["rate_bytes_per_s"] = self.rate_estimator.rate()
        return out

    def close(self, reason: str = "channel closed") -> None:
        with self._lock:
            self._closed = True
        self._drop_connection(reason, count_failure=False)
        self._jobs.put(None)  # wake the worker so it can exit

    # -- internals ----------------------------------------------------------

    @staticmethod
    def _header(rid, op: str, engine_id: str, wire_quant: str,
                trace: Optional[tuple], prefix_pages: int = 0,
                total_chunks: int = 0) -> Dict[str, Any]:
        h = {
            "handoff_id": str(rid), "request_id": str(rid),
            "wire_quant": wire_quant or "none", "op": op,
            "engine_id": engine_id, "prefix_pages": prefix_pages,
            "total_chunks": total_chunks,
        }
        if trace:
            h["trace_id"], h["parent_span_id"] = trace
        return h

    def _start_stream(self, stream: _KvStream,
                      frames: List[Tuple[str, Dict[str, Any]]]) -> None:
        if not self.breaker.try_acquire():
            # circuit OPEN (or a half-open probe already in flight):
            # fail fast to the caller's local fallback — the member's
            # wire is judged broken, and hammering it would only delay
            # the fallback the request is going to take anyway
            stream.cb(False, "kv data channel circuit open "
                      f"(member {self.member_id} wire unhealthy)", stream)
            return
        with self._lock:
            if self._closed:
                reject = "kv data channel closed"
            elif len(self._streams) >= self.max_streams:
                # the in-flight window: fail fast to the caller's local
                # fallback instead of queueing bulk transfers behind
                # each other unboundedly
                reject = (f"kv data channel window full "
                          f"({self.max_streams} streams in flight)")
            else:
                reject = None
                self._streams[stream.key] = stream
        if reject is not None:
            # the attempt never ran: hand back a consumed half-open
            # probe, or it would wedge the breaker half-open forever
            self.breaker.release()
            stream.cb(False, reject, stream)
            return
        self._enqueue_frames(stream, frames)

    def _enqueue_frames(self, stream: Optional[_KvStream],
                        frames: List[Tuple[str, Dict[str, Any]]]) -> None:
        with self._lock:
            if self._closed:
                return  # fire-and-forget sends after close just drop
            if self._worker is None:
                # lazy wire worker: nothing is spawned (and nothing is
                # dialed) until the first KV byte actually needs to move
                self._worker = threading.Thread(
                    target=self._run_worker,
                    name=f"kv-wire-{self.member_id}", daemon=True,
                )
                self._worker.start()
        self._jobs.put((stream, frames))

    def _run_worker(self) -> None:
        while True:
            job = self._jobs.get()
            if job is None:
                return
            stream, frames = job
            if stream is not None:
                with self._lock:
                    live = self._streams.get(stream.key) is stream
                if not live:
                    # the stream was already failed (a connection drop
                    # while this job sat queued): transmitting its
                    # frames anyway would make the member do work the
                    # host has abandoned — reserve pages no commit will
                    # ever claim, or decode a ghost duplicate of a
                    # sequence already decoding in place
                    continue
            try:
                sock = self._ensure_connected()
                # the data wire wedges/times out mid-send
                # (docs/RESILIENCE.md fleet.wire_timeout): repeated
                # hits walk the circuit breaker closed -> open
                faults.fire("fleet.wire_timeout")
                for name, obj in frames:
                    if name == "KvChunk":
                        # per-chunk wire death (docs/RESILIENCE.md):
                        # nth=N tears the stream at its Nth chunk
                        faults.fire("fleet.kv_chunk")
                    n = send_kv_frame(sock, name, obj)
                    with self._lock:
                        self._bytes_sent += n
                        if stream is not None:
                            stream.wire_bytes += n
                            if name == "KvChunk":
                                stream.wire_chunks += 1
            except Exception as e:  # noqa: BLE001 — transport fault
                # domain: the stream fails, the connection is torn down
                # (its reader resolves every OTHER in-flight stream)
                logger.debug("kv channel %s: send failed: %s",
                             self.member_id, e)
                if self.metrics:
                    self.metrics.record_error("fleet_kv.send")
                self.breaker.record_failure()
                self._reconnecting = True
                self._resolve_stream(stream, False, str(e))
                # count_failure=False: THIS incident is already recorded
                # above — letting the drop count it again would halve
                # the effective health.wire_failures threshold whenever
                # other streams/event requests are live
                self._drop_connection(f"send failed: {e}",
                                      count_failure=False)

    def _ensure_connected(self) -> socket.socket:
        with self._lock:
            sock = self._sock
        if sock is not None:
            return sock
        now = time.monotonic()
        if now < self._not_before:
            raise OSError(
                f"kv data channel to {self.member_id} backing off "
                f"({self._not_before - now:.2f}s left)"
            )
        if (self._reconnecting and self.retry_budget is not None
                and not self.retry_budget.acquire("kv_reconnect")):
            # a RE-dial after a failure is a retry: the shared budget
            # (serving/health.py) is dry, so degrade this stream to its
            # local fallback instead of amplifying dial load
            raise OSError(
                f"kv data channel to {self.member_id}: retry budget "
                "exhausted"
            )
        # injected dial failure (docs/RESILIENCE.md): member-to-member
        # mesh wires and registry-to-member wires are distinct chaos
        # fault domains, so each gets its own LITERAL point
        if self.peer_wire:
            faults.fire("fleet.kv_peer_dial")
        else:
            faults.fire("fleet.kv_connect")
        try:
            # the channel's dedicated wire worker thread: blocking by
            # design with a bounded timeout; never a dispatch/async path
            sock = socket.create_connection(  # distlint: ignore[DL001]
                self.address, timeout=self.connect_timeout_s)
        except OSError:
            self._not_before = now + self._backoff_s
            self._backoff_s = min(self._backoff_s * 2.0, 5.0)
            self._reconnecting = True
            raise
        try:
            sock.settimeout(None)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            # a socket that dialed but cannot be configured is as dead
            # as a failed dial: close it (else the fd leaks) and take
            # the same backoff the dial failure would have
            sock.close()
            self._not_before = now + self._backoff_s
            self._backoff_s = min(self._backoff_s * 2.0, 5.0)
            self._reconnecting = True
            raise
        self._backoff_s = 0.25
        self._reconnecting = False
        with self._lock:
            if self._closed:
                sock.close()
                raise OSError("kv data channel closed")
            self._sock = sock
        threading.Thread(
            target=self._read_loop, args=(sock,),
            name=f"kv-read-{self.member_id}", daemon=True,
        ).start()
        logger.info("kv data channel to %s dialed %s:%d", self.member_id,
                    *self.address)
        return sock

    # the host half of the data channel only ever *initiates* streams:
    # handoff headers/states and prefix-fetch requests flow host->member
    # and come back as chunks + results, never inbound here
    # distlint: wire-ignores[KvHandoffHeader, KvHandoff, KvPrefixFetch]
    def _read_loop(self, sock: socket.socket) -> None:
        try:
            while True:
                frame = recv_kv_frame(sock)
                if frame is None:
                    break
                name, obj = frame
                if name == "KvChunk":
                    with self._lock:
                        payload_n = len(obj.get("payload", b""))
                        self._bytes_received += payload_n
                        stream = self._streams.get(
                            f"fetch:{obj.get('handoff_id', '')}")
                        if stream is not None:
                            stream.wire_bytes += payload_n
                            stream.wire_chunks += 1
                    if stream is not None:
                        stream.chunks.append(chunk_from_wire(obj))
                elif name == "KvStreamResult":
                    self._on_result(obj)
                elif name == "FleetEvent":
                    rid = obj.get("request_id", "")
                    if obj.get("kind") in ("done", "error"):
                        self.release_request(rid)
                    if self.on_event is not None:
                        self.on_event(obj)
                # headers of fetch responses carry no state the result
                # frame doesn't; chunks key on handoff_id directly
        except (OSError, KvWireError) as e:
            logger.debug("kv channel %s reader ended: %s", self.member_id, e)
        finally:
            self._drop_connection("kv data connection lost")

    def _on_result(self, obj: Dict[str, Any]) -> None:
        key = f"{obj.get('op', '')}:{obj.get('stream_id', '')}"
        with self._lock:
            stream = self._streams.pop(key, None)
        if stream is None:
            return  # already resolved (send failure / channel death)
        # a result frame — ok or not — proves the WIRE round-tripped:
        # member-side rejects (validation, engine unavailable) are not
        # wire failures and must not open the breaker
        self.breaker.record_success()
        if (self.rate_estimator is not None and bool(obj.get("ok"))
                and stream.wire_bytes > 0):
            # feed the learned wire-rate model (serving/fleet_mesh.py):
            # only OK streams with bulk payload count — a reject moved
            # control frames, not pages, and would poison the rate
            self.rate_estimator.observe(
                stream.wire_bytes,
                max(time.monotonic() - stream.started_at, 1e-6),
                chunks=stream.wire_chunks,
            )
        stream.result_depth = obj.get("depth", 0)
        try:
            stream.cb(bool(obj.get("ok")),
                      obj.get("error") or None, stream)
        except Exception as e:  # noqa: BLE001 — callback isolation
            self._absorbed("stream_callback", e)

    def _resolve_stream(self, stream: Optional[_KvStream], ok: bool,
                        err: Optional[str]) -> None:
        if stream is None:
            return
        with self._lock:
            if self._streams.pop(stream.key, None) is None:
                return  # the reader's result beat us to it
        try:
            stream.cb(ok, err, stream)
        except Exception as e:  # noqa: BLE001 — callback isolation
            self._absorbed("stream_callback", e)

    def _drop_connection(self, reason: str,
                         count_failure: bool = True) -> None:
        with self._lock:
            sock, self._sock = self._sock, None
            streams = list(self._streams.values())
            self._streams.clear()
            lost = sorted(self._event_rids)
            self._event_rids.clear()
        if count_failure and (streams or lost):
            # the connection died UNDER work: wire-failure evidence for
            # the breaker (an idle orderly EOF is not)
            self.breaker.record_failure()
            self._reconnecting = True
        if sock is not None:
            try:
                # shutdown BEFORE close: a close() under a reader thread
                # blocked in recv defers the FIN until that syscall
                # returns (the in-flight recv pins the kernel socket) —
                # the peer would never notice the death
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
        for stream in streams:
            try:
                stream.cb(False, reason, stream)
            except Exception as e:  # noqa: BLE001 — callback isolation
                self._absorbed("stream_callback", e)
        if lost and self.on_lost_requests is not None:
            # migrated requests whose decode events rode this
            # connection: they already streamed tokens, so they fail
            # fast (engine_crashed) — never silently re-run
            try:
                self.on_lost_requests(lost, reason)
            except Exception as e:  # noqa: BLE001 — callback isolation
                self._absorbed("lost_requests", e)

    def _absorbed(self, site: str, exc: BaseException) -> None:
        logger.debug("kv channel %s: absorbed error at %s: %s",
                     self.member_id, site, exc)
        if self.metrics:
            self.metrics.record_error(f"fleet_kv.{site}")


# ---------------------------------------------------------------------------
# Member side: the data listener FleetWorker advertises
# ---------------------------------------------------------------------------


class _DataEventSink:
    """ResultSink of a cross-host-migrated sequence on the MEMBER: every
    token/terminal encodes a FleetEvent frame onto the data connection's
    writer queue. Runs on the member's engine-runner threads; enqueue
    only — the writer thread owns serialization and the socket."""

    def __init__(self, conn: "_KvPeerConn", request_id: str,
                 engine_id: str):
        self._conn = conn
        self._rid = request_id
        self._eid = engine_id

    def _event(self, obj: Dict[str, Any]) -> None:
        obj["request_id"] = self._rid
        obj["engine_id"] = self._eid
        self._conn.enqueue("FleetEvent", obj)

    def on_token(self, token_id, text, token_index, logprob=None) -> None:
        ev = {"kind": "token", "text": text or "",
              "token_index": token_index or 0}
        if token_id is not None:
            ev["token_id"] = int(token_id)
        if logprob is not None:
            ev["logprob"] = float(logprob)
        self._event(ev)

    def on_done(self, finish_reason, usage) -> None:
        self._conn.release(self._rid)
        self._event({
            "kind": "done",
            "finish_reason": getattr(finish_reason, "value",
                                     str(finish_reason)),
            "prompt_tokens": getattr(usage, "prompt_tokens", 0),
            "completion_tokens": getattr(usage, "completion_tokens", 0),
        })

    def on_error(self, message, code) -> None:
        self._conn.release(self._rid)
        self._event({"kind": "error", "message": message or "",
                     "code": code or "inference_failed"})


class _Assembly:
    """Reassembly state of one inbound stream on a member connection
    (owned by the connection's reader thread)."""

    __slots__ = ("header", "chunks")

    def __init__(self, header: Dict[str, Any]):
        self.header = header
        self.chunks: List[KvChunk] = []


class _KvPeerConn:
    """One accepted registry-host connection on the member's data
    listener: a reader thread (frames → stream reassembly → local runner
    calls) and a writer thread (bounded frame queue → socket). Runner
    callbacks only enqueue; a full queue blocks the enqueueing runner
    callback briefly (TCP backpressure shaped) rather than buffering
    unboundedly."""

    def __init__(self, server: "KvDataServer", sock: socket.socket,
                 peer: str):
        self.server = server
        self.sock = sock
        self.peer = peer
        # reader-owned: inbound stream reassembly keyed by handoff id
        # distlint: registry
        self._assemblies: Dict[str, _Assembly] = {}
        self._out: "queue.Queue" = queue.Queue(maxsize=256)
        self._lock = threading.Lock()
        # migrated requests decoding locally whose events ride this
        # connection; aborted if the host vanishes mid-decode
        # distlint: registry
        self._live: Dict[str, str] = {}  # rid -> engine_id
        self._closed = False
        self._writer = threading.Thread(
            target=self._write_loop, name=f"kv-peer-write-{peer}",
            daemon=True,
        )
        self._writer.start()

    # -- outbound (runner threads enqueue, writer thread sends) -------------

    def enqueue(self, name: str, obj: Dict[str, Any]) -> None:
        with self._lock:
            if self._closed:
                return
        try:
            self._out.put((name, obj), timeout=5.0)
        except queue.Full:
            # the host stopped draining: treat the connection as dead
            # rather than stalling runner callbacks forever
            self.close("kv data writer queue wedged")

    def release(self, rid: str) -> None:
        with self._lock:
            self._live.pop(str(rid), None)

    def _write_loop(self) -> None:
        while True:
            item = self._out.get()
            if item is None:
                return
            name, obj = item
            try:
                if name == "KvChunk":
                    # the member half of the per-chunk wire death: a
                    # fetch response can tear mid-stream too
                    faults.fire("fleet.kv_chunk")
                send_kv_frame(self.sock, name, obj)
            except Exception as e:  # noqa: BLE001 — transport fault domain
                logger.debug("kv peer %s: send failed: %s", self.peer, e)
                self.close(f"send failed: {e}")
                return

    # -- inbound (reader thread) --------------------------------------------

    # FleetEvent frames go member->host on this wire (_DataEventSink
    # enqueues them outbound); the peer conn never receives one
    # distlint: wire-ignores[FleetEvent]
    def run(self) -> None:
        try:
            while True:
                frame = recv_kv_frame(self.sock)
                if frame is None:
                    break
                name, obj = frame
                if name == "KvChunk":
                    asm = self._assemblies.get(obj.get("handoff_id", ""))
                    if asm is not None:
                        asm.chunks.append(chunk_from_wire(obj))
                        self._maybe_complete(obj.get("handoff_id", ""))
                elif name == "KvHandoffHeader":
                    hid = obj.get("handoff_id", "")
                    self._assemblies[hid] = _Assembly(obj)
                    self._maybe_complete(hid)
                elif name == "KvHandoff":
                    self._on_state(obj)
                elif name == "KvPrefixFetch":
                    self._on_fetch(obj)
                elif name == "KvStreamResult":
                    if obj.get("op") == "abort":
                        self._on_abort(obj)
        except (OSError, KvWireError) as e:
            logger.debug("kv peer %s reader ended: %s", self.peer, e)
        finally:
            self.close("kv data connection lost")

    def _runner(self, engine_id: str):
        return self.server.scheduler.get(engine_id)

    def _result(self, rid: str, op: str, ok: bool,
                error: Optional[str] = None, depth: int = 0) -> None:
        self.enqueue("KvStreamResult", {
            "stream_id": rid, "op": op, "ok": ok,
            "error": error or "", "depth": depth,
        })

    def _maybe_complete(self, hid: str) -> None:
        """An ``open`` stream acts once its chunk count arrives (commit/
        resume wait for their terminal KvHandoff state frame)."""
        # single-owner: the reader thread is the only resolver of
        # _assemblies (close() never touches it), so get-then-pop
        # cannot race a second resolver
        # distlint: ignore[DL015]
        asm = self._assemblies.get(hid)
        if asm is None or asm.header.get("op") != "open":
            return
        if len(asm.chunks) < asm.header.get("total_chunks", 0):
            return
        self._assemblies.pop(hid, None)
        header = asm.header
        rid = header.get("request_id", "")
        runner = self._runner(header.get("engine_id", ""))
        if runner is None or not runner.is_healthy():
            self._result(rid, "open", False, "remote engine unavailable")
            return
        chunks = sorted(asm.chunks, key=lambda c: c.index)

        def _done(ok: bool, err: Optional[str]) -> None:
            # runner thread: enqueue only
            self._result(rid, "open", ok, err)

        runner.submit_import_open(
            rid, header.get("prefix_pages", 0), chunks, _done)

    def _on_state(self, obj: Dict[str, Any]) -> None:
        """Terminal KvHandoff frame of a commit/resume stream: rebuild
        the SequenceExport, register a local ServerRequest whose sink
        streams FleetEvents back, and hand it to the target runner."""
        from distributed_inference_server_tpu.serving.runner import (
            ServerRequest,
        )

        rid = obj.get("request_id", "")
        # pop-before-submit is safe HERE only because _assemblies is
        # owned by this reader thread alone: no crash sweep races the
        # window, and if the submit dies the wire dies with it — the
        # host settles the stream through connection death
        # distlint: ignore[DL015]
        asm = self._assemblies.pop(rid, None)
        if asm is None:
            return  # state frame with no header: torn stream, ignore
        header = asm.header
        op = header.get("op", "")
        engine_id = header.get("engine_id", "")
        runner = self._runner(engine_id)
        if runner is None or not runner.is_healthy():
            self._result(rid, op, False, "remote engine unavailable")
            return
        exp = _export_state_from_wire(obj)
        if asm.chunks:
            exp.kv_chunks = sorted(asm.chunks, key=lambda c: c.index)
            exp.wire_quant = header.get("wire_quant") or "none"
        sink = _DataEventSink(self, rid, engine_id)
        req = ServerRequest(
            rid, [int(t) for t in exp.token_ids[:exp.prompt_len]],
            exp.params, sink,
        )
        # the sequence streamed its pre-migration tokens on the HOST;
        # marking the first token here keeps member-side accounting from
        # double-counting TTFT for a mid-stream arrival
        req.first_token_at = time.monotonic()

        def _done(ok: bool, err: Optional[str]) -> None:
            if ok and err != "aborted":
                with self._lock:
                    self._live[rid] = engine_id
            self._result(rid, op, ok, err if not ok else None)

        if op == "commit":
            runner.submit_import_commit(exp, req, _done)
        else:
            runner.submit_resume(exp, req, _done)

    def _on_fetch(self, obj: Dict[str, Any]) -> None:
        rid = obj.get("request_id", "")
        runner = self._runner(obj.get("engine_id", ""))
        if runner is None or not runner.is_healthy():
            self._result(rid, "fetch", False, "remote engine unavailable")
            return
        wire_quant = obj.get("wire_quant") or "none"

        def _done(result, err: Optional[str]) -> None:
            # peer runner's thread: enqueue the response frames only —
            # serialization happens on the writer thread
            if result is None:
                self._result(rid, "fetch", False, err)
                return
            depth, chunks = result
            self.enqueue("KvHandoffHeader", {
                "handoff_id": rid, "request_id": rid,
                "wire_quant": wire_quant, "op": "fetch",
                "total_chunks": len(chunks),
            })
            for c in chunks:
                self.enqueue("KvChunk", chunk_to_wire(rid, c))
            self._result(rid, "fetch", True, depth=depth)

        runner.submit_prefix_export(
            rid, list(obj.get("hashes", [])),
            obj.get("chunk_pages", 0) or 8, wire_quant, _done,
        )

    def _on_abort(self, obj: Dict[str, Any]) -> None:
        rid = obj.get("stream_id", "")
        runner = self._runner(obj.get("engine_id", ""))
        if runner is not None:
            runner.submit_import_abort(rid)
        self._assemblies.pop(rid, None)

    def close(self, reason: str) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            live = dict(self._live)
            self._live.clear()
        try:
            # shutdown first: our own reader blocked in recv pins the
            # kernel socket — a bare close would defer the FIN and the
            # host would never see this connection die
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass
        # stop the writer WITHOUT blocking: the queue may be full (a
        # wedged writer is one of the paths into close) and the writer
        # will never drain it — a plain put() here would deadlock the
        # engine-runner thread whose enqueue() triggered the close.
        # Drain the stale frames (the connection is dead; none would be
        # sent) and best-effort the sentinel: if it still doesn't fit,
        # the writer is mid-send and exits via the send-failure arm the
        # shutdown above just armed.
        while True:
            try:
                self._out.get_nowait()
            except queue.Empty:
                break
        try:
            self._out.put_nowait(None)
        except queue.Full:
            pass
        # the host vanished mid-decode: abort the orphaned migrated
        # sequences — nobody is listening for their tokens, and the
        # host's channel death already failed them client-side
        for rid, engine_id in live.items():
            runner = self._runner(engine_id)
            if runner is not None:
                try:
                    runner.abort(rid)
                except Exception as e:  # noqa: BLE001 — cleanup isolation
                    logger.debug("kv peer %s: orphan abort failed: %s",
                                 self.peer, e)
        self.server._drop_conn(self)


class KvDataServer:
    """The member's KV data listener (started by FleetWorker; its bound
    port rides every heartbeat). Serves export/import streams against
    the member's LOCAL runners via the scheduler."""

    def __init__(self, scheduler, host: str = "0.0.0.0", port: int = 0,
                 metrics: Optional[MetricsCollector] = None):
        self.scheduler = scheduler
        self.metrics = metrics
        self._host = host
        self._port = port
        self._sock: Optional[socket.socket] = None
        self._thread: Optional[threading.Thread] = None
        self._conns: List[_KvPeerConn] = []
        self._lock = threading.Lock()
        self._stopping = False
        self.bound_port = 0

    def start(self) -> None:
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        sock.bind((self._host, self._port))
        sock.listen(8)
        self._sock = sock
        self.bound_port = sock.getsockname()[1]
        self._stopping = False
        # lifecycle handle  # distlint: ignore[DL008]
        self._thread = threading.Thread(
            target=self._accept_loop, name="kv-data-accept", daemon=True
        )
        self._thread.start()
        logger.info("kv data listener on %s:%d", self._host, self.bound_port)

    def stop(self) -> None:
        self._stopping = True
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        with self._lock:
            conns = list(self._conns)
        for conn in conns:
            conn.close("kv data server stopping")
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None

    def _accept_loop(self) -> None:
        while not self._stopping:
            try:
                sock, addr = self._sock.accept()
            except OSError:
                return  # listener closed
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn = _KvPeerConn(self, sock, f"{addr[0]}:{addr[1]}")
            with self._lock:
                self._conns.append(conn)
            threading.Thread(
                target=conn.run, name=f"kv-peer-read-{addr[0]}:{addr[1]}",
                daemon=True,
            ).start()

    def _drop_conn(self, conn: _KvPeerConn) -> None:
        with self._lock:
            try:
                self._conns.remove(conn)
            except ValueError:
                pass
