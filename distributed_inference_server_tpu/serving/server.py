"""Server orchestrator: spawn engines, wire the spine, serve HTTP.

Realizes the reference's spec'd ``InferenceServer`` (S9, ``tasks.md:298-312``
[spec]; behavior ``requirements.md:104-110,130-134``):

- spawn N engine replicas ("workers") and wait until each reports ready;
- register them with the adaptive scheduler + start health checking;
- start the dispatcher (queue→batcher→engines) and the HTTP transport;
- graceful shutdown: stop accepting (503), drain in-flight, stop threads;
- runtime elastic scaling: ``scale_to(n)`` adds/removes engine replicas
  without interrupting in-flight requests (requirements.md:110).
"""

from __future__ import annotations

import asyncio
from typing import Callable, List, Optional

from aiohttp import web

from distributed_inference_server_tpu.core.queue import QueueConfig
from distributed_inference_server_tpu.core.validator import ValidatorConfig
from distributed_inference_server_tpu.engine.engine import LLMEngine
from distributed_inference_server_tpu.models.tokenizer import Tokenizer
from distributed_inference_server_tpu.serving.app import build_app
from distributed_inference_server_tpu.serving.batcher import BatcherConfig
from distributed_inference_server_tpu.serving.dispatcher import Dispatcher
from distributed_inference_server_tpu.serving.handler import InferenceHandler
from distributed_inference_server_tpu.serving.metrics import MetricsCollector
from distributed_inference_server_tpu.serving.runner import EngineRunner
from distributed_inference_server_tpu.serving.scheduler import (
    AdaptiveScheduler,
    SchedulingStrategy,
)


def _bind_factory(factory: Callable, idx: int) -> Callable[[], LLMEngine]:
    """Bind a replica index: index-aware factories (``def factory(i)``) let
    multi-replica TP deployments give each replica a disjoint device
    slice; zero-arg factories pass through."""
    import inspect

    try:
        takes_index = bool(inspect.signature(factory).parameters)
    except (TypeError, ValueError):
        takes_index = False
    return (lambda: factory(idx)) if takes_index else factory


class InferenceServer:
    """Owns the full serving stack for one model."""

    def __init__(
        self,
        engine_factory: Callable[[], LLMEngine],
        tokenizer: Tokenizer,
        model_name: str,
        num_engines: int = 1,
        strategy: SchedulingStrategy = SchedulingStrategy.LEAST_LOADED,
        queue_config: Optional[QueueConfig] = None,
        batcher_config: Optional[BatcherConfig] = None,
        validator_config: Optional[ValidatorConfig] = None,
        auto_restart: bool = True,
        health_check_interval_s: float = 1.0,
        restart_backoff_s: float = 1.0,
        restart_backoff_max_s: float = 30.0,
        max_redispatch: int = 2,
        model_resolver: Optional[Callable[[str], Callable[[], LLMEngine]]] = None,
        otlp_endpoint: str = "",
        otlp_service_name: str = "distributed-inference-server-tpu",
        engine_roles: Optional[List[str]] = None,
        disagg_settings=None,
        fetch_costs=None,
        fleet_settings=None,
        slo_settings=None,
        health_settings=None,
        admission_settings=None,
    ):
        """``model_resolver(name) -> engine_factory`` enables the admin
        model-swap endpoint (Req 13); None leaves it unconfigured (501).
        ``otlp_endpoint`` (a collector's /v1/traces URL) turns on the
        OTLP/HTTP exporter (utils/otlp.py) — real OpenTelemetry export,
        S12 — alongside the in-memory ring.

        ``engine_roles`` (disaggregated prefill/decode serving,
        serving/disagg.py; docs/DISAGG.md): one role per replica —
        "prefill" | "decode" | "unified". Any prefill/decode role brings
        up the DisaggController and KV-handoff channel; None/all-unified
        is exactly today's monolithic behavior. ``disagg_settings`` is a
        disagg.DisaggSettings (timeout/retries/channel backend) — it
        also configures the fleet prefix-sharing channel (the
        PrefixFetcher reuses its channel/chunk_pages/wire_quant).
        ``fetch_costs`` is a scheduler.FetchCosts for the cache_aware
        three-way route/fetch/recompute cost model (docs/CACHING.md);
        None = defaults.

        ``fleet_settings`` (multi-host fleet control plane,
        serving/fleet.py; docs/FLEET.md): with ``enabled`` this server
        becomes a REGISTRY HOST — it listens for worker members, ages
        them through the alive/suspect/dead state machine, and routes
        their engines through RemoteRunner proxies; with ``rerole`` the
        RoleBalancer flips unified engines to prefill under prompt-queue
        pressure (and back) with hysteresis. None/defaults = no fleet
        networking, no rebalancing — today's behavior exactly.

        ``slo_settings`` (serving/teledigest.py SloSettings; config
        section ``slo``): arms per-request SLO verdicts in the flight
        recorder and shapes the windowed-digest rings behind
        ``GET /server/perf`` (docs/OBSERVABILITY.md "Performance
        telemetry"). None = no SLO accounting, default windows.

        ``health_settings`` / ``admission_settings`` (serving/health.py;
        docs/RESILIENCE.md "Gray failures and overload"): the gray-
        failure control plane — latency-scored health demotion with
        routing tiering, KV data-channel circuit breakers, deadline-
        aware admission shedding (503 + Retry-After, ``admission_shed``),
        and the shared retry budget. None = defaults (scorer ON with
        conservative thresholds; shedding armed but inert until a TTFT
        SLO or explicit ``admission.deadline_ms`` gives requests a
        deadline)."""
        from distributed_inference_server_tpu.utils.tracing import Tracer

        from distributed_inference_server_tpu.serving.flightrec import (
            FlightRecorder,
        )

        self.engine_factory = engine_factory
        self.model_resolver = model_resolver
        self.metrics = MetricsCollector()
        self.tracer = Tracer()
        self.slo_settings = slo_settings
        if slo_settings is not None:
            # boot-time only: the rings are empty here, so re-shaping
            # them discards nothing
            self.metrics.configure_perf(slo_settings.epoch_s,
                                        slo_settings.window_s)
        # drop accounting (docs/OBSERVABILITY.md): ring eviction,
        # exporter failure, and fleet-wire buffer overflow surface as
        # trace_spans_dropped_total{reason=...} instead of a debug log
        self.tracer.on_drop = self.metrics.record_trace_drops
        # per-request flight recorder: the spine notes lifecycle events
        # into bounded timelines served at GET /server/requests/<id>;
        # slo_settings arms its verdict derivation
        self.recorder = FlightRecorder(metrics=self.metrics,
                                       slo=slo_settings)
        from distributed_inference_server_tpu.serving import faults as _faults

        # fault arm/disarm hops land in the recorder's fleet window so a
        # postmortem timeline shows when the chaos lever moved; the
        # bound method is held so shutdown can unregister THIS server's
        # observer (chaos builds several servers per interpreter)
        self._fault_observer = self.recorder.note_global
        _faults.add_observer(self._fault_observer)
        self.otlp = None
        if otlp_endpoint:
            from distributed_inference_server_tpu.utils.otlp import (
                OTLPExporter,
            )

            self.otlp = OTLPExporter(
                otlp_endpoint, service_name=otlp_service_name
            ).attach(self.tracer)
        self.scheduler = AdaptiveScheduler(
            strategy=strategy,
            health_check_interval_s=health_check_interval_s,
            auto_restart=auto_restart,
            metrics=self.metrics,
            restart_backoff_s=restart_backoff_s,
            restart_backoff_max_s=restart_backoff_max_s,
            fetch_costs=fetch_costs,
        )
        # gray-failure defense (serving/health.py; docs/RESILIENCE.md
        # "Gray failures and overload"): the latency-scored health
        # scorer (routing tiering rides scheduler.statuses()), the
        # shared retry budget, and deadline-aware admission control
        from distributed_inference_server_tpu.serving.health import (
            AdmissionControl,
            AdmissionSettings,
            HealthScorer,
            HealthSettings,
            RetryBudget,
        )

        self.health_settings = health_settings or HealthSettings()
        self.retry_budget = RetryBudget(
            ratio=self.health_settings.retry_budget_ratio,
            min_retries=self.health_settings.retry_budget_min,
            window_s=self.health_settings.retry_window_s,
            metrics=self.metrics,
        )
        self.health: Optional[HealthScorer] = None
        if self.health_settings.enabled:
            self.health = HealthScorer(
                self.health_settings, self.scheduler,
                metrics=self.metrics, recorder=self.recorder,
            )
            self.scheduler.health_scorer = self.health
        self.admission = AdmissionControl(
            admission_settings or AdmissionSettings(),
            slo=slo_settings,
            metrics=self.metrics,
            tenant_weights=(queue_config.tenant_weights
                            if queue_config is not None else None),
        )
        from distributed_inference_server_tpu.serving.disagg import (
            DisaggController,
            DisaggSettings,
            PrefixFetcher,
            make_channel,
            parse_roles,
        )

        if engine_roles is not None and isinstance(engine_roles, str):
            engine_roles = parse_roles(engine_roles, num_engines)
        self._roles: List[str] = list(engine_roles or [])
        settings = disagg_settings or DisaggSettings()
        self.disagg: Optional[DisaggController] = None
        if any(r in ("prefill", "decode") for r in self._roles):
            self.disagg = DisaggController(
                self.scheduler,
                metrics=self.metrics,
                channel=make_channel(settings.channel),
                settings=settings,
                tracer=self.tracer,
                recorder=self.recorder,
            )
            self.metrics.set_engines_by_role(
                DisaggController.role_counts(self._roles)
            )
        # fleet prefix sharing (docs/CACHING.md): always constructed —
        # whether it runs is the scheduler's cost-model decision, which
        # only yields "fetch" under cache_aware with peer fetch enabled
        self.prefix_fetcher = PrefixFetcher(
            channel=make_channel(settings.channel),
            settings=settings,
            metrics=self.metrics,
            tracer=self.tracer,
            recorder=self.recorder,
        )
        self.dispatcher = Dispatcher(
            self.scheduler,
            queue_config=queue_config,
            batcher_config=batcher_config,
            metrics=self.metrics,
            tracer=self.tracer,
            disagg=self.disagg,
            max_redispatch=max_redispatch,
            prefix_fetcher=self.prefix_fetcher,
            recorder=self.recorder,
            admission=self.admission,
            retry_budget=self.retry_budget,
        )
        if self.disagg is not None:
            # the handoff retry loop draws from the shared retry budget
            # (serving/health.py): a sick decode fleet must not turn
            # every migration into retry amplification
            self.disagg.retry_budget = self.retry_budget
        from distributed_inference_server_tpu.native import make_validator

        self.handler = InferenceHandler(
            self.dispatcher,
            tokenizer,
            model_name,
            # native C++ validator when the library builds; the Python
            # reference tier otherwise (identical contract, differential-
            # tested in tests/test_native.py)
            validator=make_validator(validator_config),
            metrics=self.metrics,
            tracer=self.tracer,
            recorder=self.recorder,
        )
        from distributed_inference_server_tpu.serving.degradation import (
            DegradationController,
        )

        self.degradation = DegradationController(
            self.dispatcher, self.scheduler,
            # SLO burn rate as an escalation input alongside memory
            # pressure (serving/health.py settings; docs/RESILIENCE.md)
            metrics=self.metrics,
            burn_high=self.health_settings.slo_burn_high,
            burn_min_requests=self.health_settings.slo_burn_min_requests,
        )
        # multi-host fleet control plane (serving/fleet.py; docs/FLEET.md)
        from distributed_inference_server_tpu.serving.fleet import (
            FleetRegistry,
            FleetServer,
            FleetSettings,
            RoleBalancer,
        )

        self.fleet_settings = fleet_settings or FleetSettings()
        self.fleet_registry: Optional[FleetRegistry] = None
        self.fleet_server: Optional[FleetServer] = None
        self.role_balancer: Optional[RoleBalancer] = None
        self.fleet_ha = None
        if self.fleet_settings.enabled:
            self.fleet_registry = FleetRegistry(
                self.fleet_settings, metrics=self.metrics
            )
            self.fleet_server = FleetServer(
                self.fleet_registry, self.scheduler, self.fleet_settings,
                metrics=self.metrics,
                redispatch=self.dispatcher.redispatch,
                tracer=self.tracer,
                recorder=self.recorder,
                health_settings=self.health_settings,
                retry_budget=self.retry_budget,
            )
            if self.health is not None:
                # per-member latency comparison: the scorer reads the
                # same telemetry frames GET /server/perf merges
                self.health.telemetry_fn = (
                    self.fleet_server.telemetry_snapshot
                )
            # telemetry-learned wire costs (serving/fleet_mesh.py;
            # docs/CACHING.md): plan_route and the handoff election
            # price the ACTUAL (src, dst) wire a move would cross from
            # observed chunk bytes/seconds; cold wires keep charging
            # the fleet.kv_page_cost constant as the prior. mesh_route
            # additionally admits member->member fetch delegation once
            # the registry has introduced the pair (docs/FLEET.md).
            fs = self.fleet_server

            def _member_of(status) -> str:
                # remote engine ids are "<member>:<engine>"; a local
                # status (or a None peer, the handoff source) is this
                # host — the registry side of the wire
                if status is None or not getattr(status, "remote",
                                                 False):
                    return "registry"
                return status.engine_id.rsplit(":", 1)[0]

            def _wire_cost(target, peer):
                dst = _member_of(target)
                src = _member_of(peer)
                if src == dst:
                    return None  # no wire crossed: static model rules
                base = self.scheduler._fetch_costs.remote_page_cost
                # the mover is the wire's src->dst direction: chunks
                # flow FROM the warm side (peer / handoff source) TO
                # the target, but rates are keyed by the channel that
                # carries them — registry channels are keyed
                # ("registry", member) regardless of direction
                if "registry" in (src, dst):
                    member = dst if src == "registry" else src
                    return fs.mesh_rates.page_cost(
                        "registry", member, base)
                return fs.mesh_rates.page_cost(dst, src, base)

            def _mesh_route(target, peer) -> bool:
                return fs.mesh_route(_member_of(target),
                                     _member_of(peer))

            self.scheduler.wire_cost = _wire_cost
            self.scheduler.mesh_route = _mesh_route
            self.prefix_fetcher.mesh_route = fs.mesh_route
            # registry HA (serving/fleet_ha.py; docs/FLEET.md "Registry
            # HA"): fleet.registries names the warm-standby set — this
            # registry joins the lease election and stamps its epoch on
            # every control frame. Single-registry fleets skip all of it.
            if self.fleet_settings.registries:
                from distributed_inference_server_tpu.serving.fleet_ha \
                    import RegistryHA

                self.fleet_ha = RegistryHA(
                    self.fleet_server, self.fleet_settings,
                    metrics=self.metrics, recorder=self.recorder,
                )
                self.fleet_server.ha = self.fleet_ha
                if not self.fleet_settings.standby_http:
                    # single-front-door mode: a standby's dispatcher
                    # rejects ingress (QueueFull) until it holds the
                    # lease; fleet-internal paths are never gated
                    self.dispatcher.ingress_gate = self.fleet_ha.is_primary
        if self.fleet_settings.rerole:
            self.role_balancer = RoleBalancer(
                self.scheduler, self.dispatcher, self.fleet_settings,
                metrics=self.metrics,
                recorder=self.recorder,
            )
            if self.fleet_ha is not None:
                # only the lease holder balances roles: two balancers
                # flipping the same fleet would fight (fleet_ha.py)
                self.role_balancer.active_fn = self.fleet_ha.is_primary
        self._num_engines = num_engines
        self._next_engine_idx = 0
        self._started = False

    # -- lifecycle ---------------------------------------------------------

    def start(self, wait_ready: bool = True) -> None:
        """Spawn engines (Req 7.1-7.2), start health checks and dispatch."""
        if self.disagg is not None:
            self.disagg.start()
        for _ in range(self._num_engines):
            self._spawn_engine(wait_ready=wait_ready)
        self.scheduler.start_health_loop()
        self.dispatcher.start()
        self.degradation.start()
        if self.health is not None:
            self.health.start()
        if self.fleet_server is not None:
            self.fleet_server.start()
        if self.fleet_ha is not None:
            # after fleet_server.start(): the lease wire's self identity
            # needs the BOUND port (fleet.port=0 binds ephemerally)
            self.fleet_ha.start(
                f"{self.fleet_settings.host}:{self.fleet_server.bound_port}"
            )
        if self.role_balancer is not None:
            self.role_balancer.start()
        # lifecycle flag, orchestrator-called  # distlint: ignore[DL008]
        self._started = True

    def shutdown(self, drain_timeout_s: float = 30.0) -> None:
        """Graceful: stop accepting, drain, stop engines (Req 9.5).
        The dispatcher drain counts in-flight KV migrations
        (disagg.pending_count); the controller then drains its queue by
        resuming any stragglers in place before the engines stop."""
        self.degradation.stop()
        if self.health is not None:
            self.health.stop()
        if self.role_balancer is not None:
            self.role_balancer.stop()
        self.dispatcher.shutdown(drain_timeout_s)
        if self.fleet_ha is not None:
            # before the fleet server: the lease loop must not race the
            # listener teardown (peers just see the lease age out)
            self.fleet_ha.stop()
        if self.fleet_server is not None:
            # after the drain (remote in-flight counted), before the
            # local engines stop: detaches member sessions cleanly
            self.fleet_server.stop()
        if self.disagg is not None:
            self.disagg.shutdown()
        self.scheduler.stop_health_loop()
        for runner in self.scheduler.engines():
            runner.shutdown()
        if self.otlp is not None:
            self.otlp.shutdown()
        from distributed_inference_server_tpu.serving import faults as _faults

        _faults.remove_observer(self._fault_observer)
        self._started = False

    # -- elasticity --------------------------------------------------------

    def _spawn_engine(self, wait_ready: bool = True) -> EngineRunner:
        idx = self._next_engine_idx
        engine_id = f"engine-{idx}"
        self._next_engine_idx += 1
        # replicas beyond the configured role list (elastic scale_to)
        # come up unified: they serve immediately without rebalancing
        # the prefill/decode split under the operator
        role = self._roles[idx] if idx < len(self._roles) else "unified"
        runner = EngineRunner(
            engine_id, _bind_factory(self.engine_factory, idx), self.metrics,
            tracer=self.tracer, role=role, disagg=self.disagg,
            recorder=self.recorder,
        )
        # crash-safe redispatch (docs/RESILIENCE.md): a dead runner hands
        # its zero-token in-flight requests back to the dispatcher, which
        # re-runs them on a healthy replica invisibly to the client
        runner.redispatch = self.dispatcher.redispatch
        runner.start(wait_ready=wait_ready)
        self.scheduler.register(runner)
        return runner

    def scale_to(self, n: int) -> None:
        """Add or remove engine replicas at runtime (requirements.md:110).
        Removal drains: the engine is unregistered (no new batches) and shut
        down once its in-flight requests finish."""
        # fleet proxies are not ours to scale: their member owns them
        current = [r for r in self.scheduler.engines()
                   if not getattr(r, "is_remote", False)]
        for _ in range(n - len(current)):
            self._spawn_engine()
        if n < len(current):
            # retire unified replicas first (youngest within each class):
            # scaling down must not tear out a disaggregated topology's
            # only decode engine while unified spares exist
            unified = [r for r in current if r.role == "unified"]
            roled = [r for r in current if r.role != "unified"]
            victims = (unified[::-1] + roled[::-1])[: len(current) - n]
            for runner in victims:
                self.scheduler.unregister(runner.engine_id)
                self._drain_and_stop(runner)

    def _drain_and_stop(self, runner: EngineRunner) -> None:
        import threading
        import time

        def _wait():
            deadline = time.monotonic() + 60.0
            while runner.active_count() and time.monotonic() < deadline:
                # dedicated scale-down drain thread polling a runner that
                # has no completion event to park on; never an async or
                # dispatch path
                time.sleep(0.05)  # distlint: ignore[DL001]
            runner.shutdown()

        threading.Thread(target=_wait, daemon=True).start()

    # -- model hot-swap (Req 13) ------------------------------------------

    def swap_model(
        self,
        engine_factory: Callable[[], LLMEngine],
        model_name: Optional[str] = None,
        timeout_s: float = 600.0,
    ) -> tuple:
        """Swap every replica to a new model (requirements.md:178-182):
        background load per runner, atomic per-runner switch, in-flight
        requests finish on the old model. Returns (ok, error). On any
        replica's load failure that replica keeps the old model and the
        call reports failure (Req 13.4); replicas are independent, so a
        partial swap is visible in /server/stats until retried. Stragglers
        past the deadline are cancelled — they never install late."""
        import threading as _t
        import time as _time

        # remote proxies never swap: the member's own operator swaps its
        # models (a partial fleet-wide swap is visible in /server/stats)
        runners = [r for r in self.scheduler.engines()
                   if not getattr(r, "is_remote", False)]
        results: dict = {}
        events = []
        cancelled = _t.Event()
        for idx, runner in enumerate(runners):
            ev = _t.Event()
            events.append(ev)

            def _cb(ok, err, eid=runner.engine_id, ev=ev):
                results[eid] = (ok, err)
                ev.set()

            runner.swap_model(
                _bind_factory(engine_factory, idx), _cb, cancelled=cancelled
            )
        deadline = _time.monotonic() + timeout_s
        for ev in events:
            if not ev.wait(max(0.0, deadline - _time.monotonic())):
                cancelled.set()
                # report which replicas already installed the new model so
                # the operator can see the divergence and retry (matching
                # the failure path's per-replica reporting)
                installed = sorted(
                    e for e, (ok, _) in results.items() if ok
                )
                return False, (
                    f"model swap timed out after {timeout_s}s; replicas "
                    f"already on the new model: {installed or 'none'} — "
                    "retry the swap to converge"
                )
        failed = {e: err for e, (ok, err) in results.items() if not ok}
        if failed:
            return False, f"swap failed on {failed}"
        self.engine_factory = engine_factory
        if model_name is not None:
            self.handler.model_name = model_name
        # retarget the handler's tokenizer to the NEW model's: the chat
        # template family follows model_name, and templating in the new
        # family while encoding with the old tokenizer would garble every
        # /chat prompt (cross-family swaps)
        for runner in runners:
            tok = runner.tokenizer()
            if tok is not None:
                self.handler.tok = tok
                break
        else:
            # every runner's engine read back None after a successful
            # swap — the handler keeps templating with the OLD tokenizer
            # against the NEW model_name, exactly the cross-family /chat
            # garbling the retarget exists to prevent; say so loudly
            import logging

            logging.getLogger(__name__).error(
                "model swap to %r succeeded but no runner yielded a "
                "tokenizer; handler tokenizer NOT retargeted (stale "
                "tokenizer paired with the new model name)",
                model_name,
            )
        return True, None

    # -- hot-reload --------------------------------------------------------

    def apply_hot_config(self, diff: dict, new_config) -> None:
        """Apply hot-reloadable config changes (requirements.md:146):
        batching window/size, queue watermarks/timeout, scheduling
        strategy. Only the *diffed hot keys* are applied — a non-hot key
        (e.g. queue.max_queue_size) changing in the same edit must NOT leak
        onto the live server. ConfigWatcher subscriber signature."""
        from dataclasses import replace

        batcher_updates = {k: v for (sec, k), v in diff.items() if sec == "batcher"}
        if batcher_updates:
            self.dispatcher.batcher.config = replace(
                self.dispatcher.batcher.config, **batcher_updates
            )
        queue_updates = {k: v for (sec, k), v in diff.items() if sec == "queue"}
        if queue_updates:
            self.dispatcher.queue.config = replace(
                self.dispatcher.queue.config, **queue_updates
            )
        if ("server", "strategy") in diff:
            self.scheduler.set_strategy(new_config.strategy())

    # -- HTTP --------------------------------------------------------------

    def build_app(self) -> web.Application:
        swap_fn = None
        if self.model_resolver is not None:
            def swap_fn(name: str):  # noqa: F811 — deliberate rebind
                try:
                    factory = self.model_resolver(name)
                except Exception as e:  # noqa: BLE001 — unknown model etc.
                    return False, str(e)
                return self.swap_model(factory, model_name=name)

        def scale_fn(n: int):
            try:
                self.scale_to(n)
            except Exception as e:  # noqa: BLE001 — spawn failure etc.
                return False, str(e)
            return True, None

        fleet_fn = None
        if (self.fleet_registry is not None
                or self.role_balancer is not None):
            fleet_fn = self._fleet_stats

        return build_app(self.handler, self.metrics, swap_fn=swap_fn,
                         scale_fn=scale_fn, fleet_fn=fleet_fn,
                         perf_fn=self._perf_stats,
                         health_fn=self._health_stats)

    def _health_stats(self) -> dict:
        """The ``health`` block of ``/server/stats`` (serving/health.py;
        docs/RESILIENCE.md "Gray failures and overload"): scored
        per-engine states with their evidence, KV data-channel breaker
        states, the shared retry budget, and the admission estimator."""
        out: dict = {}
        if self.health is not None:
            out.update(self.health.stats())
        out["retry_budget"] = self.retry_budget.stats()
        out["admission"] = self.admission.stats()
        if self.fleet_server is not None:
            breakers = {}
            for member, stats in self.fleet_server.kv_stats().items():
                if "breaker" in stats:
                    breakers[member] = stats["breaker"]
            if breakers:
                out["kv_breakers"] = breakers
        return out

    def _perf_stats(self) -> dict:
        """The ``GET /server/perf`` payload (docs/OBSERVABILITY.md
        "Performance telemetry"): per-engine step clock, windowed
        latency percentiles, SLO burn, and — on a registry host — the
        per-member digests plus the fleet-merged view. Assembled by
        teledigest.build_perf_payload so the merge/percentile path is
        the exact one an operator re-merging member digests uses."""
        from distributed_inference_server_tpu.serving.teledigest import (
            build_perf_payload,
        )

        slo_counts, goodput = self.metrics.slo_counts()
        fleet_members = None
        if self.fleet_server is not None:
            fleet_members = self.fleet_server.telemetry_snapshot()
        return build_perf_payload(
            self.metrics.perf_store(), self.slo_settings,
            slo_counts=slo_counts, goodput=goodput,
            fleet_members=fleet_members,
        )

    def _fleet_stats(self) -> dict:
        """The ``fleet`` block of ``/server/stats`` (docs/FLEET.md):
        members with state + last-beat age, heartbeat/rerole counters,
        the live role map, and the rebalance history."""
        out: dict = {}
        if self.fleet_registry is not None:
            out.update(self.fleet_registry.stats())
        if self.fleet_server is not None:
            # KV data plane (serving/fleet_kv.py): per-member channel
            # state — connected / in-flight streams / bytes moved
            out["kv_channels"] = self.fleet_server.kv_stats()
            # KV mesh (serving/fleet_mesh.py): every priced wire —
            # registry<->member and member<->member — with its learned
            # rate and lifetime bytes/chunks
            out["kv_wires"] = self.fleet_server.kv_wire_stats()
        if self.fleet_ha is not None:
            # registry HA (serving/fleet_ha.py): role, epoch, lease age
            # + holder state, peer-registry views, takeover counts
            out["registry"] = self.fleet_ha.stats()
        if self.role_balancer is not None:
            out["rebalancer"] = self.role_balancer.stats()
        out["role_map"] = {
            r.engine_id: r.role for r in self.scheduler.engines()
        }
        out.update(self.metrics.fleet_counters())
        return out

    async def serve(self, host: str = "0.0.0.0", port: int = 8000) -> web.AppRunner:
        """Bind and serve; returns the AppRunner (caller controls lifetime)."""
        runner = web.AppRunner(self.build_app())
        await runner.setup()
        site = web.TCPSite(runner, host, port)
        await site.start()
        return runner

    async def serve_forever(
        self,
        host: str = "0.0.0.0",
        port: int = 8000,
        grpc_port: int = 0,
    ) -> None:
        """Serve HTTP (and, with ``grpc_port`` > 0, the gRPC transport —
        S1's optional second surface, serving/grpc_server.py — sharing
        this server's handler/queue/engines) until cancelled."""
        runner = await self.serve(host, port)
        grpc_srv = None
        if grpc_port:
            from distributed_inference_server_tpu.serving.grpc_server import (
                serve_grpc,
            )

            grpc_srv = await serve_grpc(self.handler, host, grpc_port)
        try:
            while True:
                await asyncio.sleep(3600)
        finally:
            if grpc_srv is not None:
                await grpc_srv.stop(grace=5.0)
            await runner.cleanup()
            self.shutdown()
