"""Fleet-federated performance telemetry: deterministic, mergeable
log-bucket latency digests with sliding epoch rings, the engine step
clock's counter store, and SLO/goodput accounting
(docs/OBSERVABILITY.md "Performance telemetry").

The problem this solves: every latency surface so far was per-process
and per-lifetime — ``/server/stats`` p99 was a whole-process sort of raw
latencies, and nothing could answer "what is FLEET-wide p99 TTFT over
the last minute, and which member is burning it". The pieces:

- **LogBuckets** — a fixed logarithmic bucket layout (8 buckets per
  octave, ~4.4% mid-bucket quantile error). A value maps to an integer
  bucket index; a quantile maps back to the bucket's geometric
  midpoint. Everything downstream is integer counts, so **merging two
  digests is exact** (count addition) and a quantile of a merged digest
  is a deterministic function of the counts alone — the registry host
  and an operator re-merging member digests by hand compute bit-equal
  percentiles.
- **WindowedDigest** — a sliding ring of *epochs* (wall-clock aligned:
  ``epoch index = time // epoch_s``, so epochs line up ACROSS
  processes), each holding sparse bucket counts plus an exact n/sum.
  A windowed percentile merges the last ``window_s`` worth of epochs;
  old epochs fall out of the ring. Count-only digests (no buckets,
  just per-epoch n) double as windowed counters for SLO burn rates.
- **wire form** — each digest serializes to a canonical dict (sorted
  epochs, sorted parallel bucket/count arrays) that IS the
  ``TeleDigest`` protowire message and the ``/server/perf`` JSON.
  ``merge_digests``/``window_stats`` operate on wire dicts only, so the
  member-local view, the host's fleet merge, and an offline re-merge
  share one code path — the fleet-smoke acceptance (host merged p99
  == re-merge of member digests) is equality of one function's output.
- **PerfTelemetry** — the per-process store: named digests + a flat
  cumulative counter map (the engine step clock's
  ``step.<engine>.<kind>.<field>`` and ``events.<engine>.<event>``
  series), snapshotted into one bounded ``FleetTelemetry`` frame per
  heartbeat (serving/remote_runner.py ``ship_telemetry_once``).
- **SloSettings** — the SLO layer's config (``slo.ttft_ms`` /
  ``slo.tbt_p99_ms`` + per-tenant overrides): ``slo_verdict`` turns a
  finished request's exact phase partition (serving/flightrec.py) into
  an ok/violated verdict feeding ``slo_requests_total{tenant,verdict}``
  and the goodput-token counters.

Catalog constants at the bottom (``PERF_FIELDS``, ``TELEMETRY_METRICS``,
``DIGEST_NAMES``) are lint-enforced against the docs/OBSERVABILITY.md
"Performance telemetry" tables (distlint DL014) so the endpoint, the
metric names, and their documentation cannot drift apart.
"""

from __future__ import annotations

import math
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Mapping, Optional, Tuple

# ---------------------------------------------------------------------------
# Log-bucket layout (shared by every digest; never reconfigured — a
# layout change would silently mis-merge against older digests)
# ---------------------------------------------------------------------------

#: buckets per octave: bucket width = 2^(1/8) ≈ +9.05%, so a quantile
#: read at the geometric midpoint is within ~4.4% of the true value
BUCKETS_PER_OCTAVE = 8
#: smallest resolvable value (milliseconds): 1 microsecond
MIN_VALUE_MS = 1e-3
#: bucket 0 holds values <= MIN_VALUE_MS (including exact zeros);
#: the top bucket absorbs everything past ~38 hours
MAX_BUCKET = 37 * BUCKETS_PER_OCTAVE

_LOG2_MIN = math.log2(MIN_VALUE_MS)


def bucket_of(value_ms: float) -> int:
    """Deterministic value -> bucket index (integers merge exactly)."""
    if not value_ms > MIN_VALUE_MS:  # catches <= MIN, 0, negatives, NaN
        return 0
    idx = 1 + int((math.log2(value_ms) - _LOG2_MIN) * BUCKETS_PER_OCTAVE)
    return idx if idx < MAX_BUCKET else MAX_BUCKET


def bucket_value_ms(idx: int) -> float:
    """Bucket index -> representative value (geometric midpoint)."""
    if idx <= 0:
        return 0.0
    return 2.0 ** (_LOG2_MIN + (idx - 0.5) / BUCKETS_PER_OCTAVE)


# ---------------------------------------------------------------------------
# Sliding epoch ring
# ---------------------------------------------------------------------------


class WindowedDigest:
    """One named series: a ring of wall-clock-aligned epochs, each with
    sparse bucket counts plus exact n/sum. NOT thread-safe on its own —
    PerfTelemetry serializes access (one short lock, no allocation on
    the common path)."""

    __slots__ = ("epoch_s", "ring_epochs", "_epochs")

    def __init__(self, epoch_s: float = 5.0, window_s: float = 60.0):
        self.epoch_s = float(epoch_s)
        # keep one extra epoch beyond the window so a query straddling
        # an epoch boundary still sees a full window behind it
        self.ring_epochs = max(1, int(math.ceil(window_s / self.epoch_s))) + 1
        # epoch index -> [bucket_counts dict, n, sum_us]
        self._epochs: Dict[int, list] = {}

    def epoch_index(self, now: Optional[float] = None) -> int:
        # wall clock, not monotonic: epoch indices must align ACROSS
        # processes so the registry host can merge member epochs
        return int((time.time() if now is None else now) // self.epoch_s)

    def observe(self, value_ms: float, now: Optional[float] = None) -> None:
        ep = self._epoch_locked(self.epoch_index(now))
        b = bucket_of(value_ms)
        ep[0][b] = ep[0].get(b, 0) + 1
        ep[1] += 1
        # exact integer microseconds: float addition is order-dependent
        # in its last bits, which would break the bit-equality of
        # merged views under re-grouping; integers are associative
        ep[2] += int(round(value_ms * 1000.0))

    def count(self, k: int = 1, now: Optional[float] = None) -> None:
        """Bucketless observation: the digest as a windowed counter
        (SLO burn rates — per-epoch n only, still mergeable)."""
        ep = self._epoch_locked(self.epoch_index(now))
        ep[1] += k

    def _epoch_locked(self, idx: int) -> list:
        # _locked: the OWNING PerfTelemetry's lock serializes every
        # mutation path (observe/count are only reached under it);
        # direct WindowedDigest use is single-threaded (tests, merges)
        ep = self._epochs.get(idx)
        if ep is None:
            ep = self._epochs[idx] = [{}, 0, 0]
            if len(self._epochs) > self.ring_epochs:
                for old in sorted(self._epochs)[: len(self._epochs)
                                                - self.ring_epochs]:
                    del self._epochs[old]
        return ep

    def to_wire(self, name: str) -> Dict[str, Any]:
        """Canonical wire dict (== the TeleDigest protowire message):
        epochs sorted by index, bucket/count parallel arrays sorted by
        bucket — byte-stable, so equal contents encode equal."""
        epochs = []
        for idx in sorted(self._epochs):
            counts, n, total = self._epochs[idx]
            buckets = sorted(counts)
            epochs.append({
                "index": idx,
                "buckets": buckets,
                "counts": [counts[b] for b in buckets],
                "n": n,
                "sum_us": total,
            })
        return {"name": name, "epoch_s": self.epoch_s, "epochs": epochs}


# ---------------------------------------------------------------------------
# Wire-dict algebra: ONE merge + ONE quantile path for member-local
# views, the host's fleet merge, and offline re-merges
# ---------------------------------------------------------------------------


def merge_digests(wires: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    """Exact merge of same-series wire dicts: per-epoch, per-bucket
    count addition. Deterministic: output epochs/buckets are sorted, so
    any grouping/ordering of the inputs yields the identical dict.

    Epoch geometry is part of the key space: a wire whose ``epoch_s``
    differs from the first non-empty input's is EXCLUDED (its epoch
    indices are denominated in a different time unit — adding its
    counts at numerically-colliding indices would corrupt the merged
    windows). The fleet ingest path additionally drops and counts such
    digests at the wire (FleetServer.ingest_telemetry), so this guard
    is the merge algebra staying sound, not the operator signal."""
    name = ""
    epoch_s = 0.0
    acc: Dict[int, list] = {}  # index -> [counts dict, n, sum_us]
    for w in wires:
        if not w:
            continue
        name = name or w.get("name", "")
        epoch_s = epoch_s or float(w.get("epoch_s", 0.0))
        if float(w.get("epoch_s", 0.0)) != epoch_s:
            continue  # foreign epoch geometry: see docstring
        for ep in w.get("epochs", []):
            idx = int(ep.get("index", 0))
            slot = acc.get(idx)
            if slot is None:
                slot = acc[idx] = [{}, 0, 0]
            counts = slot[0]
            for b, c in zip(ep.get("buckets", []), ep.get("counts", [])):
                counts[int(b)] = counts.get(int(b), 0) + int(c)
            slot[1] += int(ep.get("n", 0))
            slot[2] += int(ep.get("sum_us", 0))
    epochs = []
    for idx in sorted(acc):
        counts, n, total = acc[idx]
        buckets = sorted(counts)
        epochs.append({"index": idx, "buckets": buckets,
                       "counts": [counts[b] for b in buckets],
                       "n": n, "sum_us": total})
    return {"name": name, "epoch_s": epoch_s, "epochs": epochs}


def window_stats(wire: Dict[str, Any], window_s: float,
                 as_of_epoch: Optional[int] = None) -> Dict[str, Any]:
    """p50/p90/p99 (+count/mean) over the trailing window of a wire
    dict. Pure and deterministic: given the same dict, window, and
    ``as_of_epoch``, every process computes the identical floats — the
    fleet-smoke merge-identity acceptance compares exactly this."""
    epoch_s = float(wire.get("epoch_s", 0.0)) or 1.0
    if as_of_epoch is None:
        as_of_epoch = int(time.time() // epoch_s)
    first = as_of_epoch - max(1, int(math.ceil(window_s / epoch_s))) + 1
    counts: Dict[int, int] = {}
    n = 0
    total = 0
    for ep in wire.get("epochs", []):
        idx = int(ep.get("index", 0))
        if idx < first or idx > as_of_epoch:
            continue
        for b, c in zip(ep.get("buckets", []), ep.get("counts", [])):
            counts[int(b)] = counts.get(int(b), 0) + int(c)
        n += int(ep.get("n", 0))
        total += int(ep.get("sum_us", 0))
    out: Dict[str, Any] = {"count": n}
    bucketed = sum(counts.values())
    if bucketed:
        out.update(
            p50=_quantile(counts, bucketed, 0.50),
            p90=_quantile(counts, bucketed, 0.90),
            p99=_quantile(counts, bucketed, 0.99),
        )
        out["mean"] = total / 1000.0 / bucketed
    return out


def _quantile(counts: Dict[int, int], n: int, q: float) -> float:
    rank = max(1, int(math.ceil(q * n)))
    seen = 0
    for b in sorted(counts):
        seen += counts[b]
        if seen >= rank:
            return bucket_value_ms(b)
    return bucket_value_ms(MAX_BUCKET)


def windowed_count(wire: Dict[str, Any], window_s: float,
                   as_of_epoch: Optional[int] = None) -> int:
    """Trailing-window n of a count-only digest (SLO burn rates)."""
    return int(window_stats(wire, window_s, as_of_epoch)["count"])


# ---------------------------------------------------------------------------
# Per-process telemetry store
# ---------------------------------------------------------------------------


class PerfTelemetry:
    """Named windowed digests + a flat cumulative counter map — the
    per-process half of the fleet telemetry plane. Thread-safe; the
    per-observation cost is one short lock + a dict bump (the engine
    step clock observes per DISPATCH, never per token)."""

    def __init__(self, epoch_s: float = 5.0, window_s: float = 60.0):
        self.epoch_s = float(epoch_s)
        self.window_s = float(window_s)
        self._lock = threading.Lock()
        self._digests: Dict[str, WindowedDigest] = {}
        self._counters: Dict[str, float] = {}

    def configure(self, epoch_s: float, window_s: float) -> None:
        """Re-shape the rings (boot-time only — the server applies the
        ``slo.epoch_s``/``slo.window_s`` config before traffic; a live
        reconfigure would discard the accumulated epochs)."""
        with self._lock:
            self.epoch_s = float(epoch_s)
            self.window_s = float(window_s)
            self._digests.clear()

    # -- recording ---------------------------------------------------------

    def observe(self, name: str, value_ms: float) -> None:
        with self._lock:
            self._digest_locked(name).observe(value_ms)

    def count(self, name: str, k: int = 1) -> None:
        with self._lock:
            self._digest_locked(name).count(k)

    def add_counter(self, name: str, delta: float) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0.0) + delta

    def _digest_locked(self, name: str) -> WindowedDigest:
        d = self._digests.get(name)
        if d is None:
            d = self._digests[name] = WindowedDigest(self.epoch_s,
                                                     self.window_s)
        return d

    # -- snapshots ---------------------------------------------------------

    def counters(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._counters)

    def wire_digests(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {name: d.to_wire(name)
                    for name, d in sorted(self._digests.items())}

    def wire_digest(self, name: str) -> Dict[str, Any]:
        """One series' wire dict ({} when it has no observations) —
        for callers that need a single series (the /server/stats
        sliding p99) without serializing the whole store."""
        with self._lock:
            d = self._digests.get(name)
            return d.to_wire(name) if d is not None else {}

    def wire(self) -> Dict[str, Any]:
        """The FleetTelemetry frame body (sans member_id): bounded by
        construction — a fixed digest-name set × a bounded epoch ring ×
        sparse buckets, and a counter per (engine, kind, field)."""
        with self._lock:
            return {
                "digests": [d.to_wire(name)
                            for name, d in sorted(self._digests.items())],
                "counters": [{"name": n, "value": v}
                             for n, v in sorted(self._counters.items())],
            }

    def stats(self, window_s: Optional[float] = None,
              as_of_epoch: Optional[int] = None) -> Dict[str, Any]:
        window = window_s or self.window_s
        return {
            name: window_stats(w, window, as_of_epoch)
            for name, w in self.wire_digests().items()
        }

    def as_of_epoch(self) -> int:
        return int(time.time() // self.epoch_s)


# ---------------------------------------------------------------------------
# SLO layer
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class SloSettings:
    """Config section ``slo`` (serving/config.py): request-level
    latency objectives. 0 = that objective is unset; a request with no
    applicable objective gets no verdict (and never counts toward the
    burn rate). Per-tenant overrides win over the global values."""

    ttft_ms: float = 0.0
    tbt_p99_ms: float = 0.0
    tenant_ttft_ms: Mapping[str, float] = field(default_factory=dict)
    tenant_tbt_ms: Mapping[str, float] = field(default_factory=dict)
    window_s: float = 60.0
    epoch_s: float = 5.0

    def enabled(self) -> bool:
        return bool(self.ttft_ms or self.tbt_p99_ms
                    or self.tenant_ttft_ms or self.tenant_tbt_ms)

    def limits_for(self, tenant: str) -> Tuple[float, float]:
        """(ttft_ms, tbt_ms) applicable to ``tenant`` (0 = none)."""
        return (
            float(self.tenant_ttft_ms.get(tenant, self.ttft_ms)),
            float(self.tenant_tbt_ms.get(tenant, self.tbt_p99_ms)),
        )

    def to_dict(self) -> Dict[str, Any]:
        return {
            "ttft_ms": self.ttft_ms,
            "tbt_p99_ms": self.tbt_p99_ms,
            "tenant_ttft_ms": dict(self.tenant_ttft_ms),
            "tenant_tbt_ms": dict(self.tenant_tbt_ms),
            "window_s": self.window_s,
            "epoch_s": self.epoch_s,
        }


def slo_verdict(slo: SloSettings, tenant: str,
                ttft_s: Optional[float], tbt_s: Optional[float],
                status: str) -> Optional[Dict[str, Any]]:
    """Derive a request's SLO verdict from its exact phase partition
    (serving/flightrec.py): ``ttft_s`` is admit -> first token (the
    queue_wait + prefill + peer_fetch phases, exactly), ``tbt_s`` the
    mean inter-token gap of first -> last token (decode + handoff
    stalls — the client observes the stall, so the SLO charges it).
    Returns None when no objective applies; an errored request with an
    applicable objective is always a violation (goodput = useful
    completed work)."""
    ttft_lim, tbt_lim = slo.limits_for(tenant)
    if not ttft_lim and not tbt_lim:
        return None
    ttft_violated = bool(
        ttft_lim and (ttft_s is None or ttft_s * 1000.0 > ttft_lim))
    tbt_violated = bool(
        tbt_lim and tbt_s is not None and tbt_s * 1000.0 > tbt_lim)
    violated = ttft_violated or tbt_violated or status != "ok"
    out: Dict[str, Any] = {
        "verdict": "violated" if violated else "ok",
        "tenant": tenant,
    }
    if ttft_lim:
        out["ttft_violated"] = ttft_violated
    if tbt_lim:
        out["tbt_violated"] = tbt_violated
    if status != "ok":
        out["errored"] = True
    return out


# ---------------------------------------------------------------------------
# Enforced catalogs (distlint DL014 — docs/OBSERVABILITY.md
# "Performance telemetry" tables must list exactly these names)
# ---------------------------------------------------------------------------

#: top-level fields of the GET /server/perf payload
PERF_FIELDS = (
    "as_of_epoch",
    "epoch_s",
    "window_s",
    "engines",
    "windows",
    "slo",
    "digests",
    "fleet",
)

#: telemetry metric names registered in serving/metrics.py (the rest of
#: the metric namespace predates the telemetry plane and is DL006-only)
TELEMETRY_METRICS = (
    "engine_step_seconds_total",
    "engine_step_dispatches_total",
    "engine_step_tokens_total",
    "engine_step_events_total",
    "slo_requests_total",
    "slo_goodput_tokens_total",
    "fleet_telemetry_frames_total",
    "fleet_member_step_tokens",
    "fleet_member_ttft_p99_ms",
)

#: named digest series (the keys of /server/perf "digests"/"windows")
DIGEST_NAMES = (
    "ttft_ms",
    "tbt_ms",
    "queue_wait_ms",
    "latency_ms",
    "step_ms.prefill",
    "step_ms.decode_block",
    "step_ms.mixed",
    "step_ms.loop",
    "slo.ok",
    "slo.violated",
)


def build_perf_payload(
    perf: PerfTelemetry,
    slo: Optional[SloSettings],
    slo_counts: Optional[Dict[str, Dict[str, int]]] = None,
    goodput: Optional[Dict[str, int]] = None,
    fleet_members: Optional[Dict[str, Dict[str, Any]]] = None,
) -> Dict[str, Any]:
    """Assemble the ``GET /server/perf`` JSON (keys ⊆ PERF_FIELDS).

    ``fleet_members`` (registry host only): member_id -> {"digests":
    {name: wire}, "counters": {...}, "age_s": float} as ingested from
    FleetTelemetry frames. The merged view merges the LOCAL digests
    with every member's, per series, through the same merge_digests /
    window_stats pair an operator would use offline — so re-merging the
    response's own per-member digests reproduces the merged percentiles
    bit-for-bit."""
    as_of = perf.as_of_epoch()
    window = perf.window_s
    local_wires = perf.wire_digests()
    counters = perf.counters()

    engines: Dict[str, Dict[str, Any]] = {}
    for name, value in counters.items():
        parts = name.split(".")
        if parts[0] == "step" and len(parts) == 4:
            _, engine_id, kind, fld = parts
            eng = engines.setdefault(engine_id,
                                     {"kinds": {}, "events": {}})
            eng["kinds"].setdefault(kind, {})[fld] = value
        elif parts[0] == "events" and len(parts) == 3:
            _, engine_id, event = parts
            eng = engines.setdefault(engine_id,
                                     {"kinds": {}, "events": {}})
            eng["events"][event] = int(value)

    windows = {
        name: window_stats(w, window, as_of)
        for name, w in local_wires.items()
        if not name.startswith("slo.")
    }

    payload: Dict[str, Any] = {
        "as_of_epoch": as_of,
        "epoch_s": perf.epoch_s,
        "window_s": window,
        "engines": engines,
        "windows": windows,
        "digests": local_wires,
    }

    slo_block: Dict[str, Any] = {}
    if slo is not None and slo.enabled():
        slo_block["config"] = slo.to_dict()
    if slo_counts:
        slo_block["requests"] = {t: dict(v) for t, v in slo_counts.items()}
    if goodput:
        slo_block["goodput_tokens"] = dict(goodput)
    ok_w = windowed_count(local_wires.get("slo.ok", {}), window, as_of)
    bad_w = windowed_count(local_wires.get("slo.violated", {}), window,
                           as_of)
    if ok_w or bad_w:
        slo_block["window_requests"] = {"ok": ok_w, "violated": bad_w}
        slo_block["burn_rate"] = bad_w / (ok_w + bad_w)
    if slo_block:
        payload["slo"] = slo_block

    if fleet_members is not None:
        # slo.* burn-rate counters stay per-process (a fleet burn rate
        # would need per-member objectives to mean anything), so skip
        # them BEFORE the merge instead of merging and discarding
        series: Dict[str, List[Dict[str, Any]]] = {
            name: [w] for name, w in local_wires.items()
            if not name.startswith("slo.")
        }
        for member_id in sorted(fleet_members):
            for name, w in fleet_members[member_id].get("digests",
                                                        {}).items():
                if not name.startswith("slo."):
                    series.setdefault(name, []).append(w)
        payload["fleet"] = {
            "members": {
                m: {"counters": dict(v.get("counters", {})),
                    "digests": dict(v.get("digests", {})),
                    "age_s": round(float(v.get("age_s", 0.0)), 3)}
                for m, v in fleet_members.items()
            },
            "merged": {
                name: window_stats(merge_digests(ws), window, as_of)
                for name, ws in sorted(series.items())
            },
        }
    return payload
