"""Member↔member KV mesh with telemetry-learned wire costs
(docs/FLEET.md "KV mesh"; docs/CACHING.md cost model).

Two halves, one module:

**The mesh.** Historically every fleet KV byte relayed through the
registry host — a member-to-member prefix fetch terminated both bulk
streams on one NIC, capping fleet KV bandwidth at a single machine.
The mesh lets members dial each other's already-advertised ``data_port``
directly: the registry stays a pure *introduction broker*, pushing a
``KvIntro`` frame (member_id, host, data_port, stream grant) to every
member whenever an endpoint appears, changes, or dies (``gone=true``).
``MeshClient`` (worker side) turns intros into lazily-dialed
``KvDataChannel`` peers — the same bounded-streams/backoff/circuit-
breaker machinery the registry host uses, so a broken member↔member
wire is gated exactly like a broken registry↔member one. The fetch
instruction itself rides the control plane: the registry attaches a
fetch hint to the ``FleetSubmit`` it was sending anyway, and the member
pulls the prefix from its peer over its own mesh channel — bulk bytes
never touch the registry's sockets.

**The prices.** The routing cost model used to charge every cross-host
page the same ``fleet.kv_page_cost`` constant — a 100GbE wire and a
congested one priced identically. ``WireRateEstimator`` learns each
wire's real transfer rate from observed stream bytes/seconds in a
wall-clock-aligned epoch ring (the teledigest windowing idiom), and
``MeshWireRates`` keys estimators by ``(src, dst)``: the registry's own
channels observe locally, while member↔member wires reach the registry
as cumulative ``kvwire|src|dst|{bytes,seconds,chunks}`` counters
piggybacked on fleet telemetry. ``page_cost`` then scales the
configured constant by ``prior_rate / learned_rate`` — a cold wire
prices at exactly the constant (the prior), a fast wire gets cheaper, a
congested one dearer — so ``plan_route`` and the handoff election
charge the actual wire instead of guessing. Breaker-open wires never
reach pricing: they are excluded upstream (``wire_available`` /
``EngineStatus.data_plane``).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

#: counter-name separator for the telemetry piggyback: member ids
#: contain "." and ":" (host:pid), so the kvwire counter names use "|"
#: — "kvwire|<src>|<dst>|bytes" splits unambiguously
WIRE_COUNTER_PREFIX = "kvwire|"

#: clamp band for a learned per-page cost: never free (a fetch always
#: beats recompute on a miraculously fast wire, but not infinitely so)
#: and never priced past certainly-lose (the option drops out anyway)
_MIN_PAGE_COST = 0.01
_MAX_PAGE_COST = 1000.0


class WireRateEstimator:
    """Windowed bytes-per-second estimator for one directed wire.

    A wall-clock-aligned epoch ring (the serving/teledigest.py
    windowing idiom): observations land in ``time // epoch_s`` buckets,
    buckets older than ``window_s`` are pruned, and the rate is the
    window's summed bytes over summed busy-seconds. ``rate()`` is None
    while the window is empty — the wire is COLD and the caller must
    fall back to its configured prior instead of trusting a stale or
    absent measurement. Thread-safe: observations arrive from channel
    reader threads, reads from the scheduler's routing path. ``now``
    is injectable so tests drive the window deterministically."""

    def __init__(self, window_s: float = 30.0, epochs: int = 8):
        self.window_s = max(float(window_s), 0.001)
        self.epoch_s = self.window_s / max(int(epochs), 1)
        self._lock = threading.Lock()
        # epoch index -> [bytes, seconds, chunks]
        self._buckets: Dict[int, List[float]] = {}
        self._total_bytes = 0
        self._total_chunks = 0

    def observe(self, nbytes: int, seconds: float, chunks: int = 0,
                now: Optional[float] = None) -> None:
        if nbytes <= 0 or seconds <= 0:
            return
        now = time.time() if now is None else now
        idx = int(now // self.epoch_s)
        with self._lock:
            b = self._buckets.setdefault(idx, [0, 0.0, 0])
            b[0] += int(nbytes)
            b[1] += float(seconds)
            b[2] += int(chunks)
            self._total_bytes += int(nbytes)
            self._total_chunks += int(chunks)
            self._prune_locked(idx)

    def _prune_locked(self, now_idx: int) -> None:
        horizon = now_idx - int(self.window_s // self.epoch_s)
        for idx in [i for i in self._buckets if i < horizon]:
            del self._buckets[idx]

    def rate(self, now: Optional[float] = None) -> Optional[float]:
        """Learned bytes/s over the live window, or None when cold
        (no observation young enough to trust)."""
        now = time.time() if now is None else now
        now_idx = int(now // self.epoch_s)
        with self._lock:
            self._prune_locked(now_idx)
            nbytes = sum(b[0] for b in self._buckets.values())
            seconds = sum(b[1] for b in self._buckets.values())
        if nbytes <= 0 or seconds <= 0:
            return None
        return nbytes / seconds

    def totals(self) -> Tuple[int, int]:
        """Lifetime (bytes, chunks) observed — window-independent, for
        the ``kv_wires`` stats table."""
        with self._lock:
            return self._total_bytes, self._total_chunks


class _WireHandle:
    """The per-wire estimator facade a ``KvDataChannel`` holds: same
    observe/rate surface as ``WireRateEstimator``, but observations
    route through the owning ``MeshWireRates`` so the gauge and the
    telemetry piggyback stay in step with every stream."""

    __slots__ = ("_rates", "src", "dst")

    def __init__(self, rates: "MeshWireRates", src: str, dst: str):
        self._rates = rates
        self.src = src
        self.dst = dst

    def observe(self, nbytes: int, seconds: float, chunks: int = 0,
                now: Optional[float] = None) -> None:
        self._rates.observe(self.src, self.dst, nbytes, seconds,
                            chunks=chunks, now=now)

    def rate(self, now: Optional[float] = None) -> Optional[float]:
        return self._rates.rate(self.src, self.dst, now=now)


class MeshWireRates:
    """Registry of learned transfer rates keyed by directed wire
    ``(src, dst)`` — member ids, or ``"registry"`` for the host's own
    channels. Owns the bounded metric label sets: every observation
    refreshes ``fleet_kv_wire_rate_bytes_per_s{src,dst}``, and
    ``drop_member`` removes a dead member's series (the tenant-gauge
    policy — dead identities must not pin label sets forever). When a
    ``perf`` telemetry sink is wired (worker processes), observations
    also bump cumulative ``kvwire|src|dst|*`` counters so the registry
    host learns member↔member rates from the existing telemetry
    piggyback — no new wire frames for the data."""

    def __init__(self, window_s: float = 30.0,
                 prior_rate: float = 125_000_000.0,
                 metrics=None, perf=None):
        """``prior_rate`` (config ``fleet.kv_rate_prior``, bytes/s) is
        the rate the configured ``fleet.kv_page_cost`` constant is
        assumed to price: a wire measured at exactly the prior costs
        exactly the constant. <= 0 disables learned pricing (every
        wire stays at the constant) while still collecting rates for
        observability."""
        self.window_s = float(window_s)
        self.prior_rate = float(prior_rate)
        self.metrics = metrics
        self.perf = perf
        self._lock = threading.Lock()
        self._est: Dict[Tuple[str, str], WireRateEstimator] = {}

    def estimator(self, src: str, dst: str) -> _WireHandle:
        """The handle a ``KvDataChannel`` feeds its stream
        observations into (``rate_estimator=`` ctor param)."""
        return _WireHandle(self, src, dst)

    def _estimator(self, src: str, dst: str) -> WireRateEstimator:
        key = (src, dst)
        with self._lock:
            est = self._est.get(key)
            if est is None:
                est = self._est[key] = WireRateEstimator(self.window_s)
            return est

    def observe(self, src: str, dst: str, nbytes: int, seconds: float,
                chunks: int = 0, now: Optional[float] = None) -> None:
        est = self._estimator(src, dst)
        est.observe(nbytes, seconds, chunks=chunks, now=now)
        if self.metrics is not None:
            r = est.rate(now=now)
            if r is not None:
                self.metrics.set_kv_wire_rate(src, dst, r)
        if self.perf is not None:
            base = f"{WIRE_COUNTER_PREFIX}{src}|{dst}|"
            self.perf.add_counter(base + "bytes", float(nbytes))
            self.perf.add_counter(base + "seconds", float(seconds))
            self.perf.add_counter(base + "chunks", float(chunks))

    def rate(self, src: str, dst: str,
             now: Optional[float] = None) -> Optional[float]:
        with self._lock:
            est = self._est.get((src, dst))
        return est.rate(now=now) if est is not None else None

    def page_cost(self, src: str, dst: str, base_cost: float,
                  now: Optional[float] = None) -> Optional[float]:
        """Learned per-page cost for the ``(src, dst)`` wire, or None
        when the wire is cold / learned pricing is disabled — the
        caller then charges the static constant (the prior). A wire
        measured at the prior rate costs exactly ``base_cost``; slower
        wires scale up, faster ones down, clamped to a sane band."""
        if self.prior_rate <= 0:
            return None
        learned = self.rate(src, dst, now=now)
        if learned is None or learned <= 0:
            return None
        cost = base_cost * (self.prior_rate / learned)
        return min(max(cost, _MIN_PAGE_COST), _MAX_PAGE_COST)

    def drop_member(self, member_id: str) -> None:
        """A member died: drop every wire touching it and retract its
        gauge series (bounded label sets — dead host:pid identities
        would otherwise grow the gauge forever)."""
        with self._lock:
            gone = [k for k in self._est
                    if member_id in (k[0], k[1])]
            for key in gone:
                del self._est[key]
        if self.metrics is not None:
            for src, dst in gone:
                self.metrics.remove_kv_wire_rate(src, dst)

    def snapshot(self, now: Optional[float] = None
                 ) -> List[Dict[str, Any]]:
        """The ``kv_wires`` stats rows: one per observed wire, sorted
        for a stable table."""
        with self._lock:
            items = sorted(self._est.items())
        out = []
        for (src, dst), est in items:
            nbytes, chunks = est.totals()
            out.append({
                "src": src, "dst": dst,
                "rate_bytes_per_s": est.rate(now=now),
                "bytes": nbytes, "chunks": chunks,
            })
        return out


class MeshPeer:
    """The fetch-source adapter a worker hands its PrefixFetcher: the
    ``submit_prefix_export`` surface (serving/disagg.py) satisfied over
    a mesh ``KvDataChannel`` to the peer member. Mirrors
    RemoteRunner.submit_prefix_export — same exactly-once callback
    contract, including the fail-fast arm when the wire is missing or
    its breaker is open."""

    is_remote = True

    def __init__(self, channel, engine_id: str):
        """``engine_id`` is the PEER's member-local engine id (what its
        KvDataServer resolves exports against)."""
        self.channel = channel
        self.engine_id = engine_id

    def submit_prefix_export(self, request_id, hashes, chunk_pages: int,
                             wire_quant: str,
                             on_done: Callable, trace=None) -> None:
        ch = self.channel
        if ch is None or not ch.wire_available():
            on_done(None, "mesh peer wire unavailable")
            return
        ch.fetch_prefix(request_id, self.engine_id, hashes,
                        chunk_pages, wire_quant, trace, on_done)


class MeshClient:
    """Worker-side registry of direct member↔member KV data channels,
    driven entirely by ``KvIntro`` frames from the registry broker.

    Channels are created on introduction but dial LAZILY on first use
    (the KvDataChannel contract) — an introduced-but-idle mesh costs no
    sockets. A re-intro with a changed endpoint replaces the channel; a
    ``gone`` retraction closes it and drops the wire's learned-rate
    series. Each channel feeds ``rates`` under the
    ``(this member, peer member)`` key, which the worker's telemetry
    piggyback ships to the registry as kvwire counters."""

    def __init__(self, member_id: str, rates: MeshWireRates,
                 metrics=None, connect_timeout_s: float = 5.0,
                 breaker_threshold: int = 3, breaker_open_s: float = 5.0,
                 retry_budget=None):
        self.member_id = member_id
        self.rates = rates
        self.metrics = metrics
        self.connect_timeout_s = connect_timeout_s
        self.breaker_threshold = breaker_threshold
        self.breaker_open_s = breaker_open_s
        self.retry_budget = retry_budget
        self._lock = threading.Lock()
        self._peers: Dict[str, Any] = {}  # peer member_id -> KvDataChannel
        self._closed = False

    def on_intro(self, obj: Dict[str, Any]) -> None:
        """Apply one KvIntro frame (worker reader thread)."""
        from distributed_inference_server_tpu.serving.fleet_kv import (
            KvDataChannel,
        )

        peer = obj.get("member_id", "")
        if not peer or peer == self.member_id:
            return
        gone = bool(obj.get("gone"))
        host = obj.get("host", "")
        port = int(obj.get("data_port", 0) or 0)
        if gone or not host or port <= 0:
            self._drop(peer, "mesh peer retracted")
            return
        with self._lock:
            if self._closed:
                return
            old = self._peers.get(peer)
            if old is not None and old.address == (host, port):
                return  # unchanged re-intro (broker resends are cheap)
            self._peers[peer] = KvDataChannel(
                peer, host, port,
                max_streams=max(1, int(obj.get("max_streams", 0) or 1)),
                connect_timeout_s=self.connect_timeout_s,
                metrics=self.metrics,
                breaker_threshold=self.breaker_threshold,
                breaker_open_s=self.breaker_open_s,
                retry_budget=self.retry_budget,
                rate_estimator=self.rates.estimator(self.member_id, peer),
                peer_wire=True,
            )
        if old is not None:
            old.close("mesh peer endpoint changed")
        logger.info("mesh: %s introduced to %s at %s:%d",
                    self.member_id, peer, host, port)

    def _drop(self, peer: str, reason: str) -> None:
        with self._lock:
            ch = self._peers.pop(peer, None)
        if ch is not None:
            ch.close(reason)
        self.rates.drop_member(peer)

    def channel(self, peer: str):
        """The live channel to ``peer``, or None if never introduced
        (the caller degrades to plain recompute)."""
        with self._lock:
            return self._peers.get(peer)

    def peer(self, member_id: str, engine_id: str) -> Optional[MeshPeer]:
        """A MeshPeer fetch source over the channel to ``member_id``,
        or None when the mesh has no wire to it."""
        ch = self.channel(member_id)
        if ch is None:
            return None
        return MeshPeer(ch, engine_id)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            peers = dict(self._peers)
        return {pid: ch.stats() for pid, ch in sorted(peers.items())}

    def close(self) -> None:
        with self._lock:
            self._closed = True
            peers, self._peers = dict(self._peers), {}
        for ch in peers.values():
            ch.close("mesh client closed")
