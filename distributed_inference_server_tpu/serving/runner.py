"""Engine runner: a dedicated thread owning one ``LLMEngine`` replica.

The reference's ``InferenceWorker`` (``design.md:335-342`` [spec]) maps to
one runner = one engine replica = one "worker". The engine itself is
single-owner and synchronous (engine/engine.py); every interaction with it
— request admission, aborts, embeddings — goes through a thread-safe inbox
drained on the runner thread between decode steps. Step outputs are fanned
out to per-request ``ResultSink``s, which the HTTP layer bridges onto the
asyncio loop.

Failure semantics (``requirements.md:104-110,130-134``):
- per-request failures surface as ``StepOutput.error`` and poison only that
  request (Property 22);
- an unhandled exception in the step loop marks the runner unhealthy and
  fails all in-flight requests; the scheduler's health checker notices the
  flag within its check interval (<5 s detection, requirements.md:133) and
  can ``restart()`` it (worker self-restart, requirements.md:109).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Protocol, Sequence

import numpy as np

from distributed_inference_server_tpu.core.models import FinishReason, Usage
from distributed_inference_server_tpu.core.types import RequestId
from distributed_inference_server_tpu.engine.engine import (
    LLMEngine,
    SamplingParams,
    StepOutput,
)
from distributed_inference_server_tpu.serving import faults
from distributed_inference_server_tpu.serving.metrics import (
    EngineStatus,
    MetricsCollector,
)

logger = logging.getLogger(__name__)


class ResultSink(Protocol):
    """Receives a request's step outputs. Methods are called on the runner
    thread and must be non-blocking and exception-free; the HTTP layer's
    sinks bounce to the asyncio loop via ``call_soon_threadsafe``."""

    def on_token(self, token_id: int, text: str, token_index: int,
                 logprob=None) -> None: ...

    def on_done(self, finish_reason: FinishReason, usage: Usage) -> None: ...

    def on_error(self, message: str, code: str) -> None: ...


class ServerRequest:
    """A validated, tokenized request handed to the serving spine."""

    __slots__ = ("request_id", "prompt_ids", "params", "sink", "submitted_at",
                 "first_token_at", "span", "engine_span", "redispatches",
                 "tenant")

    def __init__(
        self,
        request_id: RequestId,
        prompt_ids: List[int],
        params: SamplingParams,
        sink: ResultSink,
        span=None,
        tenant: str = "default",
    ):
        self.request_id = request_id
        self.prompt_ids = prompt_ids
        self.params = params
        self.sink = sink
        self.submitted_at = time.monotonic()
        self.first_token_at: Optional[float] = None
        # request-lifecycle tracing (S12): root span owned by the handler,
        # engine child span owned by the runner
        self.span = span
        self.engine_span = None
        # crash-safe redispatch attempts consumed (docs/RESILIENCE.md):
        # bounded by the dispatcher so a systemic crash cannot bounce a
        # request around the fleet forever
        self.redispatches = 0
        # per-tenant fair admission key (core/queue.py DRR; docs/FLEET.md)
        self.tenant = tenant or "default"


class EngineRunner:
    """Runs one engine on a dedicated thread; thread-safe façade."""

    def __init__(
        self,
        engine_id: str,
        engine_factory: Callable[[], LLMEngine],
        metrics: Optional[MetricsCollector] = None,
        tracer=None,
        role: str = "unified",
        disagg=None,
        recorder=None,
    ):
        """``role`` ("prefill" | "decode" | "unified") and ``disagg``
        (the DisaggController) enable disaggregated serving
        (serving/disagg.py): a prefill runner admits requests
        prefill-only and exports each finished prefill to the controller
        for migration; a decode runner receives them via
        ``submit_resume``. Unified (the default) is today's monolithic
        behavior exactly.

        ``recorder`` (serving/flightrec.py): first-token / decode-block
        / terminal events land in the per-request flight-recorder
        timeline. None (the default) keeps the per-token path free of
        recorder work entirely."""
        self.engine_id = engine_id
        self.role = role
        self._disagg = disagg
        self._factory = engine_factory
        self.metrics = metrics
        self.tracer = tracer
        self.recorder = recorder
        # crash-safe redispatch hook (docs/RESILIENCE.md): the server
        # wires this to Dispatcher.redispatch. Called from _fail_all_of
        # for an in-flight request that streamed ZERO tokens; returns
        # True when it took ownership (the request will terminate on
        # another replica — this runner must NOT also resolve its sink).
        self.redispatch: Optional[
            Callable[[ServerRequest, str, str], bool]
        ] = None
        self._inbox: Deque[Callable[[], None]] = deque()
        self._inbox_lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._healthy = False
        self._last_error: Optional[str] = None
        self._total_processed = 0
        # lock-free by design: per-request dict ops are GIL-atomic and
        # the exactly-once protocol is pop-first — every terminal path
        # pops before resolving (docs/RESILIENCE.md)
        # distlint: registry
        self._inflight: Dict[RequestId, ServerRequest] = {}  # distlint: ignore[DL008]
        # submit_resume callbacks not yet run by the engine thread: a
        # crash/shutdown before the inbox drains resolves them from
        # _fail_all (exactly-once via dict.pop), otherwise the migration
        # job would leak in DisaggController._migrating and wedge every
        # future drain on pending_count()
        self._pending_resumes: Dict[RequestId, Callable] = {}
        # streamed handoff exports in flight (engine HandoffExportSession
        # + the request + the controller stream job), advanced by
        # _pump_export_jobs between steps; owned by the runner thread —
        # the only cross-thread touches are GIL-atomic pops at crash/
        # restart time, after the thread died  # distlint: ignore[DL008]
        self._export_jobs: Dict[RequestId, list] = {}
        # phased-import state on a DECODE runner: open sessions awaiting
        # their commit (request_id -> (KvImportSession, engine)), plus
        # un-run open callbacks for crash-time resolution
        self._import_sessions: Dict[RequestId, tuple] = {}
        # token -> callback maps written from submitter threads (disagg
        # worker, dispatcher/fetcher, runner callbacks) and resolved on
        # the runner thread or at crash time: per-token dict ops are
        # GIL-atomic and exactly-once is pop-first by construction
        # (docs/RESILIENCE.md)  # distlint: ignore[DL008]
        self._pending_opens: Dict[str, Callable] = {}
        # un-run peer-fetch EXPORT callbacks (fleet prefix sharing,
        # serving/disagg.py PrefixFetcher): a crash before the inbox
        # drains resolves them from _fail_all — the fetcher then falls
        # back to recompute on the target instead of waiting forever on
        # a dead peer (same GIL-atomic pop-first exactly-once protocol)
        # distlint: ignore[DL008]
        self._pending_fetches: Dict[str, Callable] = {}
        self._pending_embeds: Dict[int, Callable] = {}
        self._embed_seq = 0
        # incremental embeddings jobs, advanced one device batch per
        # runner-loop iteration (owned by the engine thread)
        self._embed_jobs: Deque[dict] = deque()
        self._engine: Optional[LLMEngine] = None
        self._thread: Optional[threading.Thread] = None
        self._cache_seen = {"hits": 0, "misses": 0, "evictions": 0,
                            "host_hit_pages": 0}
        # mixed-step counter watermarks (engine.mixed_stats() reports
        # totals; the collector wants deltas)
        self._mixed_seen = {"prefill_tokens": 0, "decode_tokens": 0}
        # payload-byte watermarks (engine.payload_byte_counters()
        # reports totals by encoding kind; the collector wants deltas)
        self._payload_seen: Dict[str, int] = {}
        # looped-block counter watermarks (engine.loop_stats() reports
        # totals; the collector wants deltas — same shape as the mixed
        # block)
        self._loop_seen: Dict[str, Any] = {"steps": 0, "exits": {}}
        # step-clock watermarks (engine.step_clock_stats() reports
        # cumulative kind/event counters; the collector wants deltas —
        # same shape as the mixed block, docs/OBSERVABILITY.md)
        self._sc_seen: Dict[str, Dict] = {"kinds": {}, "events": {}}
        # rolling prefix digest for cache-aware routing (ISSUE 5):
        # refreshed on the engine thread (allocator state is single-
        # owner), read as an immutable snapshot by status() from any
        # thread
        self._prefix_digest: frozenset = frozenset()
        self._digest_ts = 0.0
        # old engines still finishing their in-flight requests after a
        # model hot-swap (Req 13.3: in-flight completes on the old model)
        self._draining: List[LLMEngine] = []

    # -- lifecycle ---------------------------------------------------------

    def start(self, wait_ready: bool = True, timeout: float = 300.0) -> None:
        """Spawn the runner thread; optionally block until the engine is
        constructed (model loaded) and the runner reports ready
        (reference Req 7.2: worker reports ready before serving)."""
        ready = threading.Event()
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, args=(ready,), name=f"engine-{self.engine_id}",
            daemon=True,
        )
        self._thread.start()
        if wait_ready and not ready.wait(timeout):
            raise TimeoutError(f"engine {self.engine_id} failed to start in {timeout}s")
        if wait_ready and not self._healthy:
            raise RuntimeError(
                f"engine {self.engine_id} failed to initialize: {self._last_error}"
            )

    def shutdown(self, timeout: float = 30.0) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout)
        # health flag: GIL-atomic bool; writers are the runner thread and
        # lifecycle callers, readers tolerate one stale check (the health
        # loop re-reads every sweep)  # distlint: ignore[DL008]
        self._healthy = False
        if self.metrics:
            self.metrics.set_engine_up(self.engine_id, False)
        # anything still in flight will never complete — tell the clients
        self._fail_all("engine shut down before request completion")

    def restart(self, wait_ready: bool = True, timeout: float = 300.0) -> None:
        """Tear down and bring the engine back (worker self-restart,
        requirements.md:109)."""
        self.shutdown()
        # under the lock even though the runner thread is joined: submit()
        # may still race in from the dispatcher thread (distlint DL002)
        with self._inbox_lock:
            self._inbox.clear()
        self._inflight.clear()
        self._export_jobs.clear()
        self.start(wait_ready=wait_ready, timeout=timeout)

    def set_role(self, role: str) -> None:
        """Re-role this runner at runtime (fleet role rebalancing,
        serving/fleet.py RoleBalancer). The flip is one attribute write:
        ``submit`` reads the role per batch, so the NEXT admission batch
        follows the new role while in-flight requests finish under the
        old one (a unified→prefill flip never strands a decode)."""
        self.role = role

    # -- submission (any thread) -------------------------------------------

    def submit(self, requests: Sequence[ServerRequest]) -> None:
        reqs = list(requests)
        # register in _inflight immediately (not inside the closure) so a
        # crash between submit and inbox-drain still fails these sinks
        for r in reqs:
            self._inflight[r.request_id] = r
        if not self._healthy:
            self._fail_all_of(reqs, self._last_error or "engine unavailable")
            return

        # admit unified when the decode fleet is gone (e.g. scaled away):
        # prefill-only admission would pay a KV serialize + retry +
        # in-place fallback on every request, forever
        prefill_only = (
            self.role == "prefill"
            and self._disagg is not None
            and self._disagg.has_decode_targets()
        )

        def _do() -> None:
            for r in reqs:
                if r.request_id in self._inflight:  # not aborted meanwhile
                    if self.tracer and r.span is not None:
                        r.engine_span = self.tracer.start(
                            "engine.infer", parent=r.span.context(),
                            engine_id=self.engine_id,
                            request_id=str(r.request_id),
                            prompt_tokens=len(r.prompt_ids),
                        )
                    self._engine.add_request(r.request_id, r.prompt_ids,
                                             r.params,
                                             prefill_only=prefill_only)

        self._post(_do)

    def abort(self, request_id: RequestId) -> None:
        def _do() -> None:
            if not self._engine.abort(request_id):
                for eng in self._draining:
                    if eng.abort(request_id):
                        break
            self._inflight.pop(request_id, None)

        self._post(_do)

    def submit_resume(self, exp, req: ServerRequest,
                      on_done: Callable[[bool, Optional[str]], None]) -> None:
        """Resume a migrated sequence on this runner's engine (KV handoff
        import, serving/disagg.py). ``on_done(ok, err)`` fires exactly
        once from the runner thread — or here, if the engine is already
        down. On ok=False the request has been deregistered again and the
        caller (the DisaggController) owns its fate (fallback)."""
        # register BEFORE the health check (same crash-safe ordering as
        # submit_embed): a crash between check and registration would
        # otherwise strand on_done un-called and leak the migration job.
        # _pending_resumes FIRST: a concurrent _fail_all that saw
        # _inflight but not the callback would sink-fail the request AND
        # let the fallback resume it — two contradictory terminal paths.
        # Cross-thread by design: GIL-atomic dict ops + exactly-once via
        # dict.pop  # distlint: ignore[DL008]
        self._pending_resumes[req.request_id] = on_done
        self._inflight[req.request_id] = req
        if not self._healthy:
            self._inflight.pop(req.request_id, None)
            cb = self._pending_resumes.pop(req.request_id, None)
            if cb is not None:  # None: _fail_all already resolved it
                cb(False, self._last_error or "engine unavailable")
            return

        def _do() -> None:
            cb = self._pending_resumes.pop(req.request_id, None)
            if cb is None:
                return  # already resolved by _fail_all (crash/shutdown)
            if req.request_id not in self._inflight:
                # aborted between registration and import: resolved (no
                # fallback wanted), but NOT a real transfer — the
                # "aborted" marker keeps the handoff metrics honest
                cb(True, "aborted")
                return
            try:
                self._engine.import_sequence(exp)
            except Exception as e:  # noqa: BLE001 — import fault domain
                self._inflight.pop(req.request_id, None)
                cb(False, str(e))
                return
            cb(True, None)

        self._post(_do)

    def submit_import_open(self, request_id: RequestId, prefix_pages: int,
                           chunks, on_done: Callable[[bool, Optional[str]],
                                                     None]) -> None:
        """Phase 1 of a streamed handoff on the TARGET runner: open an
        incremental import session, reserve the prefix pages, and absorb
        the prefix chunks — all while the source sequence is still
        decoding in place. ``on_done(ok, err)`` fires exactly once (from
        the runner thread, or here if the engine is down); ok=True means
        the target is ready for the switchover commit."""
        token = f"open:{request_id}"
        self._pending_opens[token] = on_done
        if not self._healthy:
            cb = self._pending_opens.pop(token, None)
            if cb is not None:
                cb(False, self._last_error or "engine unavailable")
            return

        def _do() -> None:
            cb = self._pending_opens.pop(token, None)
            if cb is None:
                return  # resolved by _fail_all
            engine = self._engine
            session = None
            try:
                session = engine.import_stream_open(request_id, prefix_pages)
                engine.import_stream_add(session, chunks)
            except Exception as e:  # noqa: BLE001 — import fault domain
                if session is not None:
                    # the open reserved pages; a chunk-validation failure
                    # (crc, shape, duplicate) must hand them back or the
                    # decode engine bleeds capacity on every bad stream
                    try:
                        engine.import_stream_abort(session)
                    except Exception as abort_exc:  # noqa: BLE001
                        self._absorbed("import_abort", abort_exc)
                cb(False, str(e))
                return
            # bind the session to ITS engine: a hot-swap between open
            # and commit must not scatter into the new model's pool
            self._import_sessions[request_id] = (session, engine)
            cb(True, None)

        self._post(_do)

    def submit_import_commit(self, exp, req: ServerRequest,
                             on_done: Callable[[bool, Optional[str]],
                                               None]) -> None:
        """Phase 2: absorb the tail delta, validate, publish, and seat —
        the part of the import that sits inside the migrated sequence's
        stall window. Same registration/crash-safety contract as
        submit_resume (on_done exactly once; ok=False hands the request
        back to the controller's fallback)."""
        self._pending_resumes[req.request_id] = on_done
        self._inflight[req.request_id] = req
        if not self._healthy:
            self._inflight.pop(req.request_id, None)
            cb = self._pending_resumes.pop(req.request_id, None)
            self._drop_import_session(req.request_id)
            if cb is not None:
                cb(False, self._last_error or "engine unavailable")
            return

        def _do() -> None:
            cb = self._pending_resumes.pop(req.request_id, None)
            if cb is None:
                return  # already resolved by _fail_all (crash/shutdown)
            entry = self._import_sessions.pop(req.request_id, None)
            if req.request_id not in self._inflight:
                # aborted between registration and commit
                if entry is not None:
                    entry[1].import_stream_abort(entry[0])
                cb(True, "aborted")
                return
            if entry is None:
                self._inflight.pop(req.request_id, None)
                cb(False, "no open import session (engine restarted?)")
                return
            session, engine = entry
            if engine is not self._engine:
                # hot-swapped since open: the reserved pages belong to
                # the OLD pool; abort there and reject the commit
                engine.import_stream_abort(session)
                self._inflight.pop(req.request_id, None)
                cb(False, "engine swapped mid-import")
                return
            try:
                engine.import_stream_commit(session, exp)
            except Exception as e:  # noqa: BLE001 — import fault domain
                self._inflight.pop(req.request_id, None)
                cb(False, str(e))
                return
            cb(True, None)

        self._post(_do)

    def submit_import_abort(self, request_id: RequestId) -> None:
        """Drop an opened-but-uncommitted import (source cancelled the
        stream / client disconnect): release the reserved pages."""
        self._post(lambda: self._drop_import_session(request_id))

    # -- fleet prefix sharing (peer fetch, serving/disagg.py) --------------

    def submit_prefix_export(
        self, request_id: RequestId, hashes: Sequence[int],
        chunk_pages: int, wire_quant: str,
        on_done: Callable[[Optional[tuple], Optional[str]], None],
        trace=None,
    ) -> None:
        """Peer-fetch SOURCE side: serialize this engine's cached prefix
        chain for ``hashes`` (engine.export_prefix_chunks — HBM and
        host tier, consecutive from the head) on the engine thread.
        ``on_done((depth, chunks), None)`` or ``on_done(None, err)``
        fires exactly once — from the runner thread, or here/at crash
        time if the engine is (or becomes) unavailable, so a peer dying
        mid-fetch degrades the caller to recompute instead of wedging
        the request (docs/RESILIENCE.md). ``trace`` exists for surface
        parity with RemoteRunner (serving/fleet_kv.py carries it on the
        wire); an in-process export has nowhere to ship it."""
        token = f"pfx:{request_id}"
        self._pending_fetches[token] = on_done
        if not self._healthy:
            cb = self._pending_fetches.pop(token, None)
            if cb is not None:
                cb(None, self._last_error or "engine unavailable")
            return

        def _do() -> None:
            cb = self._pending_fetches.pop(token, None)
            if cb is None:
                return  # resolved by _fail_all (crash/shutdown)
            try:
                depth, chunks = self._engine.export_prefix_chunks(
                    hashes, chunk_pages=chunk_pages, wire_quant=wire_quant
                )
            except Exception as e:  # noqa: BLE001 — export fault domain
                cb(None, str(e))
                return
            cb((depth, chunks), None)

        self._post(_do)

    def submit_prefix_import(
        self, request_id: RequestId, tokens: Sequence[int], chunks,
        on_done: Callable[[bool, Optional[str]], None],
    ) -> None:
        """Peer-fetch TARGET side: validate-and-scatter the fetched
        prefix chunks into this engine's prefix cache
        (engine.import_prefix) so the request submitted right after
        matches them. Same exactly-once callback contract as
        submit_import_open (ok=False → the fetcher falls back to plain
        recompute; the pages were released by the aborted session)."""
        token = f"pfx-import:{request_id}"
        self._pending_opens[token] = on_done
        if not self._healthy:
            cb = self._pending_opens.pop(token, None)
            if cb is not None:
                cb(False, self._last_error or "engine unavailable")
            return

        def _do() -> None:
            cb = self._pending_opens.pop(token, None)
            if cb is None:
                return  # resolved by _fail_all
            try:
                self._engine.import_prefix(tokens, chunks)
            except Exception as e:  # noqa: BLE001 — import fault domain
                cb(False, str(e))
                return
            cb(True, None)

        self._post(_do)

    def _drop_import_session(self, request_id: RequestId) -> None:
        entry = self._import_sessions.pop(request_id, None)
        if entry is not None:
            try:
                entry[1].import_stream_abort(entry[0])
            except Exception as e:  # noqa: BLE001 — cleanup isolation
                self._absorbed("import_abort", e)

    def _drain_handoffs(self) -> bool:
        """Export finished prefills parked by the engine and queue their
        migration (prefill-role runners only). Runs on the runner thread
        between steps; returns True if it moved anything.

        With ``disagg.stream`` on (the default) each export runs as a
        STREAMED job: the sequence resumes decoding in place while its
        immutable prefix pages serialize (engine.export_handoff_begin),
        and one runner-loop iteration later the switchover drains the
        pipeline, serializes only the tail delta, and enqueues the
        migration — the request's decode pause is O(tail), not
        O(seq_len). Draft-model engines and too-short completions take
        the monolithic path (engine.export_handoff)."""
        if self._disagg is None or self._engine is None:
            return False
        worked = self._pump_export_jobs()
        ids = self._engine.handoff_ready_ids()
        if not ids:
            return worked
        settings = self._disagg.settings
        stream = settings.stream and self._engine.draft_state is None
        for rid in ids:
            # pop-tolerant engine-thread read: only crash sweeps pop
            # concurrently, and the None arm below handles that winner
            # distlint: ignore[DL015]
            req = self._inflight.get(rid)
            if req is None:
                # aborted after readiness: clear the engine-side state
                self._engine.abort(rid)
                continue
            try:
                if stream:
                    session = self._engine.export_handoff_begin(
                        rid, chunk_pages=settings.chunk_pages,
                        wire_quant=settings.wire_quant,
                    )
                    if session is not None:
                        entry = [session, req, None]
                        self._export_jobs[rid] = entry
                        # serialize + open the target NOW (the pulls
                        # overlap the in-flight decode pipeline) so the
                        # overlap window stays a couple of blocks wide
                        self._advance_export_job(rid, entry)
                        self._advance_export_job(rid, entry)
                        continue
                    # not worth streaming (tiny prefix / short budget)
                stalled_at = time.monotonic()
                exp = self._engine.export_handoff(
                    rid, wire_quant=settings.wire_quant)
            except Exception as e:  # noqa: BLE001 — per-request isolation
                # the engine may still hold the sequence (and its pages);
                # abort releases them and clears has_work, or the runner
                # loop would busy-spin on a zombie forever
                self._engine.abort(rid)
                self._inflight.pop(rid, None)
                if self.recorder is not None:
                    self.recorder.finish(rid, "error",
                                         code="handoff_failed")
                try:
                    req.sink.on_error(f"KV export failed: {e}",
                                      "handoff_failed")
                except Exception as sink_exc:  # noqa: BLE001
                    self._absorbed("sink_error", sink_exc)
                continue
            if exp is None:
                continue
            exp.source_engine = self.engine_id
            exp.stalled_at = stalled_at
            self._inflight.pop(rid, None)
            self._disagg.enqueue(exp, req, self)
        return True

    def _pump_export_jobs(self) -> bool:
        """Advance streamed exports one stage per runner-loop iteration
        (the sequence decodes a block between stages — that is the
        overlap window): serialize the prefix, open the target through
        the controller (phase 1), poll until the target is ready, then
        switch over — export only the tail delta and commit (phase 2).
        Any failure before the switchover costs nothing: the sequence
        just keeps decoding in place."""
        if not self._export_jobs:
            return False
        for rid, entry in list(self._export_jobs.items()):
            self._advance_export_job(rid, entry)
        return True

    def _advance_export_job(self, rid, entry) -> None:
        """One stage of one streamed export; exceptions are contained to
        the request (per-request isolation)."""
        session, req, job = entry
        try:
            if session.dead:
                self._drop_export_job(rid, job, record=False)
                return
            if not session.prefix_done:
                self._engine.export_handoff_pump(session)
                return  # target opens while the next block decodes
            if job is None:
                job = self._disagg.open_stream(
                    rid, session.chunks, len(session.prefix_pages),
                    session.wire_quant, req, self,
                )
                if job is None:  # controller not accepting
                    self._cancel_export(rid, session, None, record=False)
                    return
                entry[2] = job
                return
            if job.status == "opening":
                if time.monotonic() > job.deadline:
                    self._cancel_export(rid, session, job, record=True)
                return
            if job.status in ("failed", "cancelled"):
                self._cancel_export(rid, session, job,
                                    record=job.status == "failed")
                return
            # target ready -> switchover
            exp, outputs = self._engine.export_handoff_finish(session)
            self._dispatch(outputs)
            self._export_jobs.pop(rid, None)
            # pop-tolerant engine-thread read (absent entry = resolved)
            # distlint: ignore[DL015]
            if exp is None or rid not in self._inflight:
                # finished/aborted/preempted in place during the
                # overlap: no migration, nothing to fall back from
                logger.debug(
                    "%s: streamed export of %s cancelled "
                    "(sequence resolved in place)", self.engine_id, rid,
                )
                self._disagg.cancel_stream(job, record=False)
                return
            exp.source_engine = self.engine_id
            self._inflight.pop(rid, None)
            self._disagg.commit_stream(job, exp)
        except Exception as e:  # noqa: BLE001 — per-request isolation
            self._drop_export_job(rid, job, record=False)
            self._engine.abort(rid)
            self._inflight.pop(rid, None)
            if self.recorder is not None:
                self.recorder.finish(rid, "error", code="handoff_failed")
            try:
                req.sink.on_error(f"KV export failed: {e}",
                                  "handoff_failed")
            except Exception as sink_exc:  # noqa: BLE001
                self._absorbed("sink_error", sink_exc)

    def _cancel_export(self, rid, session, job, record: bool) -> None:
        """Abandon a streamed export BEFORE the switchover: the sequence
        keeps decoding in place (that is the whole fallback), the
        target's reserved pages are released via the controller."""
        self._engine.export_handoff_cancel(session)
        self._drop_export_job(rid, job, record=record)

    def _drop_export_job(self, rid, job, record: bool) -> None:
        self._export_jobs.pop(rid, None)
        if job is not None and self._disagg is not None:
            self._disagg.cancel_stream(job, record=record)

    def evict_cache(self, target_frac: float,
                    drop_host_tier: bool = False) -> None:
        """Evict cached (refcount-0) prefix pages until used/total <=
        target_frac (degradation ladder, design.md:937 [spec]). Evicted
        pages DEMOTE to the host tier when one is configured;
        ``drop_host_tier`` (the ladder's most severe rung) skips the
        demotion and clears the host tier too."""

        def _do() -> None:
            self._engine.evict_cache(target_frac,
                                     drop_host_tier=drop_host_tier)
            self._refresh_digest(force=True)

        self._post(_do)

    def submit_embed(
        self,
        ids_list: List[List[int]],
        on_result: Callable[[Optional[np.ndarray], Optional[str]], None],
    ) -> None:
        """Queue an embeddings computation; ``on_result(array, error)`` is
        called exactly once — on the runner thread, or here/at crash time if
        the engine is (or becomes) unavailable.

        The computation runs as an incremental job: the runner loop
        processes ONE device batch per iteration between decode steps
        (engine.embed_step), so a large embeddings request never stalls
        the in-flight generations on this replica."""
        # register BEFORE the health check (same crash-safe ordering as
        # submit): a crash between check and registration would otherwise
        # strand the callback un-called forever
        self._embed_seq += 1
        token = self._embed_seq
        self._pending_embeds[token] = on_result
        if not self._healthy:
            cb = self._pending_embeds.pop(token, None)
            if cb is not None:
                cb(None, self._last_error or "engine unavailable")
            return

        def _enqueue() -> None:
            # bind the CURRENT engine: a hot-swap mid-job must not mix
            # two models' hidden states in one accumulator
            engine = self._engine
            try:
                state = engine.embed_start(ids_list)
            except Exception as e:  # noqa: BLE001 — called-exactly-once
                cb = self._pending_embeds.pop(token, None)
                if cb is not None:
                    cb(None, str(e))
                return
            self._embed_jobs.append(
                {"token": token, "engine": engine, "state": state}
            )

        self._post(_enqueue)

    def _embed_quantum(self) -> bool:
        """Advance the oldest embeddings job by one device batch (runner
        loop calls this between decode steps). Returns True if it did
        work."""
        if not self._embed_jobs:
            return False
        job = self._embed_jobs[0]
        # pop-tolerant engine-thread read: a crash handler popping the
        # token is exactly the case the branch below retires
        # distlint: ignore[DL015]
        if job["token"] not in self._pending_embeds:
            self._embed_jobs.popleft()  # failed by a crash handler
            return True
        result = error = None
        try:
            if job["engine"].embed_step(job["state"]):
                result = job["engine"].embed_finish(job["state"])
        except Exception as e:  # noqa: BLE001 — isolation boundary
            error = str(e)
        if result is not None or error is not None:
            self._embed_jobs.popleft()
            cb = self._pending_embeds.pop(job["token"], None)
            if cb is not None:
                cb(result, error)
        return True

    def set_mixed_prefill_frac(self, frac: float) -> None:
        """Degradation-ladder hook: shrink the mixed step's prefill
        share under memory pressure (engine.set_mixed_prefill_frac on
        the engine thread; a no-op when the mixed step is off)."""

        def _do() -> None:
            self._engine.set_mixed_prefill_frac(frac)

        self._post(_do)

    def set_loop_cap_frac(self, frac: float) -> None:
        """Degradation-ladder hook: shrink the looped-block iteration
        cap under pressure so run-to-completion blocks hand control
        back to the host sooner (engine.set_loop_cap_frac on the
        engine thread; a no-op when loop_to_completion is off)."""

        def _do() -> None:
            self._engine.set_loop_cap_frac(frac)

        self._post(_do)

    def reset_speculation(self) -> None:
        """Clear every pattern's acceptance tracker (Req 12.5 explicit
        reset — e.g. the operator knows the request pattern changed);
        re-enables speculation immediately with fresh measurement
        windows."""

        def _do() -> None:
            if self._engine.spec_trackers is not None:
                self._engine.spec_trackers.reset()

        self._post(_do)

    def profile_steps(self, n: int, timeout_s: float = 30.0) -> dict:
        """Capture a device trace over the next ``n`` engine steps
        (utils/profiler.py; SURVEY §5 device-tracing bar). Blocks up to
        ``timeout_s`` for the capture to finish — an idle engine only
        captures once work arrives. Returns the trace summary dict, or a
        dict with an ``error`` key."""
        if not self._healthy:
            return {"error": self._last_error or "engine unavailable"}
        box: dict = {}
        armed = threading.Event()

        def _do() -> None:
            box["ev"], box["holder"] = self._engine.profile_steps(n)
            armed.set()

        self._post(_do)
        if not armed.wait(timeout_s):
            return {"error": "engine thread did not arm the capture in time"}
        if not box["ev"].wait(timeout_s):
            self._post(lambda: self._engine.cancel_profile(box["holder"]))
            return {
                "error": f"capture did not complete within {timeout_s}s "
                "(engine idle? send traffic while profiling)"
            }
        return dict(box["holder"])

    def _post(self, fn: Callable[[], None]) -> None:
        with self._inbox_lock:
            self._inbox.append(fn)
        self._wake.set()

    def _absorbed(self, site: str, exc: BaseException) -> None:
        """An isolation boundary deliberately ate ``exc``; make that
        observable — debug log + ``errors_total{site=...}`` — instead of
        silent (distlint DL004). Must never raise itself."""
        logger.debug("%s: absorbed error at %s: %s: %s", self.engine_id,
                     site, type(exc).__name__, exc)
        if self.metrics:
            self.metrics.record_error(f"runner.{site}")

    # -- model hot-swap (Req 13, requirements.md:178-182) ------------------

    def swap_model(
        self,
        factory: Callable[[], LLMEngine],
        on_done: Optional[Callable[[bool, Optional[str]], None]] = None,
        cancelled: Optional[threading.Event] = None,
    ) -> None:
        """Hot-swap the model: build the new engine on a background thread
        (serving continues on the old model, Req 13.1-13.2), then switch
        atomically at an inbox-drain point — new requests hit the new
        engine, in-flight ones finish on the old (Req 13.3). On load
        failure the old model stays (Req 13.4). The new engine starts with
        an empty KV cache and fresh cache stats (Req 13.5).

        ``cancelled`` (checked right before the switch, on the runner
        thread) lets an orchestrator abandon a swap that exceeded its
        deadline without a late install sneaking in afterwards."""

        def _build() -> None:
            try:
                eng = factory()
                if eng.ecfg.warmup_compile:
                    # the new model must not serve cold after the switch
                    eng.warmup()
            except Exception as e:  # noqa: BLE001 — keep old model
                self._last_error = f"model swap failed: {e}"
                if on_done:
                    on_done(False, str(e))
                return

            def _install() -> None:
                if cancelled is not None and cancelled.is_set():
                    if on_done:
                        on_done(False, "swap cancelled")
                    return
                old = self._engine
                self._engine = eng
                # restarts must come back on the swapped model
                self._factory = factory
                if old is not None and old.has_work():
                    self._draining.append(old)
                # fresh stats baseline for the new model (Req 13.5)
                self._cache_seen = {"hits": 0, "misses": 0, "evictions": 0}
                self._mixed_seen = {"prefill_tokens": 0,
                                    "decode_tokens": 0}
                self._loop_seen = {"steps": 0, "exits": {}}
                self._payload_seen = {}
                self._sc_seen = {"kinds": {}, "events": {}}
                if on_done:
                    on_done(True, None)

            self._post(_install)

        threading.Thread(
            target=_build, name=f"swap-{self.engine_id}", daemon=True
        ).start()

    # -- introspection (any thread) ---------------------------------------

    def tokenizer(self):
        """Tokenizer of the currently-installed engine (None until ready).
        A plain reference read — safe from other threads; the server uses
        it to retarget the handler's tokenizer after a model swap."""
        eng = self._engine
        return eng.tok if eng is not None else None

    def is_healthy(self) -> bool:
        return self._healthy

    def audit(self, timeout_s: float = 30.0) -> List[str]:
        """KV-page conservation audit (docs/RESILIENCE.md): run
        ``LLMEngine.audit_pages`` on the engine thread (allocator state
        is single-owner), counting open import sessions' reserved pages
        as live holders. Returns inconsistency strings — empty = clean.
        Unhealthy engines audit vacuously clean (their pool died with
        them and is rebuilt on restart)."""
        if not self._healthy:
            return []
        box: Dict[str, List[str]] = {}
        done = threading.Event()

        def _do() -> None:
            extra = [p for (session, _eng) in self._import_sessions.values()
                     for p in session.pages]
            box["issues"] = self._engine.audit_pages(extra)
            done.set()

        self._post(_do)
        if not done.wait(timeout_s):
            return [f"{self.engine_id}: audit timed out after {timeout_s}s "
                    "(engine thread wedged?)"]
        return box["issues"]

    def last_error(self) -> Optional[str]:
        return self._last_error

    def active_count(self) -> int:
        return len(self._inflight)

    def status(self) -> EngineStatus:
        eng = self._engine
        used = total = cached = page_size = digest_depth = 0
        waiting = 0
        speculation = host_tier = mixed = loop = latent = None
        if eng is not None:
            try:
                s = eng.cache_stats()
                # RAW occupancy (pages off the free list) with the cached
                # share broken out: cached (refcount-0 prefix) pages are
                # effectively free capacity — allocate() reclaims them
                # LRU on demand — so consumers score live pressure as
                # used - cached (scheduler memory_aware, degradation
                # ladder); counting cache as live pressure would drive
                # the ladder to EMERGENCY on a pool merely FULL OF CACHE.
                total = s.pages_total
                cached = s.pages_cached
                used = total - s.pages_free
                page_size = eng.pcfg.page_size
                digest_depth = eng.ecfg.digest_depth
                waiting = eng.num_waiting()
                host_tier = eng.host_tier_stats()
                latent = eng.latent_stats()
                mixed = eng.mixed_stats()
                loop = eng.loop_stats()
                speculation = eng.spec_stats()
                if speculation is not None and self.metrics:
                    self.metrics.set_speculation(self.engine_id, speculation)
            except Exception as e:  # noqa: BLE001 — status must never raise
                self._absorbed("status", e)
        return EngineStatus(
            engine_id=self.engine_id,
            role=self.role,
            healthy=self._healthy,
            active_requests=len(self._inflight),
            waiting_requests=waiting,
            total_processed=self._total_processed,
            memory_used_pages=used,
            memory_total_pages=total,
            pages_cached=cached,
            speculation=speculation,
            prefix_digest=self._prefix_digest,
            page_size=page_size,
            digest_depth=digest_depth,
            host_tier=host_tier,
            latent=latent,
            mixed=mixed,
            loop=loop,
        )

    # -- runner thread ----------------------------------------------------

    def _run(self, ready: threading.Event) -> None:
        try:
            self._engine = self._factory()
            if self._engine.ecfg.warmup_compile:
                # compile all serving programs before reporting ready
                # (first-request TTFT must not pay XLA compile)
                self._engine.warmup()
            self._healthy = True
        except Exception as e:  # noqa: BLE001 — startup failure isolation
            self._last_error = str(e)
            self._healthy = False
            ready.set()
            return
        finally:
            if self.metrics:
                self.metrics.set_engine_up(self.engine_id, self._healthy)
        ready.set()

        try:
            self._refresh_digest(force=True)
            while not self._stop.is_set():
                self._drain_inbox()
                worked = False
                if self._engine.has_work():
                    worked = True
                    t0 = time.monotonic()
                    outputs = self._engine.step()
                    # crash mid-step (docs/RESILIENCE.md): outputs were
                    # computed but none reached a sink — the nastiest
                    # window for the exactly-once termination contract
                    faults.fire("runner.step")
                    dt = time.monotonic() - t0
                    if self.metrics:
                        self.metrics.record_inference(dt)
                    self._dispatch(outputs)
                    self._report_cache_deltas()
                    # force on the busy→idle transition: a request's
                    # FINAL step is what publishes its prefix chain
                    # (_release_seq), and with no further steps the
                    # rate-limited refresh would never snapshot it —
                    # the fleet registry (cache_aware routing + peer
                    # fetch) would stay blind to a drained replica's
                    # freshly warmed cache
                    self._refresh_digest(force=not self._engine.has_work())
                if self._drain_handoffs():
                    # a handoff export moves payload bytes without a
                    # step — flush the per-kind byte counters now, or
                    # an otherwise-idle prefill replica's export never
                    # reaches kv_payload_bytes_total
                    self._report_cache_deltas()
                    worked = True
                worked |= self._step_draining()
                worked |= self._embed_quantum()
                if not worked:
                    self._wake.wait(0.005)
                    self._wake.clear()
        except Exception as e:  # noqa: BLE001 — engine-level crash
            self._last_error = str(e)
            self._healthy = False
            if self.metrics:
                self.metrics.set_engine_up(self.engine_id, False)
            self._fail_all(str(e))

    def _step_draining(self) -> bool:
        """Step old engines still finishing in-flight work after a swap.
        A crash in a draining engine fails only its own requests — the new
        engine keeps serving."""
        worked = False
        for eng in list(self._draining):
            if not eng.has_work():
                self._draining.remove(eng)
                continue
            worked = True
            try:
                self._dispatch(eng.step())
            except Exception as e:  # noqa: BLE001 — old-model isolation
                ids = list(getattr(eng, "_by_id", {}).keys())
                self._fail_all_of(
                    [r for r in self._inflight.values()
                     if r.request_id in ids],
                    f"old model failed during drain: {e}",
                )
                self._draining.remove(eng)
        return worked

    def _drain_inbox(self) -> None:
        while True:
            with self._inbox_lock:
                if not self._inbox:
                    return
                fn = self._inbox.popleft()
            # crash between submit and drain (docs/RESILIENCE.md):
            # requests sit in _inflight but the engine never saw them —
            # zero tokens streamed, so they are redispatchable. Fired
            # OUTSIDE the per-command try: an injected fault here kills
            # the runner, it is not a per-request failure.
            faults.fire("runner.inbox")
            try:
                fn()
            except Exception as e:  # noqa: BLE001 — command isolation
                self._last_error = str(e)

    def _dispatch(self, outputs: List[StepOutput]) -> None:
        tokens = 0
        for out in outputs:
            # pop-tolerant engine-thread read: only crash sweeps pop
            # concurrently, and the None arm below handles that winner
            # distlint: ignore[DL015]
            req = self._inflight.get(out.request_id)
            if req is None:
                continue
            # a terminal event (done OR error) already reached the sink:
            # the stream is resolved, so the except arm must not send a
            # second terminal event and must still count the request
            terminal_delivered = False
            try:
                if out.error is not None:
                    if self.recorder is not None:
                        self.recorder.finish(out.request_id, "error",
                                             code="inference_failed")
                    req.sink.on_error(out.error, "inference_failed")
                    terminal_delivered = True
                elif out.token_id is not None or out.text:
                    if req.first_token_at is None:
                        req.first_token_at = time.monotonic()
                        if self.metrics:
                            self.metrics.record_ttft(
                                req.first_token_at - req.submitted_at
                            )
                        if req.engine_span is not None:
                            req.engine_span.event("first_token")
                    if out.token_id is not None:
                        tokens += 1
                        if self.recorder is not None:
                            self.recorder.token(out.request_id)
                    if not out.finished:
                        req.sink.on_token(out.token_id, out.text,
                                          out.token_index, out.logprob)
                if out.finished:
                    if out.error is None:
                        # flush any final delta carried on the done event
                        if out.text:
                            req.sink.on_token(None, out.text, out.token_index)
                        req.sink.on_done(
                            out.finish_reason or FinishReason.STOP,
                            out.usage or Usage(),
                        )
                        terminal_delivered = True
                        if self.recorder is not None:
                            self.recorder.finish(out.request_id, "ok")
                    if self.tracer and req.engine_span is not None:
                        if out.usage is not None:
                            req.engine_span.set(
                                completion_tokens=out.usage.completion_tokens
                            )
                        self.tracer.finish(
                            req.engine_span,
                            status="ok" if out.error is None else "error",
                        )
                    self._inflight.pop(out.request_id, None)
                    self._total_processed += 1
            except Exception as e:  # noqa: BLE001 — sink isolation
                self._last_error = f"sink error: {e}"
                # best-effort: resolve the waiter before dropping, or the
                # client's future waits forever on a request the runner
                # no longer tracks (on_error is a different method — it
                # may well work even when on_token just raised). But if
                # a terminal event already succeeded (e.g. tracer.finish
                # raised after on_done/on_error), the request IS resolved
                # — a second terminal event would contradict the stream
                # contract.
                if not terminal_delivered:
                    if self.recorder is not None:
                        self.recorder.finish(out.request_id, "error",
                                             code="server_error")
                    try:
                        req.sink.on_error(f"sink failure: {e}",
                                          "server_error")
                    except Exception as err_exc:  # noqa: BLE001
                        self._absorbed("sink_error", err_exc)
                elif out.finished:
                    # the request DID resolve — only post-terminal
                    # bookkeeping raised; keep the count honest
                    self._total_processed += 1
                self._inflight.pop(out.request_id, None)
        if self.metrics and tokens:
            self.metrics.record_tokens(tokens)

    def _refresh_digest(self, force: bool = False,
                        min_interval_s: float = 0.25) -> None:
        """Snapshot the engine's prefix digest for cache-aware routing
        (engine thread only; rate-limited — the digest is advisory)."""
        now = time.monotonic()
        if not force and now - self._digest_ts < min_interval_s:
            return
        try:
            self._prefix_digest = self._engine.prefix_digest()
            self._digest_ts = now
        except Exception as e:  # noqa: BLE001 — digest is best-effort
            self._absorbed("prefix_digest", e)

    def _report_cache_deltas(self) -> None:
        if not self.metrics or self._engine is None:
            return
        try:
            s = self._engine.cache_stats()
            host = self._engine.host_tier_stats()
            payload = self._engine.payload_byte_counters()
            reloads = self._engine.drain_reload_durations()
            mixed = self._engine.mixed_stats()
            loop = self._engine.loop_stats()
            step_clock = self._engine.step_clock_stats()
            step_samples = self._engine.drain_step_samples()
        except Exception as e:  # noqa: BLE001
            self._absorbed("cache_stats", e)
            return
        self._report_step_clock(step_clock, step_samples)
        if mixed is not None:
            seen_m = self._mixed_seen
            dp = max(0, mixed["prefill_tokens"] - seen_m["prefill_tokens"])
            dd = max(0, mixed["decode_tokens"] - seen_m["decode_tokens"])
            if dp or dd:
                self.metrics.record_mixed_step(prefill_tokens=dp,
                                               decode_tokens=dd)
            self.metrics.set_mixed_density(self.engine_id,
                                           mixed["batch_density"])
            self._mixed_seen = {
                "prefill_tokens": mixed["prefill_tokens"],
                "decode_tokens": mixed["decode_tokens"],
            }
        if loop is not None:
            seen_l = self._loop_seen
            d_steps = max(0, loop["steps"] - seen_l["steps"])
            d_exits = {
                reason: max(0, n - seen_l["exits"].get(reason, 0))
                for reason, n in loop["exits"].items()
            }
            if d_steps or any(d_exits.values()):
                self.metrics.record_loop_block(steps=d_steps,
                                               exits=d_exits)
            self._loop_seen = {"steps": loop["steps"],
                               "exits": dict(loop["exits"])}
        # payload bytes by encoding kind (kv_payload_bytes_total): the
        # engine reports totals, the collector wants deltas
        payload_deltas = {
            kind: max(0, n - self._payload_seen.get(kind, 0))
            for kind, n in payload.items()
        }
        if any(payload_deltas.values()):
            self.metrics.record_kv_payload(payload_deltas)
        self._payload_seen = dict(payload)
        seen = self._cache_seen
        hits = max(0, s.hits - seen["hits"])
        self.metrics.record_cache(
            hits=hits,
            misses=max(0, s.misses - seen["misses"]),
            evictions=max(0, s.evictions - seen["evictions"]),
        )
        host_hit_pages = 0
        if host is not None:
            host_hit_pages = max(
                0, host["hit_pages"] - seen.get("host_hit_pages", 0)
            )
            self.metrics.set_host_tier(self.engine_id, host["bytes"],
                                       host["pages"])
        if hits or host_hit_pages:
            self.metrics.record_prefix_hits(hbm=hits, host=host_hit_pages)
        for dur in reloads:
            self.metrics.record_prefix_reload(dur)
        self._cache_seen = {
            "hits": s.hits, "misses": s.misses, "evictions": s.evictions,
            "host_hit_pages": host["hit_pages"] if host is not None else 0,
        }

    def _report_step_clock(self, step_clock: Dict, samples) -> None:
        """Delta-report the engine step clock into the collector
        (docs/OBSERVABILITY.md "Performance telemetry"): cumulative
        kind/event counters diffed against the last report, per-segment
        wall-time samples fed to the step_ms.<kind> windowed digests."""
        seen_kinds = self._sc_seen.get("kinds", {})
        for kind, cur in step_clock["kinds"].items():
            prev = seen_kinds.get(kind, {})
            d_disp = int(cur["dispatches"] - prev.get("dispatches", 0))
            d_wall = cur["wall_s"] - prev.get("wall_s", 0.0)
            d_tok = int(cur["tokens"] - prev.get("tokens", 0))
            d_rows = int(cur["rows"] - prev.get("rows", 0))
            if d_disp > 0 or d_wall > 0 or d_tok > 0:
                self.metrics.record_step_clock(
                    self.engine_id, kind, dispatches=max(0, d_disp),
                    wall_s=max(0.0, d_wall), tokens=max(0, d_tok),
                    rows=max(0, d_rows),
                )
        seen_events = self._sc_seen.get("events", {})
        deltas = {
            event: int(total - seen_events.get(event, 0))
            for event, total in step_clock["events"].items()
        }
        if any(n > 0 for n in deltas.values()):
            self.metrics.record_step_events(self.engine_id, deltas)
        self._sc_seen = step_clock
        for kind, wall_s in samples:
            self.metrics.observe_step(kind, wall_s)

    def _fail_all(self, message: str) -> None:
        # streamed exports die with the engine: cancel their stream jobs
        # so any target-side reserved pages are released (the requests
        # themselves are sink-failed below with the rest of _inflight)
        for rid, entry in list(self._export_jobs.items()):
            self._drop_export_job(rid, entry[2], record=False)
        self._export_jobs.clear()
        # resolve un-run resume imports FIRST, dropping them from
        # _inflight so they are not also sink-failed below: on_done(False)
        # hands the request back to the DisaggController, whose in-place
        # fallback owns its fate (a sink error here would be a second,
        # contradictory terminal event)
        for rid in list(self._pending_resumes):
            cb = self._pending_resumes.pop(rid, None)
            if cb is None:
                continue
            self._inflight.pop(rid, None)
            try:
                cb(False, message)
            except Exception as e:  # noqa: BLE001 — callback isolation
                self._absorbed("resume_callback", e)
        # phased-import state dies with the engine: resolve un-run open
        # callbacks (the controller's stream job falls back to in-place
        # decode on the source) and drop reserved pages — the pool is
        # gone with the engine anyway, but the allocator bookkeeping
        # must not leak across a restart()
        for token in list(self._pending_opens):
            cb = self._pending_opens.pop(token, None)
            if cb is not None:
                try:
                    cb(False, message)
                except Exception as e:  # noqa: BLE001 — callback isolation
                    self._absorbed("open_callback", e)
        # peer-fetch exports die with the engine: the fetcher falls back
        # to recompute on its target (the request never lived here)
        for token in list(self._pending_fetches):
            cb = self._pending_fetches.pop(token, None)
            if cb is not None:
                try:
                    cb(None, message)
                except Exception as e:  # noqa: BLE001 — callback isolation
                    self._absorbed("fetch_callback", e)
        for rid in list(self._import_sessions):
            self._drop_import_session(rid)
        self._fail_all_of(list(self._inflight.values()), message)
        self._inflight.clear()
        for token in list(self._pending_embeds):
            cb = self._pending_embeds.pop(token, None)
            if cb is not None:
                try:
                    cb(None, message)
                except Exception as e:  # noqa: BLE001
                    self._absorbed("embed_callback", e)

    def _fail_all_of(self, reqs: Sequence[ServerRequest], message: str) -> None:
        """Resolve dead in-flight requests, exactly once each, by
        construction: every request is popped from ``_inflight`` FIRST
        (this runner can never resolve it twice), then takes exactly one
        of two terminal paths —

        - **redispatch** (zero streamed tokens only): the dispatcher
          takes ownership and the request terminates on another replica
          — or fails there, once, if the fleet is really out of capacity;
        - **sink failure**: ``worker_failure`` for zero-token requests
          the dispatcher declined (shutdown / attempts exhausted / no
          healthy replica), ``engine_crashed`` — a distinct, client-
          distinguishable code — for requests that already streamed
          tokens, which can never be transparently re-run (a re-run
          could emit a diverging continuation mid-stream)."""
        for req in reqs:
            if self._inflight.pop(req.request_id, None) is None:
                # another failure path already owns this request (e.g.
                # submit() raced the engine thread's crash and both
                # reached here) — resolving it again would double-
                # terminate or double-redispatch
                continue
            if self.tracer and req.engine_span is not None:
                self.tracer.finish(req.engine_span, status="error")
                # the request has exactly one owner (popped above)
                req.engine_span = None  # distlint: ignore[DL008]
            if req.first_token_at is None and self.redispatch is not None:
                try:
                    if self.redispatch(req, self.engine_id, message):
                        continue  # the new owner resolves the sink
                except Exception as e:  # noqa: BLE001 — hook isolation
                    self._absorbed("redispatch", e)
            code = ("worker_failure" if req.first_token_at is None
                    else "engine_crashed")
            if self.recorder is not None:
                self.recorder.finish(req.request_id, "error", code=code)
            try:
                req.sink.on_error(message, code)
            except Exception as e:  # noqa: BLE001
                self._absorbed("sink_error", e)
