"""Multi-host fleet control plane: federated engine registry, the fleet
wire, and dynamic role rebalancing (docs/FLEET.md).

Everything below the serving spine so far scaled within one process:
``server.engine_roles`` builds local runners and the dispatcher routes
against one in-process fleet snapshot. This subsystem federates it:

- **FleetRegistry** — membership truth for the whole fleet. Worker
  processes join by dialing the registry host and heartbeating
  (``FleetHeartbeat`` = member id + its full ``EngineStatus`` replica
  set, digests included, over the protowire codec); the registry ages
  members out on missed beats through an ``alive -> suspect -> dead``
  state machine and feeds every consumer — scheduler routing, metrics,
  ``/server/stats`` — the merged local+remote snapshot.
- **the fleet wire** — one duplex TCP connection per member carrying
  length-delimited protowire frames (u32 payload length, u8 kind, the
  encoded message): ``FleetHeartbeat`` and ``FleetEvent`` flow worker →
  registry host, ``FleetSubmit`` flows back. ``FleetServer`` owns the
  listener and one reader thread per member session; each heartbeat
  registers/refreshes a ``RemoteRunner`` proxy per remote engine
  (serving/remote_runner.py) in the scheduler, so the entire existing
  dispatch spine — strategies, cache_aware cost model, redispatch —
  routes remote replicas with zero special cases.
- **RoleBalancer** — dynamic role rebalancing: when the fleet's prompt
  queue deepens past ``fleet.rerole_high_ratio`` (queued + waiting
  prompts per admission-capable replica), one ``unified`` engine
  re-roles to ``prefill`` (the disagg machinery makes the flip a single
  attribute write — the next admission batch simply parks its prefills
  for migration); it flips back once the signal drops below
  ``fleet.rerole_low_ratio``. Two-sided hysteresis (signal band + a
  flip cooldown) keeps an oscillating queue from flapping roles — the
  ``rerole_flap`` chaos scenario pins that. The balancer only restores
  engines IT flipped, so an operator's static topology is never
  rewritten.

Failure semantics (docs/RESILIENCE.md): a dead member's ``RemoteRunner``
proxies map remote death onto the existing crash-safe redispatch path —
zero-token in-flight requests re-dispatch exactly once onto healthy
replicas, mid-stream requests fail fast as ``engine_crashed``. Fault
points: ``fleet.heartbeat`` (registry ingest drops the beat — the
partition model), ``fleet.submit`` (the forwarded submit dies on the
wire / the worker crashes on receipt), ``sched.rerole`` (flag: forces
the rebalance signal high for one evaluation — the chaos lever that
drives reroles deterministically).
"""

from __future__ import annotations

import json
import logging
import socket
import struct
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

from distributed_inference_server_tpu.core.errors import ConfigError
from distributed_inference_server_tpu.serving import faults, protowire
from distributed_inference_server_tpu.serving.metrics import (
    EngineStatus,
    MetricsCollector,
)
from distributed_inference_server_tpu.utils.tracing import Span

logger = logging.getLogger(__name__)

MEMBER_ALIVE = "alive"
MEMBER_SUSPECT = "suspect"
MEMBER_DEAD = "dead"
MEMBER_STATES = (MEMBER_ALIVE, MEMBER_SUSPECT, MEMBER_DEAD)


@dataclass(frozen=True)
class FleetSettings:
    """Knobs of the fleet control plane (config section ``fleet``)."""

    enabled: bool = False
    host: str = "127.0.0.1"
    port: int = 0  # registry listener; 0 = ephemeral (tests/smoke)
    connect: str = ""  # worker mode: "host:port" of the registry host
    member_id: str = ""  # worker identity; "" = derived host:pid
    heartbeat_interval_s: float = 0.5
    suspect_after_s: float = 2.0
    dead_after_s: float = 5.0
    rerole: bool = False
    rerole_high_ratio: float = 4.0
    rerole_low_ratio: float = 1.0
    rerole_cooldown_s: float = 10.0
    rerole_interval_s: float = 0.5
    # dead members are kept for observability, then pruned: every worker
    # restart mints a new host:pid identity, so without eviction the
    # member table (and fleet_members{state="dead"}) grows forever
    dead_retention_s: float = 300.0
    # fleet KV data plane (serving/fleet_kv.py; docs/FLEET.md "KV data
    # plane"): workers bind a KV data listener (kv_data_port; 0 =
    # ephemeral) and advertise it per heartbeat; the registry host
    # dials it lazily for cross-host handoff and peer prefix fetch,
    # with at most kv_max_streams bulk streams in flight per member.
    # kv_enabled=False keeps a worker control-plane-only.
    kv_enabled: bool = True
    kv_data_port: int = 0
    kv_max_streams: int = 4
    kv_connect_timeout_s: float = 5.0
    # KV mesh (serving/fleet_mesh.py; docs/FLEET.md "KV mesh"): the
    # registry brokers member endpoints over KvIntro frames and members
    # dial each other directly — bulk fetch bytes skip the registry.
    # Off by default: the relay topology is the compatible baseline.
    mesh_enabled: bool = False
    # learned wire-rate window and prior (serving/fleet_mesh.py): rates
    # older than the window are forgotten; kv_rate_prior (bytes/s) is
    # the rate kv_page_cost is assumed to price — a wire measured at
    # the prior costs exactly the constant. <= 0 disables learned
    # pricing (every wire charges the constant).
    kv_rate_window_s: float = 30.0
    kv_rate_prior: float = 125000000.0
    # Registry HA (serving/fleet_ha.py; docs/FLEET.md "Registry HA"):
    # ordered registry endpoint list shared by every process. Workers
    # dial ALL of them (dual-heartbeat); registries heartbeat each
    # other and elect a lease-fenced primary (list order breaks ties).
    # () = single-registry fleet, HA machinery entirely dormant.
    registries: Tuple[str, ...] = ()
    # lease aging on the PRIMARY itself: a standby marks the lease
    # suspect after lease_suspect_s without a beat and takes over after
    # lease_s (the same alive->suspect->dead machinery used on members)
    lease_s: float = 3.0
    lease_suspect_s: float = 1.5
    # multi-ingress: standbys serve HTTP against their own federated
    # view. False = a standby's dispatcher rejects ingress (QueueFull)
    # until it holds the lease — single-front-door deployments.
    standby_http: bool = True


# ---------------------------------------------------------------------------
# The fleet wire: length-delimited protowire frames over one TCP stream
# ---------------------------------------------------------------------------

FRAME_KINDS: Dict[int, str] = {
    1: "FleetHeartbeat",
    2: "FleetSubmit",
    3: "FleetEvent",
    # fleet-stitched tracing (docs/OBSERVABILITY.md): finished member
    # spans, batched at heartbeat cadence, worker -> registry host
    4: "FleetSpans",
    # fleet-federated performance telemetry (serving/teledigest.py):
    # member digests + step-clock counters, heartbeat-piggybacked
    5: "FleetTelemetry",
    # KV mesh introduction (serving/fleet_mesh.py): registry host ->
    # worker, brokering member-to-member data-plane endpoints
    6: "KvIntro",
    # registry HA (serving/fleet_ha.py): primary -> standby lease beat
    # and standby -> primary state echo, registry <-> registry
    7: "RegistryLease",
    8: "RegistryState",
}
_KIND_BY_NAME = {name: kind for kind, name in FRAME_KINDS.items()}

#: a fleet frame is control-plane small (statuses, token events, prompt
#: ids) — anything bigger is a torn/foreign stream, not a real frame
MAX_FRAME_BYTES = 16 * 1024 * 1024


class FleetWireError(RuntimeError):
    """A malformed frame on the fleet wire (foreign protocol, torn
    stream, oversized payload). The session treats it as member death."""


def send_frame(sock: socket.socket, name: str, obj: Dict[str, Any]) -> None:
    """Encode ``obj`` as message ``name`` and write one frame. Callers
    serialize sends per socket themselves (one lock per session)."""
    payload = protowire.encode(name, obj)
    sock.sendall(struct.pack(">IB", len(payload), _KIND_BY_NAME[name])
                 + payload)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None  # orderly EOF
        buf += chunk
    return bytes(buf)


def recv_frame(sock: socket.socket) -> Optional[Tuple[str, Dict[str, Any]]]:
    """Read one frame; returns ``(message_name, decoded_dict)`` or None
    on EOF. Raises FleetWireError on a malformed frame."""
    header = _recv_exact(sock, 5)
    if header is None:
        return None
    length, kind = struct.unpack(">IB", header)
    name = FRAME_KINDS.get(kind)
    if name is None or length > MAX_FRAME_BYTES:
        raise FleetWireError(f"bad fleet frame (kind={kind}, len={length})")
    payload = _recv_exact(sock, length)
    if payload is None:
        return None
    try:
        return name, protowire.decode(name, payload)
    except Exception as e:  # noqa: BLE001 — wire fault domain
        raise FleetWireError(f"undecodable {name} frame: {e}") from e


def status_to_wire(s: EngineStatus) -> Dict[str, Any]:
    """EngineStatus -> FleetHeartbeat wire dict (the digest travels so
    the registry host can score remote prefix matches)."""
    host = s.host_tier or {}
    return {
        "engine_id": s.engine_id,
        "healthy": s.healthy,
        "active_requests": s.active_requests,
        "waiting_requests": s.waiting_requests,
        "total_processed": s.total_processed,
        "memory_used_pages": s.memory_used_pages,
        "memory_total_pages": s.memory_total_pages,
        "role": s.role or "unified",
        "pages_cached": s.pages_cached,
        "prefix_digest": sorted(int(h) for h in (s.prefix_digest or ())),
        "page_size": s.page_size,
        "digest_depth": s.digest_depth,
        "host_tier_bytes": host.get("bytes", 0),
        "host_tier_pages": host.get("pages", 0),
    }


def status_from_wire(d: Dict[str, Any], member_id: str) -> EngineStatus:
    """Wire dict -> EngineStatus namespaced under ``member_id`` (the
    proxy id the scheduler routes on: ``<member>:<engine>``)."""
    host = None
    if d.get("host_tier_bytes") or d.get("host_tier_pages"):
        host = {"bytes": d.get("host_tier_bytes", 0),
                "pages": d.get("host_tier_pages", 0), "hit_pages": 0}
    return EngineStatus(
        engine_id=f"{member_id}:{d.get('engine_id', '')}",
        healthy=bool(d.get("healthy")),
        active_requests=d.get("active_requests", 0),
        waiting_requests=d.get("waiting_requests", 0),
        total_processed=d.get("total_processed", 0),
        memory_used_pages=d.get("memory_used_pages", 0),
        memory_total_pages=d.get("memory_total_pages", 0),
        pages_cached=d.get("pages_cached", 0),
        role=d.get("role") or "unified",
        prefix_digest=frozenset(d.get("prefix_digest") or ()),
        page_size=d.get("page_size", 0),
        digest_depth=d.get("digest_depth", 0),
        host_tier=host,
        remote=True,
    )


def _attrs_to_json(attrs: Dict[str, Any]) -> str:
    if not attrs:
        return ""
    try:
        return json.dumps(attrs, default=str)
    except (TypeError, ValueError):
        return json.dumps({k: str(v) for k, v in attrs.items()})


def _attrs_from_json(blob: str) -> Dict[str, Any]:
    if not blob:
        return {}
    try:
        obj = json.loads(blob)
        return obj if isinstance(obj, dict) else {}
    except ValueError:
        return {}


def span_to_wire(s: Span, epoch_offset_ns: int) -> Dict[str, Any]:
    """Span -> TraceSpan wire dict. Timestamps go out as EPOCH ns
    (``epoch_offset_ns`` = time_ns() - monotonic_ns() of the SENDER), so
    the receiver can re-base into its own monotonic domain — the only
    residual error is wall-clock skew between hosts, same as OTLP."""
    start = s.start_ns + epoch_offset_ns
    return {
        "name": s.name,
        "trace_id": s.trace_id,
        "span_id": s.span_id,
        "parent_id": s.parent_id or "",
        "start_unix_ns": max(0, start),
        "duration_ns": max(0, (s.end_ns or s.start_ns) - s.start_ns),
        "status": s.status or "ok",
        "attrs_json": _attrs_to_json(s.attributes),
        "events": [
            {"offset_ns": max(0, t - s.start_ns), "name": n,
             "attrs_json": _attrs_to_json(a)}
            for t, n, a in s.events
        ],
    }


def span_from_wire(d: Dict[str, Any], epoch_offset_ns: int,
                   member_id: str = "") -> Span:
    """TraceSpan wire dict -> Span in the RECEIVER's monotonic domain.
    ``member_id`` is stamped as a ``member`` attribute so a stitched
    trace shows which process each span ran in."""
    start = max(0, d.get("start_unix_ns", 0) - epoch_offset_ns)
    duration = max(0, d.get("duration_ns", 0))
    attrs = _attrs_from_json(d.get("attrs_json", ""))
    if member_id:
        attrs.setdefault("member", member_id)
    return Span(
        name=d.get("name", ""),
        trace_id=d.get("trace_id", ""),
        span_id=d.get("span_id", ""),
        parent_id=d.get("parent_id") or None,
        start_ns=start,
        end_ns=start + duration,
        attributes=attrs,
        events=[
            (start + e.get("offset_ns", 0), e.get("name", ""),
             _attrs_from_json(e.get("attrs_json", "")))
            for e in d.get("events", [])
        ],
        status=d.get("status") or "ok",
    )


# ---------------------------------------------------------------------------
# Federated engine registry
# ---------------------------------------------------------------------------


@dataclass
class FleetMember:
    """One worker process as the registry sees it. Mutated only under
    the registry's lock; ``snapshot()`` hands out copies."""

    member_id: str
    state: str = MEMBER_ALIVE
    last_beat: float = field(default_factory=time.monotonic)
    beats: int = 0
    engines: List[EngineStatus] = field(default_factory=list)

    def snapshot(self, now: float) -> Dict[str, Any]:
        return {
            "member_id": self.member_id,
            "state": self.state,
            "last_beat_age_s": round(now - self.last_beat, 3),
            "beats": self.beats,
            "engines": {s.engine_id: s.role for s in self.engines},
        }


class FleetRegistry:
    """Membership truth: heartbeat ingest + the alive/suspect/dead state
    machine. Thread-safe — beats arrive on member-session reader
    threads, the sweeper ages members out, and routing snapshots read
    from the dispatcher thread. State-change callbacks run OUTSIDE the
    lock (they unregister runners / fail requests — lock-heavy work)."""

    def __init__(
        self,
        settings: Optional[FleetSettings] = None,
        metrics: Optional[MetricsCollector] = None,
        on_state_change: Optional[Callable[[str, str, str], None]] = None,
    ):
        self.settings = settings or FleetSettings()
        self.metrics = metrics
        self.on_state_change = on_state_change
        self._members: Dict[str, FleetMember] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- ingest (member-session reader threads) ----------------------------

    def observe(self, member_id: str,
                engines: List[EngineStatus]) -> Optional[str]:
        """Ingest one heartbeat. Returns the member's PREVIOUS state (so
        the caller can re-register runners on a rejoin), or None when the
        beat was dropped by the ``fleet.heartbeat`` fault point — the
        partition model: the wire delivered it, the registry never saw
        it."""
        try:
            faults.fire("fleet.heartbeat")
        except faults.InjectedFault:
            if self.metrics:
                self.metrics.record_fleet_heartbeat("dropped")
            return None
        transition = None
        created = False
        with self._lock:
            member = self._members.get(member_id)
            if member is None:
                member = self._members[member_id] = FleetMember(member_id)
                created = True
                # the session treats a first join like a rejoin (fresh
                # proxies, clean slate), but it is NOT a revival for
                # metrics/callbacks — nothing existed to revive
                prev = MEMBER_DEAD
            else:
                prev = member.state
            member.last_beat = time.monotonic()
            member.beats += 1
            member.engines = list(engines)
            member.state = MEMBER_ALIVE
            if not created and prev != MEMBER_ALIVE:
                transition = (member_id, prev, MEMBER_ALIVE)
        if self.metrics:
            self.metrics.record_fleet_heartbeat(
                "rejoin" if transition else "ok")
            self._publish_gauge()
        if transition and self.on_state_change:
            self.on_state_change(*transition)
        return prev

    def disconnect(self, member_id: str) -> None:
        """Connection death: faster truth than beat aging — the member
        is dead NOW (its in-flight requests must redispatch, not wait
        out the suspect window)."""
        self._transition(member_id, MEMBER_DEAD)

    # -- aging (sweeper thread) --------------------------------------------

    def sweep(self, now: Optional[float] = None) -> List[Tuple[str, str, str]]:
        """Age members on missed beats: alive -> suspect after
        ``suspect_after_s``, suspect -> dead after ``dead_after_s``.
        Returns the transitions applied."""
        now = time.monotonic() if now is None else now
        transitions: List[Tuple[str, str, str]] = []
        pruned = False
        with self._lock:
            for member in list(self._members.values()):
                age = now - member.last_beat
                if (member.state == MEMBER_ALIVE
                        and age > self.settings.suspect_after_s):
                    transitions.append(
                        (member.member_id, member.state, MEMBER_SUSPECT))
                    member.state = MEMBER_SUSPECT
                if (member.state == MEMBER_SUSPECT
                        and age > self.settings.dead_after_s):
                    transitions.append(
                        (member.member_id, member.state, MEMBER_DEAD))
                    member.state = MEMBER_DEAD
                if (member.state == MEMBER_DEAD
                        and age > (self.settings.dead_after_s
                                   + self.settings.dead_retention_s)):
                    # restarted workers mint fresh host:pid identities;
                    # without eviction the dead set grows forever
                    del self._members[member.member_id]
                    pruned = True
        if (transitions or pruned) and self.metrics:
            self._publish_gauge()
        if self.on_state_change:
            for t in transitions:
                self.on_state_change(*t)
        return transitions

    def _transition(self, member_id: str, new_state: str) -> None:
        with self._lock:
            member = self._members.get(member_id)
            if member is None or member.state == new_state:
                return
            prev = member.state
            member.state = new_state
        if self.metrics:
            self._publish_gauge()
        if self.on_state_change:
            self.on_state_change(member_id, prev, new_state)

    def _publish_gauge(self) -> None:
        with self._lock:
            counts = {state: 0 for state in MEMBER_STATES}
            for member in self._members.values():
                counts[member.state] += 1
        self.metrics.set_fleet_members(counts)

    # -- snapshots (any thread) --------------------------------------------

    def member_state(self, member_id: str) -> Optional[str]:
        with self._lock:
            member = self._members.get(member_id)
            return member.state if member else None

    def members(self) -> List[Dict[str, Any]]:
        now = time.monotonic()
        with self._lock:
            return [m.snapshot(now) for m in self._members.values()]

    def stats(self) -> Dict[str, Any]:
        """The ``fleet`` block of ``/server/stats``: members with state
        and last-beat age (the role map and rebalance history ride in
        from the server's balancer)."""
        members = self.members()
        counts = {state: 0 for state in MEMBER_STATES}
        for m in members:
            counts[m["state"]] += 1
        return {"members": members, "member_counts": counts}

    # -- sweeper lifecycle -------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        # lifecycle handle: start/stop are orchestrator calls
        # distlint: ignore[DL008]
        self._thread = threading.Thread(
            target=self._sweep_loop, name="fleet-registry-sweep", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None

    def _sweep_loop(self) -> None:
        # sweep at heartbeat cadence: aging resolution finer than the
        # suspect window costs nothing and keeps detection < 1 interval
        while not self._stop.wait(self.settings.heartbeat_interval_s):
            try:
                self.sweep()
            except Exception:  # noqa: BLE001 — sweeper must stay alive
                logger.exception("fleet registry sweep failed; retrying")


# ---------------------------------------------------------------------------
# Registry-host listener: member sessions feeding the registry
# ---------------------------------------------------------------------------


class _MemberSession:
    """One accepted member connection on the registry host. The reader
    thread owns the inbound half (heartbeats, events); sends are
    serialized by ``_send_lock`` (RemoteRunner submits arrive from the
    dispatcher and redispatch paths concurrently)."""

    def __init__(self, server: "FleetServer", sock: socket.socket,
                 peer: str):
        self.server = server
        self.sock = sock
        self.peer = peer
        self.member_id: Optional[str] = None
        # engine_id (member-local) -> RemoteRunner proxy; written on the
        # reader thread, read by close/detach paths — guarded by _lock
        self.runners: Dict[str, Any] = {}
        # fleet KV data plane (serving/fleet_kv.py): the member's
        # lazily-dialed data channel, created when a heartbeat
        # advertises a data_port; guarded by _lock
        self.kv_channel: Any = None
        self._lock = threading.Lock()
        self._send_lock = threading.Lock()
        self._closed = False

    def send(self, name: str, obj: Dict[str, Any]) -> None:
        with self._send_lock:
            if self._closed:
                raise FleetWireError("member session closed")
            send_frame(self.sock, name, obj)

    # host->member kinds never arrive here: this loop reads what MEMBERS
    # send (heartbeats, events, spans, telemetry); submits and KvIntro
    # travel the other direction, on FleetWorker's reader
    # distlint: wire-ignores[FleetSubmit, KvIntro]
    def run(self) -> None:
        """Reader loop (one thread per session)."""
        try:
            while True:
                frame = recv_frame(self.sock)
                if frame is None:
                    break
                name, obj = frame
                if name == "FleetHeartbeat":
                    self._on_heartbeat(obj)
                elif name == "FleetEvent":
                    self._on_event(obj)
                elif name == "FleetSpans":
                    # finished member spans: merge into the host tracer
                    # (even from a member the registry has aged out — a
                    # dying member's last spans are exactly the ones a
                    # postmortem needs)
                    self.server.ingest_spans(
                        obj, self.member_id or obj.get("member_id", ""))
                elif name == "FleetTelemetry":
                    # member perf digests + step-clock counters: stored
                    # per member, merged on demand at GET /server/perf
                    self.server.ingest_telemetry(
                        obj, self.member_id or obj.get("member_id", ""))
                elif name in ("RegistryLease", "RegistryState"):
                    # registry HA (serving/fleet_ha.py): a peer
                    # registry's lease beat / state echo arriving on
                    # our member listener — routed to the HA module;
                    # the session stays member-less (close is a no-op
                    # detach), so peer wires never fabricate members
                    self.server.on_registry_frame(name, obj)
                # FleetSubmit frames only flow host -> worker; one
                # arriving here is a confused peer — ignore it
        except (OSError, FleetWireError) as e:
            logger.debug("fleet session %s reader ended: %s", self.peer, e)
        finally:
            self.close("fleet member connection lost")

    def _on_heartbeat(self, obj: Dict[str, Any]) -> None:
        member_id = obj.get("member_id") or self.peer
        if self.member_id is None:
            self.member_id = member_id
            superseded = self.server._claim_member(member_id, self)
            if superseded is not None:
                # a reconnect replaced a half-dead session: fail the old
                # proxies' in-flight (their connection cannot deliver
                # events anymore) without killing the member
                superseded.detach_runners(
                    f"fleet member {member_id} reconnected on a new "
                    "session")
            logger.info("fleet member %s joined from %s", member_id,
                        self.peer)
        statuses = [status_from_wire(d, member_id)
                    for d in obj.get("engines", [])]
        prev = self.server.registry.observe(member_id, statuses)
        if prev is None:
            return  # beat dropped (fleet.heartbeat fault) — no refresh
        self.server._ensure_kv_channel(self, member_id,
                                       obj.get("data_port", 0))
        self.server._broker_intros(self, member_id,
                                   obj.get("data_port", 0))
        self.server._refresh_runners(self, member_id, obj.get("engines", []),
                                     statuses, rejoined=prev == MEMBER_DEAD)

    def _on_event(self, obj: Dict[str, Any]) -> None:
        with self._lock:
            runner = self.runners.get(obj.get("engine_id", ""))
        if runner is not None:
            runner.on_event(obj)

    def detach_runners(self, message: str) -> None:
        """Unregister this member's proxies from the scheduler and fail
        their in-flight requests onto the redispatch path. Two phases on
        purpose: EVERY proxy leaves the routing set (and is marked
        detached) before ANY request is failed — redispatching the first
        proxy's requests must not land them on a dead sibling proxy of
        the same member and burn the bounded redispatch budget there."""
        with self._lock:
            runners = list(self.runners.values())
            self.runners.clear()
            kv_channel, self.kv_channel = self.kv_channel, None
        if kv_channel is not None:
            # fails every in-flight KV stream (handoffs fall back to
            # decode-in-place, fetches to recompute) and the migrated
            # requests whose events rode it (engine_crashed)
            kv_channel.close(message)
        for runner in runners:
            # identity-checked: a reconnect's fresh proxy registered
            # under the same id must survive this session's late detach
            self.server.scheduler.unregister_if(runner.engine_id, runner)
            runner.mark_detached(message)
        for runner in runners:
            runner.fail_inflight(message)

    def close(self, reason: str) -> None:
        with self._send_lock:
            if self._closed:
                return
            self._closed = True
            try:
                self.sock.close()
            except OSError:
                pass
        member = self.member_id
        logger.info("fleet session %s (%s) closed: %s", self.peer,
                    member or "pre-join", reason)
        self.detach_runners(reason)
        if member is not None and self.server._is_current(member, self):
            # only the member's CURRENT session's death kills it — a
            # superseded session's late EOF is just cleanup
            self.server.registry.disconnect(member)
        self.server._drop_session(self)


class FleetServer:
    """The registry host's listener: accepts member connections, feeds
    heartbeats to the registry, and materializes one RemoteRunner proxy
    per remote engine in the scheduler so the whole dispatch spine
    routes the federated fleet with no special cases."""

    def __init__(
        self,
        registry: FleetRegistry,
        scheduler,
        settings: Optional[FleetSettings] = None,
        metrics: Optional[MetricsCollector] = None,
        redispatch: Optional[Callable] = None,
        tracer=None,
        recorder=None,
        health_settings=None,
        retry_budget=None,
    ):
        """``tracer``: the host Tracer — remote members' FleetSpans
        frames merge into it (one stitched cross-process trace per
        request, docs/OBSERVABILITY.md). ``recorder``: the host
        FlightRecorder — RemoteRunner proxies note token/terminal
        events into per-request timelines. ``health_settings``
        (serving/health.py HealthSettings) shapes each member data
        channel's circuit breaker; ``retry_budget`` (health.RetryBudget)
        budgets its reconnect attempts (docs/RESILIENCE.md "Gray
        failures and overload")."""
        from distributed_inference_server_tpu.serving.health import (
            HealthSettings,
        )

        self.registry = registry
        self.scheduler = scheduler
        self.settings = settings or FleetSettings()
        self.metrics = metrics
        self.redispatch = redispatch
        self.tracer = tracer
        self.recorder = recorder
        self.health_settings = health_settings or HealthSettings()
        self.retry_budget = retry_budget
        # monotonic <-> epoch re-basing for ingested remote spans
        self._epoch_offset_ns = time.time_ns() - time.monotonic_ns()
        self._sessions: List[_MemberSession] = []
        # member_id -> its CURRENT session: a reconnect replaces the
        # entry, so the superseded session's late EOF can neither kill
        # the member nor detach the new session's runners
        self._by_member: Dict[str, _MemberSession] = {}
        # member_id -> last ingested FleetTelemetry frame (digests +
        # counters, serving/teledigest.py), merged at GET /server/perf;
        # guarded by _lock, pruned by age at snapshot time
        self._telemetry: Dict[str, Dict[str, Any]] = {}
        # learned per-wire transfer rates (serving/fleet_mesh.py): the
        # host's own channels observe locally; member-to-member wires
        # arrive as cumulative kvwire counters on fleet telemetry.
        # Always on — cold wires price at the configured constant, so
        # nothing changes until bytes actually flow.
        from distributed_inference_server_tpu.serving.fleet_mesh import (
            MeshWireRates,
        )

        self.mesh_rates = MeshWireRates(
            window_s=self.settings.kv_rate_window_s,
            prior_rate=self.settings.kv_rate_prior,
            metrics=metrics,
        )
        # KV mesh broker state (guarded by _lock): member_id -> its
        # last-published (host, data_port) endpoint, and the cumulative
        # kvwire counter values last seen per (member, src, dst) so the
        # telemetry ingest can feed DELTAS into the rate window
        self._intro_endpoints: Dict[str, Tuple[str, int]] = {}
        self._kvwire_last: Dict[Tuple[str, str, str],
                                Tuple[float, float, float]] = {}
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._thread: Optional[threading.Thread] = None
        self._stopping = False
        self.bound_port: int = 0
        # registry HA (serving/fleet_ha.py): set by the server when
        # fleet.registries is configured. None = single-registry fleet
        # — every HA hook below degrades to the pre-HA behavior.
        self.ha = None
        registry.on_state_change = self._on_member_state

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        sock.bind((self.settings.host, self.settings.port))
        sock.listen(16)
        self._sock = sock
        self.bound_port = sock.getsockname()[1]
        self._stopping = False
        # lifecycle handle  # distlint: ignore[DL008]
        self._thread = threading.Thread(
            target=self._accept_loop, name="fleet-accept", daemon=True
        )
        self._thread.start()
        self.registry.start()
        logger.info("fleet registry listening on %s:%d", self.settings.host,
                    self.bound_port)

    def stop(self) -> None:
        self._stopping = True
        self.registry.stop()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        with self._lock:
            sessions = list(self._sessions)
        for session in sessions:
            session.close("fleet server shutting down")
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None

    def _accept_loop(self) -> None:
        while not self._stopping:
            try:
                conn, addr = self._sock.accept()
            except OSError:
                return  # listener closed
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            session = _MemberSession(self, conn, f"{addr[0]}:{addr[1]}")
            with self._lock:
                self._sessions.append(session)
            threading.Thread(
                target=session.run,
                name=f"fleet-session-{addr[0]}:{addr[1]}", daemon=True,
            ).start()

    def _claim_member(self, member_id: str,
                      session: _MemberSession) -> Optional[_MemberSession]:
        """Make ``session`` the member's current session; returns the
        session it superseded (a reconnect), if any."""
        with self._lock:
            prev = self._by_member.get(member_id)
            self._by_member[member_id] = session
            return prev if prev is not session else None

    def _is_current(self, member_id: str, session: _MemberSession) -> bool:
        with self._lock:
            return self._by_member.get(member_id) is session

    def _drop_session(self, session: _MemberSession) -> None:
        with self._lock:
            try:
                self._sessions.remove(session)
            except ValueError:
                pass
            if (session.member_id is not None
                    and self._by_member.get(session.member_id) is session):
                self._by_member.pop(session.member_id, None)

    # -- registry HA hooks (serving/fleet_ha.py) ---------------------------

    def control_epoch(self) -> int:
        """The epoch stamped on every control frame this registry sends
        (submits, aborts, KvIntros). 0 = no HA configured — members
        treat 0 as unfenced (legacy single-registry behavior)."""
        ha = self.ha
        return ha.epoch if ha is not None else 0

    def on_registry_frame(self, name: str, obj: Dict[str, Any]) -> None:
        """A peer registry's RegistryLease / RegistryState frame,
        arriving on a member session's reader thread."""
        ha = self.ha
        if ha is not None:
            ha.on_peer_frame(name, obj)

    def on_ha_promote(self) -> None:
        """Takeover re-arm: this registry just won the lease. The member
        table, proxies, and learned rates are already warm (the dual
        heartbeat kept them live) — what needs re-arming is the intro
        broker: re-publish every known endpoint at the NEW epoch so
        members fence out any stale intros from the old primary."""
        if not self.settings.mesh_enabled:
            return
        with self._lock:
            endpoints = dict(self._intro_endpoints)
            sessions = dict(self._by_member)
        grant = self.settings.kv_max_streams
        for member_id, session in sessions.items():
            for other_id, ep in endpoints.items():
                if other_id == member_id:
                    continue
                self._send_intro(session, {
                    "member_id": other_id, "host": ep[0],
                    "data_port": ep[1], "max_streams": grant,
                })

    # -- span ingest (session reader threads) ------------------------------

    def ingest_spans(self, obj: Dict[str, Any], member_id: str) -> None:
        """Merge one FleetSpans frame into the host tracer: each span is
        re-based into this host's monotonic domain and stamped with its
        member id, then exported through every sink (ring + OTLP) with
        its original trace/span/parent ids intact — the operator's
        ``/server/trace?trace_id=`` and the OTLP backend both see ONE
        correctly-parented cross-process tree. Spans the member shed
        before shipping count as wire drops."""
        if self.tracer is None:
            return
        member = member_id or obj.get("member_id", "")
        dropped = obj.get("dropped", 0)
        if dropped:
            self.tracer.record_drop("wire", int(dropped))
        for d in obj.get("spans", []):
            try:
                self.tracer.ingest(
                    span_from_wire(d, self._epoch_offset_ns, member))
            except Exception:  # noqa: BLE001 — one bad span must not
                # drop its whole batch
                logger.debug("undecodable remote span from %s", member,
                             exc_info=True)
                self.tracer.record_drop("wire")

    # -- telemetry ingest (session reader threads) --------------------------

    def ingest_telemetry(self, obj: Dict[str, Any], member_id: str) -> None:
        """Store one FleetTelemetry frame (replacing the member's
        previous one — digests are cumulative windows, not deltas, so
        last-frame-wins is exact) and publish the fleet_*{member}
        series: cumulative step-clock tokens per dispatch kind and the
        member's windowed TTFT p99 (docs/OBSERVABILITY.md)."""
        from distributed_inference_server_tpu.serving import teledigest

        member = member_id or obj.get("member_id", "")
        if not member:
            return
        digests = {d.get("name", ""): d for d in obj.get("digests", [])
                   if d.get("name")}
        foreign: List[str] = []
        if self.metrics is not None:
            # epoch geometry is part of the merge key space: a member
            # configured with a different slo.epoch_s ships epoch
            # indices in a different time unit — merging them would
            # silently corrupt the fleet windows, so drop them LOUDLY
            local_epoch_s = self.metrics.perf_epoch_s()
            foreign = [n for n, d in digests.items()
                       if float(d.get("epoch_s", 0.0)) != local_epoch_s]
            if foreign:
                logger.warning(
                    "fleet telemetry from %s dropped %d digest(s) with "
                    "foreign epoch_s (member slo.epoch_s disagrees with "
                    "this host's %.3gs): %s", member, len(foreign),
                    local_epoch_s, sorted(foreign),
                )
                for name in foreign:
                    del digests[name]
        counters = {c.get("name", ""): c.get("value", 0.0)
                    for c in obj.get("counters", []) if c.get("name")}
        with self._lock:
            self._telemetry[member] = {
                "digests": digests,
                "counters": counters,
                "at": time.monotonic(),
            }
            pruned = self._prune_telemetry_locked(time.monotonic())
        self._drop_member_series(pruned)
        self._ingest_wire_counters(member, counters)
        if self.metrics is not None:
            # exactly ONE outcome per frame: a frame that lost digests
            # to the epoch guard must not also read as cleanly ingested
            # (sum-over-outcomes == frames, and the mismatch stays loud)
            self.metrics.record_telemetry_frame(
                "epoch_mismatch" if foreign else "ingested")
            step_tokens: Dict[str, float] = {}
            for name, value in counters.items():
                parts = name.split(".")
                if (parts[0] == "step" and len(parts) == 4
                        and parts[3] == "tokens"):
                    step_tokens[parts[2]] = (
                        step_tokens.get(parts[2], 0.0) + value
                    )
            ttft_p99 = None
            ttft = digests.get("ttft_ms")
            if ttft is not None:
                stats = teledigest.window_stats(
                    ttft, self.metrics.perf_window_s())
                ttft_p99 = stats.get("p99")
            self.metrics.set_member_telemetry(member, step_tokens,
                                              ttft_p99)

    def _ingest_wire_counters(self, member: str,
                              counters: Dict[str, float]) -> None:
        """Feed the member's cumulative ``kvwire|src|dst|*`` counters
        (serving/fleet_mesh.py — its mesh channels' observed bulk
        bytes/seconds/chunks) into the host's learned-rate windows as
        DELTAS against the last frame. A counter running backwards
        means the member's telemetry restarted: the current value IS
        the delta then (same reasoning as any cumulative-counter
        scrape)."""
        wires: Dict[Tuple[str, str], Dict[str, float]] = {}
        from distributed_inference_server_tpu.serving.fleet_mesh import (
            WIRE_COUNTER_PREFIX,
        )

        for name, value in counters.items():
            if not name.startswith(WIRE_COUNTER_PREFIX):
                continue
            parts = name.split("|")
            if len(parts) != 4 or parts[3] not in ("bytes", "seconds",
                                                   "chunks"):
                continue
            wires.setdefault((parts[1], parts[2]), {})[parts[3]] = value
        if not wires:
            return
        for (src, dst), vals in wires.items():
            cur = (vals.get("bytes", 0.0), vals.get("seconds", 0.0),
                   vals.get("chunks", 0.0))
            key = (member, src, dst)
            with self._lock:
                last = self._kvwire_last.get(key, (0.0, 0.0, 0.0))
                self._kvwire_last[key] = cur
            if any(c < p for c, p in zip(cur, last)):
                last = (0.0, 0.0, 0.0)  # member telemetry restarted
            d_bytes, d_secs, d_chunks = (c - p
                                         for c, p in zip(cur, last))
            if d_bytes > 0 and d_secs > 0:
                self.mesh_rates.observe(src, dst, int(d_bytes), d_secs,
                                        chunks=int(d_chunks))

    def _prune_telemetry_locked(self, now: float) -> List[str]:
        """Drop members silent past the dead-retention window (a
        restarted worker mints a fresh id, same rationale as the
        registry's member table). Runs on every ingest — an unpolled
        registry host must not grow one digest frame per dead worker
        forever. Returns the pruned member ids (caller drops their
        gauge series outside the lock)."""
        horizon = self.settings.dead_after_s + self.settings.dead_retention_s
        stale = [m for m, v in self._telemetry.items()
                 if now - v["at"] > horizon]
        for member in stale:
            del self._telemetry[member]
        return stale

    def _drop_member_series(self, members: List[str]) -> None:
        """Remove pruned members' fleet_member_* gauge series: a dead
        member's last TTFT p99 must stop reading as live, and per-
        restart member ids must not grow /metrics without bound (same
        policy as the tenant-depth gauge)."""
        if self.metrics is None:
            if members:
                self._forget_wires(members)
            return
        for member in members:
            self.metrics.remove_member_telemetry(member)
        self._forget_wires(members)

    def _forget_wires(self, members: List[str]) -> None:
        """Drop pruned/dead members' learned-rate state: their wire
        series leave the gauge (bounded label sets) and their stored
        cumulative counters leave the delta table."""
        for member in members:
            self.mesh_rates.drop_member(member)
        with self._lock:
            for member in members:
                self._intro_endpoints.pop(member, None)
            gone = [k for k in self._kvwire_last
                    if k[0] in members or k[1] in members
                    or k[2] in members]
            for key in gone:
                del self._kvwire_last[key]

    def telemetry_snapshot(self) -> Dict[str, Dict[str, Any]]:
        """Per-member telemetry for GET /server/perf: last frame per
        member with its age (stale members pruned here too, so a quiet
        control plane still converges on read)."""
        now = time.monotonic()
        with self._lock:
            pruned = self._prune_telemetry_locked(now)
            out = {
                member: {
                    "digests": dict(v["digests"]),
                    "counters": dict(v["counters"]),
                    "age_s": now - v["at"],
                }
                for member, v in self._telemetry.items()
            }
        self._drop_member_series(pruned)
        return out

    # -- KV data plane (session reader threads) -----------------------------

    def _ensure_kv_channel(self, session: _MemberSession, member_id: str,
                           data_port: int) -> None:
        """Create (or retire) the member's KV data channel to match its
        advertised ``data_port``. The channel itself dials lazily — the
        first cross-host handoff/fetch pays the connect, not the
        heartbeat path."""
        from distributed_inference_server_tpu.serving.fleet_kv import (
            KvDataChannel,
        )

        host = session.peer.rsplit(":", 1)[0]
        with session._lock:
            if session._closed:
                return
            current = session.kv_channel
            if data_port <= 0:
                session.kv_channel = None
                stale = current
            elif (current is not None
                    and current.address == (host, data_port)):
                return
            else:
                stale = current
                session.kv_channel = KvDataChannel(
                    member_id, host, data_port,
                    max_streams=self.settings.kv_max_streams,
                    connect_timeout_s=self.settings.kv_connect_timeout_s,
                    metrics=self.metrics,
                    on_event=session._on_event,
                    on_lost_requests=lambda rids, reason,
                    s=session: self._fail_kv_requests(s, rids, reason),
                    # gray-failure defense (serving/health.py): the
                    # wire's circuit breaker + budgeted reconnects
                    breaker_threshold=self.health_settings.wire_failures,
                    breaker_open_s=self.health_settings.breaker_open_s,
                    retry_budget=self.retry_budget,
                    # learned wire-rate model (serving/fleet_mesh.py):
                    # the host's channels are the "registry" -> member
                    # wires in the (src, dst) rate key space
                    rate_estimator=self.mesh_rates.estimator(
                        "registry", member_id),
                )
            for runner in session.runners.values():
                runner.kv_channel = session.kv_channel
        if stale is not None:
            stale.close("member advertised a new kv data port")

    def _fail_kv_requests(self, session: _MemberSession,
                          request_ids: List[str], reason: str) -> None:
        """The data channel died with migrated requests mid-decode:
        fail exactly those, fast (they streamed tokens — engine_crashed,
        never silently re-run)."""
        with session._lock:
            runners = list(session.runners.values())
        for runner in runners:
            runner.fail_requests(request_ids, reason)

    def kv_stats(self) -> Dict[str, Any]:
        """Per-member data-channel state for the ``/server/stats``
        fleet block (connected / in-flight streams / bytes)."""
        with self._lock:
            sessions = [(s.member_id, s) for s in self._sessions
                        if s.member_id is not None]
        out: Dict[str, Any] = {}
        for member_id, session in sessions:
            with session._lock:
                channel = session.kv_channel
            if channel is not None:
                out[member_id] = channel.stats()
        return out

    # -- KV mesh introduction broker (session reader threads) ---------------

    def _broker_intros(self, session: _MemberSession, member_id: str,
                       data_port: int) -> None:
        """Keep every member introduced to every other member's
        advertised data-plane endpoint (serving/fleet_mesh.py). Called
        per heartbeat, but intros only cross the wire when an endpoint
        is NEW or CHANGED — plus a full catch-up of the existing fleet
        to a member whose endpoint just (re)appeared, covering both a
        fresh joiner and a reconnect after the registry bounced."""
        if not self.settings.mesh_enabled:
            return
        if self.ha is not None and not self.ha.is_primary():
            # standby: track endpoints (warm state) but never broker —
            # only the lease holder publishes intros; on takeover
            # on_ha_promote() re-publishes everything at the new epoch
            endpoint_ = None
            host_ = session.peer.rsplit(":", 1)[0]
            if data_port > 0:
                endpoint_ = (host_, int(data_port))
            with self._lock:
                if endpoint_ is None:
                    self._intro_endpoints.pop(member_id, None)
                else:
                    self._intro_endpoints[member_id] = endpoint_
            return
        host = session.peer.rsplit(":", 1)[0]
        endpoint = (host, int(data_port)) if data_port > 0 else None
        with self._lock:
            prev = self._intro_endpoints.get(member_id)
            if endpoint == prev:
                return
            if endpoint is None:
                self._intro_endpoints.pop(member_id, None)
            else:
                self._intro_endpoints[member_id] = endpoint
            others = [(m, s, self._intro_endpoints.get(m))
                      for m, s in self._by_member.items()
                      if m != member_id]
        if endpoint is None:
            # the member stopped advertising a data plane: retract it
            for other_id, other_session, _ep in others:
                self._send_intro(other_session,
                                 {"member_id": member_id, "gone": True})
            return
        grant = self.settings.kv_max_streams
        for other_id, other_session, other_ep in others:
            # both directions: the fleet learns the (new) endpoint...
            self._send_intro(other_session, {
                "member_id": member_id, "host": endpoint[0],
                "data_port": endpoint[1], "max_streams": grant,
            })
            # ...and the (re)joiner learns the existing fleet
            if other_ep is not None:
                self._send_intro(session, {
                    "member_id": other_id, "host": other_ep[0],
                    "data_port": other_ep[1], "max_streams": grant,
                })

    def _send_intro(self, session: _MemberSession,
                    obj: Dict[str, Any]) -> None:
        """One KvIntro send, outcome-counted: the broker is best-effort
        by design (a dropped intro only costs the mesh route — the
        fetch degrades to recompute, never to an error)."""
        ha = getattr(self, "ha", None)
        if ha is not None and ha.epoch:
            # registry HA fence: members ignore intros older than the
            # highest epoch they have seen (serving/fleet_ha.py)
            obj = dict(obj, epoch=ha.epoch)
        try:
            # injected broker drop (docs/RESILIENCE.md fleet.kv_intro)
            faults.fire("fleet.kv_intro")
            session.send("KvIntro", obj)
            outcome = "gone" if obj.get("gone") else "sent"
        except faults.InjectedFault:
            outcome = "dropped"
        except (FleetWireError, OSError) as e:
            logger.debug("kv intro to %s failed: %s", session.member_id, e)
            outcome = "failed"
        if self.metrics is not None:
            self.metrics.record_kv_intro(outcome)

    def mesh_route(self, target_member: str, peer_member: str) -> bool:
        """True when the mesh has (or will have, via the per-heartbeat
        broker) introduced ``target_member`` to ``peer_member`` — the
        gate for delegating a remote-target/remote-peer fetch to the
        member instead of relaying chunk bytes through this host."""
        if not self.settings.mesh_enabled or target_member == peer_member:
            return False
        with self._lock:
            return (target_member in self._intro_endpoints
                    and peer_member in self._intro_endpoints)

    def kv_wire_stats(self) -> List[Dict[str, Any]]:
        """The ``kv_wires`` table of ``/server/stats``: one row per
        directed wire with its learned rate and lifetime bytes/chunks
        (serving/fleet_mesh.py). Registry-owned wires carry live
        connectivity + breaker state from their channel; member-to-
        member wires carry whether the pair is currently introduced
        (their sockets live in the members — the rows' rates arrive via
        telemetry)."""
        rows: Dict[Tuple[str, str], Dict[str, Any]] = {
            (r["src"], r["dst"]): r for r in self.mesh_rates.snapshot()
        }
        for member_id, st in self.kv_stats().items():
            row = rows.setdefault(("registry", member_id), {
                "src": "registry", "dst": member_id,
                "rate_bytes_per_s": None, "bytes": 0, "chunks": 0,
            })
            row["connected"] = st.get("connected", False)
            row["breaker"] = st.get("breaker")
        with self._lock:
            introduced = set(self._intro_endpoints)
        for (src, dst), row in rows.items():
            if "connected" not in row:
                row["introduced"] = (src in introduced
                                     and dst in introduced)
        return [rows[k] for k in sorted(rows)]

    # -- runner materialization (session reader threads) -------------------

    def _refresh_runners(self, session: _MemberSession, member_id: str,
                         wire_engines: List[Dict[str, Any]],
                         statuses: List[EngineStatus],
                         rejoined: bool) -> None:
        from distributed_inference_server_tpu.serving.remote_runner import (
            RemoteRunner,
        )

        by_local_id = {d.get("engine_id", ""): s
                       for d, s in zip(wire_engines, statuses)}
        with session._lock:
            if session._closed:
                return
            stale = set(session.runners) - set(by_local_id)
            if rejoined:
                # dead->alive: the death path detached the old proxies;
                # fresh ones own a clean in-flight map
                stale |= set(session.runners)
            gone = [(eid, session.runners.pop(eid)) for eid in stale]
            for local_id, status in by_local_id.items():
                runner = session.runners.get(local_id)
                if runner is None:
                    runner = RemoteRunner(
                        engine_id=status.engine_id,
                        local_engine_id=local_id,
                        send=session.send,
                        metrics=self.metrics,
                        recorder=self.recorder,
                    )
                    runner.redispatch = self.redispatch
                    runner.kv_channel = session.kv_channel
                    # registry HA: stamp submits/aborts with this
                    # registry's control epoch (0 = unfenced)
                    runner.epoch_fn = self.control_epoch
                    session.runners[local_id] = runner
                    self.scheduler.register(runner)
                    logger.info("fleet: registered remote engine %s "
                                "(role=%s)", status.engine_id, status.role)
                elif self.scheduler.get(runner.engine_id) is not runner:
                    # a superseded session's late detach (or anything
                    # else) evicted our registration — heal it, or the
                    # engine silently takes no traffic while alive
                    self.scheduler.register(runner)
                runner.update_status(status)
        for _eid, runner in gone:
            self.scheduler.unregister_if(runner.engine_id, runner)
            runner.detach("remote engine left the member's heartbeat")

    # -- member state transitions (sweeper / reader threads) ---------------

    def _on_member_state(self, member_id: str, old: str, new: str) -> None:
        logger.warning("fleet member %s: %s -> %s", member_id, old, new)
        with self._lock:
            session = self._by_member.get(member_id)
        if session is None:
            return
        if new == MEMBER_DEAD:
            # remote death maps onto the crash-safe redispatch path:
            # zero-token in-flight requests move to healthy replicas
            # exactly once, mid-stream ones fail fast (RESILIENCE.md)
            session.detach_runners(
                f"fleet member {member_id} dead (missed heartbeats)")
            # KV mesh: retract the dead member's endpoint from the
            # fleet (each receiver closes its wire) and drop its
            # learned-rate series — dead host:pid identities must not
            # pin gauge labels (serving/fleet_mesh.py)
            if self.settings.mesh_enabled:
                with self._lock:
                    known = member_id in self._intro_endpoints
                    others = [s for m, s in self._by_member.items()
                              if m != member_id]
                if known:
                    for other in others:
                        self._send_intro(other, {"member_id": member_id,
                                                 "gone": True})
            self._forget_wires([member_id])
        elif new == MEMBER_SUSPECT:
            with session._lock:
                runners = list(session.runners.values())
            for runner in runners:
                runner.set_member_state(MEMBER_SUSPECT)
        elif new == MEMBER_ALIVE and old == MEMBER_SUSPECT:
            with session._lock:
                runners = list(session.runners.values())
            for runner in runners:
                runner.set_member_state(MEMBER_ALIVE)
        # dead -> alive rejoin is handled by the heartbeat path, which
        # materializes fresh proxies (rejoined=True)


# ---------------------------------------------------------------------------
# Dynamic role rebalancing
# ---------------------------------------------------------------------------


class RoleBalancer:
    """Flips ``unified`` engines to ``prefill`` when the fleet's prompt
    queue deepens, and back when it drains — with two-sided hysteresis
    (a signal band plus a flip cooldown) so an oscillating queue cannot
    flap roles. Only engines the balancer itself flipped are ever
    restored; operator-configured roles are never rewritten."""

    def __init__(self, scheduler, dispatcher,
                 settings: Optional[FleetSettings] = None,
                 metrics: Optional[MetricsCollector] = None,
                 recorder=None):
        """``recorder`` (serving/flightrec.py): role flips land in the
        flight recorder's fleet-event window, so a request's timeline
        shows a rerole that happened mid-flight."""
        self.scheduler = scheduler
        self.dispatcher = dispatcher
        self.settings = settings or FleetSettings()
        self.metrics = metrics
        self.recorder = recorder
        self._lock = threading.Lock()
        self._flipped: Dict[str, float] = {}  # engine_id -> flip time
        self._last_flip = 0.0
        self._last_signal = 0.0
        self._history: Deque[Dict[str, Any]] = deque(maxlen=64)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # registry HA (serving/fleet_ha.py): only the lease-holding
        # primary balances roles — the server wires this to
        # RegistryHA.is_primary. None = always active (no HA).
        self.active_fn: Optional[Callable[[], bool]] = None

    # -- the decision ------------------------------------------------------

    def signal(self) -> float:
        """Fleet prompt pressure: queued + engine-waiting prompts per
        healthy admission-capable (prefill/unified) replica."""
        statuses = self.scheduler.statuses()
        admission = [s for s in statuses if s.healthy
                     and s.role in ("prefill", "unified")]
        waiting = sum(s.waiting_requests for s in admission)
        depth = self.dispatcher.queue.total_depth()
        return (depth + waiting) / max(1, len(admission))

    def evaluate(self, now: Optional[float] = None) -> Optional[str]:
        """One rebalance decision; returns the flip direction applied
        ("to_prefill" / "to_unified") or None. At most one engine flips
        per evaluation, and never within ``rerole_cooldown_s`` of the
        previous flip — that cooldown IS the temporal hysteresis the
        ``rerole_flap`` chaos scenario pins."""
        if not self.settings.rerole:
            return None
        if self.active_fn is not None and not self.active_fn():
            # registry HA: a standby's balancer stays armed but quiet —
            # two balancers flipping the same fleet would fight
            return None
        now = time.monotonic() if now is None else now
        statuses = self.scheduler.statuses()
        # gates the to_prefill direction ONLY: restores must still run
        # with the decode fleet gone, or a balancer-flipped engine would
        # be stuck in the prefill role forever. LOCAL decode only:
        # remote replicas are not KV handoff targets (disagg.py), so
        # remote decode capacity cannot make a flip pay
        has_decode = any(
            s.healthy and s.role == "decode"
            and not getattr(s, "remote", False)
            for s in statuses
        )
        sig = self.signal()
        if faults.flag("sched.rerole"):
            # chaos lever: force the raw signal high for one evaluation
            # (drives the flip DESIRE deterministically; hysteresis and
            # cooldown still bound the actual flips)
            sig = max(sig, self.settings.rerole_high_ratio)
        direction = None
        with self._lock:
            self._last_signal = sig
            if now - self._last_flip < self.settings.rerole_cooldown_s:
                return None
            if sig >= self.settings.rerole_high_ratio and has_decode:
                runner = self._pick_unified()
                if runner is not None:
                    runner.set_role("prefill")
                    self._flipped[runner.engine_id] = now
                    self._last_flip = now
                    direction = "to_prefill"
                    self._record(runner.engine_id, direction, sig)
            elif sig <= self.settings.rerole_low_ratio and self._flipped:
                runner = self._pick_flipped_locked()
                if runner is not None:
                    runner.set_role("unified")
                    self._flipped.pop(runner.engine_id, None)
                    self._last_flip = now
                    direction = "to_unified"
                    self._record(runner.engine_id, direction, sig)
        if direction:
            logger.info("fleet rerole %s (signal %.2f)", direction, sig)
            if self.recorder is not None:
                self.recorder.note_global("rerole", direction=direction,
                                          signal=round(sig, 3))
            if self.metrics:
                self.metrics.record_rerole(direction)
                self.metrics.set_engines_by_role(self._role_counts())
        return direction

    def _pick_unified(self):
        candidates = [
            r for r in self.scheduler.engines()
            if r.role == "unified" and r.is_healthy()
            and not getattr(r, "is_remote", False)
        ]
        if not candidates:
            return None
        return min(candidates, key=lambda r: r.engine_id)

    def _pick_flipped_locked(self):
        for engine_id in sorted(self._flipped):
            runner = self.scheduler.get(engine_id)
            if runner is not None and runner.role == "prefill":
                return runner
            self._flipped.pop(engine_id, None)  # unregistered/re-roled
        return None

    def _record(self, engine_id: str, direction: str, sig: float) -> None:
        self._history.append({
            "engine_id": engine_id, "direction": direction,
            "signal": round(sig, 3), "t": round(time.time(), 3),
        })

    def _role_counts(self) -> Dict[str, int]:
        # LOCAL replicas only, matching the boot-time publisher
        # (server.py uses DisaggController.role_counts over the static
        # role list) — the gauge's meaning must not depend on which
        # publisher wrote last
        counts: Dict[str, int] = {}
        for r in self.scheduler.engines():
            if not getattr(r, "is_remote", False):
                counts[r.role] = counts.get(r.role, 0) + 1
        return counts

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "signal": round(self._last_signal, 3),
                "flipped": sorted(self._flipped),
                "history": list(self._history),
            }

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        # lifecycle handle  # distlint: ignore[DL008]
        self._thread = threading.Thread(
            target=self._loop, name="fleet-rerole", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.settings.rerole_interval_s):
            try:
                self.evaluate()
            except Exception:  # noqa: BLE001 — balancer must stay alive
                logger.exception("role rebalance evaluation failed")


def parse_connect(connect: str) -> Tuple[str, int]:
    """Parse ``fleet.connect`` ("host:port") for worker mode."""
    host, sep, port = connect.rpartition(":")
    if not sep or not host or not port.isdigit():
        raise ConfigError(
            f"fleet.connect must be host:port, got {connect!r}"
        )
    return host, int(port)
