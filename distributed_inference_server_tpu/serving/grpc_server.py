"""gRPC transport: the reference's spec'd optional second API surface.

S1 lists "optional gRPC (Tonic)" next to the HTTP server
(``design.md:139-155`` [spec]; SURVEY.md §2.2). This realizes it with
``grpc.aio`` over the SAME InferenceHandler the HTTP app uses — one
request-processing spine, two transports.

The authoritative contract document is ``serving/inference.proto``
(message schemas, streaming shapes, status mapping — protoc-valid, ready
for real codegen in environments that have the plugin).

Wire contract: each method accepts BOTH encodings and answers in kind,
auto-detected per request (VERDICT r3 next #5):

- **protobuf binary** per ``serving/inference.proto`` — hand-rolled
  codecs in ``serving/protowire.py`` (the image ships grpcio but no
  protoc gRPC codegen plugin);
- **JSON** (UTF-8 bytes of the HTTP schema) — a client holding the HTTP
  schema speaks gRPC unchanged.

Detection is unambiguous: JSON payloads start with ``{`` (0x7b), which
as a protobuf key would be field 15 with the unused group wire type —
no message in the schema has such a field. Empty payloads (e.g.
HealthRequest) parse as protobuf.

  dis.tpu.InferenceService/Generate        unary    (GenerateRequest)
  dis.tpu.InferenceService/GenerateStream  s-stream (TokenEvent frames)
  dis.tpu.InferenceService/Chat            unary
  dis.tpu.InferenceService/ChatStream      s-stream
  dis.tpu.InferenceService/Embeddings      unary
  dis.tpu.InferenceService/Health          unary    (same JSON as /health)

Errors map to canonical gRPC status codes (the reference's HTTP mapping,
error.rs:39-56 semantics): 400 -> INVALID_ARGUMENT, 408 ->
DEADLINE_EXCEEDED, 503 -> UNAVAILABLE, else INTERNAL; details carry the
ErrorResponse JSON on both wires (gRPC status details are strings).
Client disconnect mid-stream aborts generation (Req 5.4), matching the
SSE path.
"""

from __future__ import annotations

import asyncio
import json
from typing import Optional

import grpc
import grpc.aio

from distributed_inference_server_tpu.core.errors import ApiError
from distributed_inference_server_tpu.core.models import ErrorResponse
from distributed_inference_server_tpu.serving import protowire
from distributed_inference_server_tpu.serving.handler import InferenceHandler

SERVICE = "dis.tpu.InferenceService"

_STATUS = {
    400: grpc.StatusCode.INVALID_ARGUMENT,
    408: grpc.StatusCode.DEADLINE_EXCEEDED,
    429: grpc.StatusCode.RESOURCE_EXHAUSTED,
    503: grpc.StatusCode.UNAVAILABLE,
}

JSON = "json"
PROTO = "proto"


def _json_out(obj) -> bytes:
    return json.dumps(obj).encode()


def _json_in(data: bytes):
    try:
        obj = json.loads(data or b"{}")
    # the None return IS the handling: callers map it to INVALID_ARGUMENT
    # with a client-facing message, so nothing is swallowed
    except Exception:  # noqa: BLE001  # distlint: ignore[DL004]
        return None
    return obj if isinstance(obj, dict) else None


def _decode_request(data: bytes, msg: str):
    """Auto-detect the wire: returns ``(mode, dict-or-None)``. JSON
    payloads start with '{'; anything else decodes as protobuf binary
    per inference.proto (empty bytes = all-defaults message)."""
    if data[:1] == b"{":
        return JSON, _json_in(data)
    try:
        obj = protowire.decode(msg, bytes(data))
    # (PROTO, None) surfaces as INVALID_ARGUMENT to the client — the
    # error reaches the caller, it is not swallowed
    except Exception:  # noqa: BLE001  # distlint: ignore[DL004]
        return PROTO, None
    if msg == "EmbeddingsRequest" and not obj.get("model"):
        # optional field: "" means absent on the proto wire
        obj.pop("model", None)
    return PROTO, obj


def _encode_response(mode: str, msg: str, obj: dict) -> bytes:
    return _json_out(obj) if mode == JSON else protowire.encode(msg, obj)


async def _abort_api_error(context, err: ApiError) -> None:
    body = ErrorResponse.of(str(err), err.error_type(), err.code())
    await context.abort(
        _STATUS.get(err.status_code(), grpc.StatusCode.INTERNAL),
        json.dumps(body.to_dict()),
    )


async def _abort_bad_json(context) -> None:
    await context.abort(
        grpc.StatusCode.INVALID_ARGUMENT,
        json.dumps({"error": {
            "message": "request payload is not a JSON object",
            "error_type": "invalid_request_error",
            "code": "invalid_json",
        }}),
    )


def build_grpc_server(
    handler: InferenceHandler,
    address: str = "127.0.0.1:0",
) -> grpc.aio.Server:
    """Build (not start) the aio server; ``server.add_insecure_port`` has
    already bound ``address`` — read the chosen port from the return of
    this function's ``bound_port`` attribute."""

    def unary(fn, req_msg: str, resp_msg: str):
        async def method(request_bytes, context):
            mode, obj = _decode_request(request_bytes, req_msg)
            if obj is None:
                await _abort_bad_json(context)
            try:
                result = await fn(obj)
            except ApiError as e:
                await _abort_api_error(context, e)
            return _encode_response(mode, resp_msg, result.to_dict())

        return grpc.unary_unary_rpc_method_handler(
            method,
            request_deserializer=lambda b: b,
            response_serializer=lambda b: b,  # method encodes per-wire
        )

    def stream(fn, req_msg: str):
        async def method(request_bytes, context):
            mode, obj = _decode_request(request_bytes, req_msg)
            if obj is None:
                await _abort_bad_json(context)
            try:
                request_id, events = await fn(obj)
            except ApiError as e:
                await _abort_api_error(context, e)
                return
            try:
                async for event in events:
                    yield _encode_response(
                        mode, "TokenEvent", event.to_dict()
                    )
            except asyncio.CancelledError:
                # client went away mid-stream: abort generation (Req 5.4)
                handler.dispatcher.abort(request_id)
                raise

        return grpc.unary_stream_rpc_method_handler(
            method,
            request_deserializer=lambda b: b,
            response_serializer=lambda b: b,
        )

    async def health(obj):
        statuses = handler.dispatcher.scheduler.statuses()
        healthy = any(s.healthy for s in statuses)

        class _Result:
            @staticmethod
            def to_dict():
                return {
                    "status": "ok" if healthy else "unhealthy",
                    "accepting": handler.dispatcher.is_accepting(),
                    "engines": [s.to_dict() for s in statuses],
                }

        return _Result

    handlers = grpc.method_handlers_generic_handler(SERVICE, {
        "Generate": unary(handler.generate, "GenerateRequest",
                          "GenerateResponse"),
        "Chat": unary(handler.chat, "ChatRequest", "ChatResponse"),
        "Embeddings": unary(handler.embeddings, "EmbeddingsRequest",
                            "EmbeddingsResponse"),
        "Health": unary(health, "HealthRequest", "HealthResponse"),
        "GenerateStream": stream(handler.generate_stream,
                                 "GenerateRequest"),
        "ChatStream": stream(handler.chat_stream, "ChatRequest"),
    })
    server = grpc.aio.server()
    server.add_generic_rpc_handlers((handlers,))
    server.bound_port = server.add_insecure_port(address)
    return server


_METHOD_MSGS = {
    "Generate": ("GenerateRequest", "GenerateResponse"),
    "Chat": ("ChatRequest", "ChatResponse"),
    "Embeddings": ("EmbeddingsRequest", "EmbeddingsResponse"),
    "Health": ("HealthRequest", "HealthResponse"),
    "GenerateStream": ("GenerateRequest", "TokenEvent"),
    "ChatStream": ("ChatRequest", "TokenEvent"),
}


class GrpcClient:
    """gRPC client for the service above (used by tests and as the
    reference client implementation). ``wire="json"`` (default) sends
    the HTTP-schema JSON; ``wire="proto"`` speaks protobuf binary per
    inference.proto — both return the same canonical dicts."""

    def __init__(self, target: str, wire: str = JSON):
        if wire not in (JSON, PROTO):
            raise ValueError(f"wire must be 'json' or 'proto': {wire!r}")
        self._channel = grpc.aio.insecure_channel(target)
        self._wire = wire

    async def close(self) -> None:
        await self._channel.close()

    def _codecs(self, method: str):
        req_msg, resp_msg = _METHOD_MSGS[method]
        if self._wire == PROTO:
            return (
                lambda obj: protowire.encode(req_msg, obj),
                lambda b: protowire.decode(resp_msg, b),
            )
        return _json_out, lambda b: json.loads(b)

    def _unary(self, method: str):
        ser, de = self._codecs(method)
        return self._channel.unary_unary(
            f"/{SERVICE}/{method}",
            request_serializer=ser,
            response_deserializer=de,
        )

    def _stream(self, method: str):
        ser, de = self._codecs(method)
        return self._channel.unary_stream(
            f"/{SERVICE}/{method}",
            request_serializer=ser,
            response_deserializer=de,
        )

    async def generate(self, obj: dict) -> dict:
        return await self._unary("Generate")(obj)

    async def chat(self, obj: dict) -> dict:
        return await self._unary("Chat")(obj)

    async def embeddings(self, obj: dict) -> dict:
        return await self._unary("Embeddings")(obj)

    async def health(self) -> dict:
        return await self._unary("Health")({})

    def generate_stream(self, obj: dict):
        return self._stream("GenerateStream")(obj)

    def chat_stream(self, obj: dict):
        return self._stream("ChatStream")(obj)


async def serve_grpc(
    handler: InferenceHandler,
    host: str = "0.0.0.0",
    port: int = 50051,
) -> grpc.aio.Server:
    """Start the gRPC transport next to the HTTP app (both share the
    handler and therefore the queue/batcher/scheduler/engines)."""
    server = build_grpc_server(handler, f"{host}:{port}")
    await server.start()
    return server
