"""Disaggregated prefill/decode serving: engine roles, the KV handoff
channel, and the migration controller.

The serving spine (queue → batcher → scheduler → engine runners) treats
every engine as a monolith that prefills and decodes in place, which
couples long-prompt prefill latency to the decode TBT of every other
request on that replica. This subsystem splits the pipeline:

- every engine runner carries a **role** — ``prefill``, ``decode``, or
  ``unified`` (the default; preserves the monolithic behavior exactly);
- the scheduler routes **admission batches to prefill engines** (least-
  load among non-decode replicas) and, after a request's first token,
  the runner parks the sequence for **migration**: the engine exports
  its paged K/V + host state (``LLMEngine.export_handoff``), a
  **KVTransferChannel** moves the payload, and a decode engine imports
  it (``LLMEngine.import_sequence``) and resumes decoding at the exact
  same position — token-identical under greedy sampling (tested in
  tests/test_disagg.py);
- the **DisaggController** owns the migration queue and a worker thread
  with timeout/retry; any failure (channel error, no healthy decode
  engine, import CacheFull, dtype mismatch) **falls back to decoding in
  place** on the source engine, so a handoff can degrade the topology
  but never drop a request. Fallbacks are visible in metrics
  (``kv_handoff_total{outcome="fallback"}``).

Channel backends: ``InProcessChannel`` hands the payload object over
zero-copy (the single-process deployment); ``ProtowireChannel`` frames
it through the ``KvHandoff`` protobuf message (serving/protowire.py,
contract in serving/inference.proto) — the cross-process wire format a
gRPC transport will carry, exercised end-to-end in-process so the
framing cannot rot before the multi-host deployment lands.

Shutdown drains: the controller stops accepting migrations and resumes
every queued job in place, so graceful shutdown (Req 9.5) holds across
the disaggregated topology.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Deque, Dict, List, Optional, Sequence

from distributed_inference_server_tpu.core.errors import ConfigError
from distributed_inference_server_tpu.engine.engine import (
    SamplingParams,
    SequenceExport,
)
from distributed_inference_server_tpu.serving import protowire
from distributed_inference_server_tpu.serving.metrics import MetricsCollector

logger = logging.getLogger(__name__)

ROLE_PREFILL = "prefill"
ROLE_DECODE = "decode"
ROLE_UNIFIED = "unified"
ROLES = (ROLE_PREFILL, ROLE_DECODE, ROLE_UNIFIED)


class HandoffError(RuntimeError):
    """A KV handoff attempt failed (channel or import); the controller
    retries and ultimately falls back to in-place decode."""


@dataclass(frozen=True)
class DisaggSettings:
    """Knobs for the migration controller (serving config section
    ``disagg``, CLI ``--disagg-*``)."""

    handoff_timeout_s: float = 5.0
    handoff_retries: int = 1  # attempts beyond the first
    channel: str = "inproc"  # inproc | protowire


def parse_roles(spec: str, num_engines: int) -> List[str]:
    """Parse/validate ``server.engine_roles`` ("prefill,decode", ...).

    Empty spec = every engine ``unified`` (today's behavior). Raises
    ConfigError for unknown roles, a count mismatch with
    ``server.num_engines``, and nonsensical topologies: decode engines
    with no prefill engine would never receive work, and prefill engines
    with no decode engine would have nowhere to hand off.
    """
    if not spec.strip():
        return [ROLE_UNIFIED] * num_engines
    roles = [r.strip().lower() for r in spec.split(",") if r.strip()]
    for r in roles:
        if r not in ROLES:
            raise ConfigError(
                f"server.engine_roles: unknown role {r!r} "
                f"(known: {', '.join(ROLES)})"
            )
    if len(roles) != num_engines:
        raise ConfigError(
            f"server.engine_roles lists {len(roles)} roles but "
            f"server.num_engines is {num_engines}"
        )
    n_prefill = roles.count(ROLE_PREFILL)
    n_decode = roles.count(ROLE_DECODE)
    if n_decode and not n_prefill:
        raise ConfigError(
            "server.engine_roles: decode engines without any prefill "
            "engine would sit idle — prompts are only admitted to "
            "prefill/unified replicas and only prefill replicas migrate"
        )
    if n_prefill and not n_decode:
        raise ConfigError(
            "server.engine_roles: prefill engines need at least one "
            "decode engine to hand off to"
        )
    return roles


# ---------------------------------------------------------------------------
# Transfer channels
# ---------------------------------------------------------------------------


class KVTransferChannel:
    """Moves a SequenceExport from a prefill engine toward a decode
    engine. ``transfer`` returns the payload as the receiver will see it
    and raises on failure (the controller retries / falls back)."""

    name = "null"

    def transfer(self, exp: SequenceExport) -> SequenceExport:
        raise NotImplementedError


class InProcessChannel(KVTransferChannel):
    """Zero-copy in-process handoff: both engines live in this process,
    so the export object moves by reference — the page bytes are not
    copied again beyond the device→host pull serialize_kv already did."""

    name = "inproc"

    def transfer(self, exp: SequenceExport) -> SequenceExport:
        return exp


def export_to_wire(exp: SequenceExport) -> bytes:
    """Encode a SequenceExport as a length-delimited ``KvHandoff``
    protobuf message (serving/inference.proto)."""
    obj: Dict[str, Any] = {
        "request_id": str(exp.request_id),
        "token_ids": [int(t) for t in exp.token_ids],
        "prompt_len": exp.prompt_len,
        "seq_len": exp.seq_len,
        "next_token": int(exp.next_token),
        "emitted_tokens": exp.emitted_tokens,
        "output_text": exp.output_text,
        "emitted_upto": exp.emitted_upto,
        "pending_ids": [int(t) for t in exp.pending_ids],
        "max_tokens": exp.params.max_tokens,
        "temperature": exp.params.temperature,
        "top_p": exp.params.top_p,
        "stop_sequences": list(exp.params.stop_sequences),
        "kv": exp.kv,
        "source_engine": exp.source_engine,
    }
    if exp.draft_kv is not None:
        obj["draft_kv"] = exp.draft_kv
    return protowire.encode("KvHandoff", obj)


def export_from_wire(data: bytes) -> SequenceExport:
    """Decode a ``KvHandoff`` frame back into a SequenceExport."""
    d = protowire.decode("KvHandoff", data)
    return SequenceExport(
        request_id=d["request_id"],
        token_ids=list(d["token_ids"]),
        prompt_len=d["prompt_len"],
        seq_len=d["seq_len"],
        next_token=d["next_token"],
        params=SamplingParams(
            max_tokens=d["max_tokens"],
            temperature=d["temperature"],
            top_p=d["top_p"],
            stop_sequences=tuple(d["stop_sequences"]),
        ),
        output_text=d["output_text"],
        emitted_upto=d["emitted_upto"],
        emitted_tokens=d["emitted_tokens"],
        pending_ids=list(d["pending_ids"]),
        kv=d["kv"],
        draft_kv=d.get("draft_kv"),
        source_engine=d["source_engine"],
    )


class ProtowireChannel(KVTransferChannel):
    """Cross-process framing exercised in-process: every handoff
    round-trips through the ``KvHandoff`` protobuf encoding, so the wire
    format the future gRPC transport will carry is differentially tested
    on every migration instead of rotting in a docstring."""

    name = "protowire"

    def transfer(self, exp: SequenceExport) -> SequenceExport:
        return export_from_wire(export_to_wire(exp))


def make_channel(name: str) -> KVTransferChannel:
    if name == "inproc":
        return InProcessChannel()
    if name == "protowire":
        return ProtowireChannel()
    raise ConfigError(
        f"disagg.channel must be inproc/protowire, got {name!r}"
    )


# ---------------------------------------------------------------------------
# Migration controller
# ---------------------------------------------------------------------------


@dataclass
class _MigrationJob:
    exp: SequenceExport
    req: Any  # ServerRequest (typed loosely to avoid an import cycle)
    source: Any  # EngineRunner that prefilled (the in-place fallback)
    enqueued_at: float = field(default_factory=time.monotonic)
    deadline: float = 0.0
    attempts: int = 0


class DisaggController:
    """Owns the migration queue between prefill and decode engines.

    Prefill runners enqueue ``(export, request, source_runner)`` after
    the first token; the worker thread moves each payload through the
    channel, picks the least-loaded healthy decode engine
    (``scheduler.schedule_decode``), and resumes the request there. Any
    failure — channel error, no decode engine, import rejection — is
    retried up to ``handoff_retries`` times within ``handoff_timeout_s``,
    then falls back to resuming on the SOURCE engine, so the request
    completes (merely un-disaggregated) instead of dropping. Shutdown
    drains the queue the same way.
    """

    def __init__(
        self,
        scheduler,
        metrics: Optional[MetricsCollector] = None,
        channel: Optional[KVTransferChannel] = None,
        settings: Optional[DisaggSettings] = None,
    ):
        self.scheduler = scheduler
        self.metrics = metrics
        self.channel = channel or InProcessChannel()
        self.settings = settings or DisaggSettings()
        self._jobs: Deque[_MigrationJob] = deque()
        self._cv = threading.Condition()
        # requests between dequeue and resume-submit: counted by
        # pending_count() so the dispatcher's drain loop cannot miss a
        # request that is in neither a queue nor a runner's inflight map
        self._migrating: Dict[Any, _MigrationJob] = {}
        # client disconnects that raced an in-flight migration: checked
        # right before the resume submit so a dead request is dropped
        # instead of decoding to completion into a closed sink
        self._aborted: set = set()
        self._stop = threading.Event()
        self._accepting = False
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        with self._cv:
            self._accepting = True
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._worker, name="disagg-migrator", daemon=True
        )
        self._thread.start()

    def shutdown(self) -> None:
        """Stop accepting migrations and drain: every queued job resumes
        in place on its source engine (drain-on-shutdown semantics — a
        graceful shutdown may lose disaggregation, never requests)."""
        self._stop.set()
        with self._cv:
            # _accepting flips under _cv: enqueue re-checks it under the
            # same lock, so a job can land in _jobs concurrently with
            # shutdown only BEFORE this block — where the drain below
            # still sees it — never after (distlint DL002-adjacent race:
            # an orphaned job would hang its client forever)
            self._accepting = False
            leftovers = list(self._jobs)
            self._jobs.clear()
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None
        for job in leftovers:
            self._fallback(job, "controller shutdown")

    # -- submission (runner threads) ---------------------------------------

    def enqueue(self, exp: SequenceExport, req, source) -> None:
        """Queue a migration. Called on the source runner's thread right
        after export; if the controller is not accepting (shutdown race),
        the request resumes in place immediately."""
        job = _MigrationJob(
            exp=exp, req=req, source=source,
            deadline=time.monotonic() + self.settings.handoff_timeout_s,
        )
        with self._cv:
            if self._accepting:
                self._jobs.append(job)
                self._cv.notify()
                return
        # checked under _cv: a shutdown racing this enqueue either sees
        # the job in _jobs (and drains it) or we see _accepting False
        # here and resume in place — the job can never be orphaned
        self._fallback(job, "controller not accepting")

    def abort(self, request_id) -> bool:
        """Client disconnect while the request sat in the migration
        queue (drop the job — pages already released by the export) or
        mid-migration (flag it so the worker drops it before the resume
        submit instead of decoding into a closed sink).

        Mid-migration returns False on purpose: the caller
        (Dispatcher.abort) then also sweeps every runner, covering the
        window where the resume was already submitted to a target; the
        flag covers the window where it was not."""
        with self._cv:
            for job in self._jobs:
                if job.req.request_id == request_id:
                    self._jobs.remove(job)
                    return True
            if request_id in self._migrating:
                self._aborted.add(request_id)
        return False

    def _consume_abort(self, job: _MigrationJob) -> bool:
        with self._cv:
            if job.req.request_id in self._aborted:
                self._aborted.discard(job.req.request_id)
                self._migrating.pop(job.req.request_id, None)
                return True
        return False

    def _consume_abort_flag(self, request_id) -> bool:
        """Pop just the abort flag (the _migrating entry is handled by
        the caller's own finish path)."""
        with self._cv:
            if request_id in self._aborted:
                self._aborted.discard(request_id)
                return True
        return False

    def pending_count(self) -> int:
        with self._cv:
            return len(self._jobs) + len(self._migrating)

    # -- worker ------------------------------------------------------------

    def _worker(self) -> None:
        while True:
            with self._cv:
                while not self._jobs and not self._stop.is_set():
                    self._cv.wait(0.1)
                if self._stop.is_set():
                    return
                job = self._jobs.popleft()
                self._migrating[job.req.request_id] = job
            try:
                self._migrate(job)
            except Exception as e:  # noqa: BLE001 — worker must survive
                logger.exception("unexpected migration failure")
                self._fallback(job, str(e))

    def _migrate(self, job: _MigrationJob) -> None:
        """One migration: channel transfer + decode-engine selection,
        retried within the deadline; the import itself resolves
        asynchronously on the target runner's thread and falls back on
        rejection."""
        last_err = "handoff timeout"
        max_attempts = 1 + max(0, self.settings.handoff_retries)
        while job.attempts < max_attempts and time.monotonic() < job.deadline:
            if job.attempts:
                # exponential backoff between attempts, bounded by the
                # deadline: a decode replica mid-restart gets a real
                # chance to come back before the in-place fallback
                # (back-to-back retries would burn the whole budget in
                # microseconds); _stop short-circuits for shutdown
                delay = min(0.1 * (2 ** (job.attempts - 1)),
                            job.deadline - time.monotonic())
                if delay > 0 and self._stop.wait(delay):
                    break
            if self._consume_abort(job):
                return
            job.attempts += 1
            try:
                wired = self.channel.transfer(job.exp)
            except Exception as e:  # noqa: BLE001 — channel fault domain
                last_err = f"channel {self.channel.name}: {e}"
                if self.metrics:
                    self.metrics.record_handoff("retry")
                continue
            target = self.scheduler.schedule_decode(
                exclude=job.source.engine_id
            )
            if target is None:
                last_err = "no healthy decode engine"
                if self.metrics:
                    self.metrics.record_handoff("retry")
                continue
            if self._consume_abort(job):
                return

            def _done(ok: bool, err: Optional[str],
                      job=job, target=target) -> None:
                # runs on the target runner's thread
                if ok:
                    # the request is (and stays) in the target's
                    # inflight map — safe to leave the migrating set
                    self._finish_migration(job)
                    if self._consume_abort_flag(job.req.request_id):
                        # client disconnected while the resume was in
                        # flight and the dispatcher's runner sweep ran
                        # before the target registered it — apply the
                        # abort now instead of decoding into a dead sink
                        target.abort(job.req.request_id)
                        return
                    if err == "aborted":
                        return  # resolved by an abort, not a transfer
                    if self.metrics:
                        self.metrics.record_handoff(
                            "ok",
                            latency_s=time.monotonic() - job.enqueued_at,
                            nbytes=job.exp.kv_bytes(),
                        )
                else:
                    logger.warning(
                        "KV handoff import rejected by %s (%s); decoding "
                        "in place on %s",
                        target.engine_id, err, job.source.engine_id,
                    )
                    self._fallback(job, err or "import failed")

            target.submit_resume(wired, job.req, _done)
            return
        self._fallback(job, last_err)

    def _finish_migration(self, job: _MigrationJob) -> None:
        with self._cv:
            self._migrating.pop(job.req.request_id, None)

    def _fallback(self, job: _MigrationJob, err: str) -> None:
        """Resume the request on its SOURCE engine (in-place decode). If
        even that fails, the request errors out — visibly, never
        silently dropped.

        Drain-coverage invariant: the job leaves the migrating set only
        AFTER submit_resume has registered the request with the source
        runner (registration is synchronous), so at every instant the
        request is visible to the dispatcher's drain loop through either
        ``pending_count()`` or some runner's ``active_count()``."""
        if self._consume_abort(job):
            return
        if self.metrics:
            self.metrics.record_handoff("fallback")

        def _done(ok: bool, import_err: Optional[str]) -> None:
            if not ok:
                try:
                    job.req.sink.on_error(
                        f"KV handoff failed ({err}) and in-place resume "
                        f"failed ({import_err})",
                        "handoff_failed",
                    )
                except Exception as sink_exc:  # noqa: BLE001 — sink isolation
                    logger.debug("fallback sink.on_error for %s raised: %s",
                                 job.req.request_id, sink_exc)
                    if self.metrics:
                        self.metrics.record_error("disagg.sink_error")

        # the original (pre-channel) export resumes in place: the source
        # engine's own dtype/topology always matches itself
        job.source.submit_resume(job.exp, job.req, _done)
        self._finish_migration(job)

    # -- introspection -----------------------------------------------------

    def has_decode_targets(self) -> bool:
        """True while at least one decode-role replica is REGISTERED
        (health is deliberately ignored: a transiently unhealthy decode
        engine is worth the retry/fallback path, a topology with no
        decode replicas at all is not — prefill runners then admit
        unified and skip the per-request serialize/fallback churn)."""
        return any(
            getattr(r, "role", "unified") == "decode"
            for r in self.scheduler.engines()
        )

    @staticmethod
    def role_counts(roles: Sequence[str]) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for r in roles:
            out[r] = out.get(r, 0) + 1
        return out
