"""Disaggregated prefill/decode serving: engine roles, the KV handoff
channel, and the migration controller.

The serving spine (queue → batcher → scheduler → engine runners) treats
every engine as a monolith that prefills and decodes in place, which
couples long-prompt prefill latency to the decode TBT of every other
request on that replica. This subsystem splits the pipeline:

- every engine runner carries a **role** — ``prefill``, ``decode``, or
  ``unified`` (the default; preserves the monolithic behavior exactly);
- the scheduler routes **admission batches to prefill engines** (least-
  load among non-decode replicas) and, after a request's first token,
  the runner parks the sequence for **migration**: the engine exports
  its paged K/V + host state (``LLMEngine.export_handoff``), a
  **KVTransferChannel** moves the payload, and a decode engine imports
  it (``LLMEngine.import_sequence``) and resumes decoding at the exact
  same position — token-identical under greedy sampling (tested in
  tests/test_disagg.py);
- the **DisaggController** owns the migration queue and a worker thread
  with timeout/retry; any failure (channel error, no healthy decode
  engine, import CacheFull, dtype mismatch) **falls back to decoding in
  place** on the source engine, so a handoff can degrade the topology
  but never drop a request. Fallbacks are visible in metrics
  (``kv_handoff_total{outcome="fallback"}``).

Channel backends: ``InProcessChannel`` hands the payload object over
zero-copy (the single-process deployment); ``ProtowireChannel`` frames
it through the ``KvHandoff`` protobuf message (serving/protowire.py,
contract in serving/inference.proto) — the cross-process wire format a
gRPC transport will carry, exercised end-to-end in-process so the
framing cannot rot before the multi-host deployment lands.

Shutdown drains: the controller stops accepting migrations and resumes
every queued job in place, so graceful shutdown (Req 9.5) holds across
the disaggregated topology.
"""

from __future__ import annotations

import dataclasses
import logging
import queue
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Deque, Dict, List, Optional, Sequence

from distributed_inference_server_tpu.core.errors import ConfigError
from distributed_inference_server_tpu.engine.engine import (
    SamplingParams,
    SequenceExport,
)
from distributed_inference_server_tpu.engine.kv_cache import KvChunk
from distributed_inference_server_tpu.serving import faults, protowire
from distributed_inference_server_tpu.serving.metrics import MetricsCollector

logger = logging.getLogger(__name__)

ROLE_PREFILL = "prefill"
ROLE_DECODE = "decode"
ROLE_UNIFIED = "unified"
ROLES = (ROLE_PREFILL, ROLE_DECODE, ROLE_UNIFIED)


class HandoffError(RuntimeError):
    """A KV handoff attempt failed (channel or import); the controller
    retries and ultimately falls back to in-place decode."""


@dataclass(frozen=True)
class DisaggSettings:
    """Knobs for the migration controller (serving config section
    ``disagg``, CLI ``--disagg-*``)."""

    handoff_timeout_s: float = 5.0
    handoff_retries: int = 1  # attempts beyond the first
    channel: str = "inproc"  # inproc | protowire
    # streamed handoff (docs/DISAGG.md "Streaming handoff"): serialize
    # page-group chunks while the sequence keeps decoding on the source,
    # sending only the overlap-window tail at switchover. stream=False
    # forces the monolithic stop-the-world export everywhere (the
    # pre-streaming behavior, kept for A/B benching).
    stream: bool = True
    chunk_pages: int = 8  # pages per KvChunk
    # per-chunk wire encoding of float pools: "int8" halves-plus the
    # bytes moved (per-vector absmax codes + f32 scales) at a bounded
    # accuracy cost; "latent"/"latent_int8" project pages into a rank-r
    # latent (docs/CACHING.md "Latent KV pages") for a further shrink,
    # degrading to "none" on engines without a codec; natively quantized
    # pools pass through unchanged
    wire_quant: str = "none"  # none | int8 | latent | latent_int8


def parse_roles(spec: str, num_engines: int,
                fleet: bool = False) -> List[str]:
    """Parse/validate ``server.engine_roles`` ("prefill,decode", ...).

    Empty spec = every engine ``unified`` (today's behavior). Raises
    ConfigError for unknown roles, a count mismatch with
    ``server.num_engines``, and nonsensical topologies: decode engines
    with no prefill engine would never receive work, and prefill engines
    with no decode engine would have nowhere to hand off. ``fleet``
    (the process is a registry host or a joined worker) RELAXES the two
    topology checks — the counterpart role may live on another fleet
    member, reachable over the KV data plane (serving/fleet_kv.py):
    a prefill-only host migrates to a member's decode replicas, and a
    decode-only member serves a remote prefill fleet.
    """
    if not spec.strip():
        return [ROLE_UNIFIED] * num_engines
    roles = [r.strip().lower() for r in spec.split(",") if r.strip()]
    for r in roles:
        if r not in ROLES:
            raise ConfigError(
                f"server.engine_roles: unknown role {r!r} "
                f"(known: {', '.join(ROLES)})"
            )
    if len(roles) != num_engines:
        raise ConfigError(
            f"server.engine_roles lists {len(roles)} roles but "
            f"server.num_engines is {num_engines}"
        )
    n_prefill = roles.count(ROLE_PREFILL)
    n_decode = roles.count(ROLE_DECODE)
    if n_decode and not n_prefill and not fleet:
        raise ConfigError(
            "server.engine_roles: decode engines without any prefill "
            "engine would sit idle — prompts are only admitted to "
            "prefill/unified replicas and only prefill replicas "
            "migrate (a decode-only topology is legal in fleet worker "
            "mode, where the prefill fleet lives on other members)"
        )
    if n_prefill and not n_decode and not fleet:
        raise ConfigError(
            "server.engine_roles: prefill engines need at least one "
            "decode engine to hand off to (a prefill-only topology is "
            "legal with fleet.enabled, where decode members join over "
            "the KV data plane)"
        )
    return roles


# ---------------------------------------------------------------------------
# Transfer channels
# ---------------------------------------------------------------------------


class KVTransferChannel:
    """Moves a SequenceExport from a prefill engine toward a decode
    engine. ``transfer`` returns the payload as the receiver will see it
    and raises on failure (the controller retries / falls back).

    Streamed (two-phase) handoffs use the chunk-iterator API instead:
    ``transfer_chunks`` moves the immutable-prefix KvChunks while the
    source sequence is still decoding, and ``transfer_commit`` moves the
    switchover delta (tail chunks + host state). The defaults pass
    objects by reference (the in-process deployment)."""

    name = "null"

    def transfer(self, exp: SequenceExport) -> SequenceExport:
        raise NotImplementedError

    def transfer_chunks(self, request_id, wire_quant: str,
                        chunks: List[KvChunk],
                        trace: Optional[tuple] = None) -> List[KvChunk]:
        """``trace`` is the request's ``Span.context()`` tuple (or
        None): it rides the KvHandoffHeader so the receiving side can
        parent its import span on the request's trace
        (docs/OBSERVABILITY.md)."""
        return chunks

    def transfer_commit(self, exp: SequenceExport,
                        tail: List[KvChunk]) -> SequenceExport:
        """The commit payload carries ONLY the tail chunks — the target
        session already holds the prefix."""
        return dataclasses.replace(exp, kv_chunks=list(tail))

    def transfer_fetch_request(self, request_id, hashes: Sequence[int],
                               chunk_pages: int, wire_quant: str,
                               trace: Optional[tuple] = None) -> tuple:
        """Move the fetch_prefix REQUEST half toward the peer (fleet
        prefix sharing, PrefixFetcher): returns ``(request_id, hashes,
        chunk_pages, wire_quant, trace)`` as the peer will see them —
        ``trace`` is the (trace_id, parent_span_id) context the fetch
        span parents on, round-tripped through the KvPrefixFetch wire
        fields under protowire. The response travels back as KvChunks
        via ``transfer_chunks``."""
        return (request_id, list(hashes), chunk_pages, wire_quant,
                tuple(trace) if trace else None)


class InProcessChannel(KVTransferChannel):
    """Zero-copy in-process handoff: both engines live in this process,
    so the export object moves by reference — the page bytes are not
    copied again beyond the device→host pull serialize_kv already did."""

    name = "inproc"

    def transfer(self, exp: SequenceExport) -> SequenceExport:
        return exp


def export_to_wire(exp: SequenceExport) -> bytes:
    """Encode a SequenceExport as a length-delimited ``KvHandoff``
    protobuf message (serving/inference.proto)."""
    obj: Dict[str, Any] = {
        "request_id": str(exp.request_id),
        "token_ids": [int(t) for t in exp.token_ids],
        "prompt_len": exp.prompt_len,
        "seq_len": exp.seq_len,
        "next_token": int(exp.next_token),
        "emitted_tokens": exp.emitted_tokens,
        "output_text": exp.output_text,
        "emitted_upto": exp.emitted_upto,
        "pending_ids": [int(t) for t in exp.pending_ids],
        "max_tokens": exp.params.max_tokens,
        "temperature": exp.params.temperature,
        "top_p": exp.params.top_p,
        "stop_sequences": list(exp.params.stop_sequences),
        "kv": exp.kv,
        "source_engine": exp.source_engine,
    }
    if exp.draft_kv is not None:
        obj["draft_kv"] = exp.draft_kv
    return protowire.encode("KvHandoff", obj)


def export_from_wire(data: bytes) -> SequenceExport:
    """Decode a ``KvHandoff`` frame back into a SequenceExport."""
    d = protowire.decode("KvHandoff", data)
    return SequenceExport(
        request_id=d["request_id"],
        token_ids=list(d["token_ids"]),
        prompt_len=d["prompt_len"],
        seq_len=d["seq_len"],
        next_token=d["next_token"],
        params=SamplingParams(
            max_tokens=d["max_tokens"],
            temperature=d["temperature"],
            top_p=d["top_p"],
            stop_sequences=tuple(d["stop_sequences"]),
        ),
        output_text=d["output_text"],
        emitted_upto=d["emitted_upto"],
        emitted_tokens=d["emitted_tokens"],
        pending_ids=list(d["pending_ids"]),
        kv=d["kv"],
        draft_kv=d.get("draft_kv"),
        source_engine=d["source_engine"],
    )


# -- streamed framing (chunk-iterator wire API) -----------------------------
#
# A streamed handoff crosses the wire as a frame sequence:
#   1 x KvHandoffHeader  (handoff id, request id, wire_quant)
#   N x KvChunk          (index/total, page range, crc32, payload)
#   1 x KvHandoff        (the host state; kv bytes empty — pages moved
#                         in the chunks)
# A real transport (gRPC streaming) maps the (message, bytes) pairs onto
# its own envelope; the in-process ProtowireChannel round-trips the same
# frames so the format is differentially tested on every migration.


def chunks_to_frames(request_id, wire_quant: str, chunks: List[KvChunk],
                     trace: Optional[tuple] = None):
    """Frame a chunk batch as ``(message_name, frame_bytes)`` pairs:
    one KvHandoffHeader, then one KvChunk per chunk — the sender half of
    the chunk-iterator channel API, framed lazily so a transport can put
    each frame on the wire while the next serializes. ``trace`` is the
    request's (trace_id, parent_span_id) context; it rides the header
    so the receiver's spans stitch into the request's trace."""
    hid = str(request_id)
    header = {
        "handoff_id": hid,
        "request_id": str(request_id),
        "wire_quant": wire_quant,
    }
    if trace:
        header["trace_id"], header["parent_span_id"] = trace
    yield "KvHandoffHeader", protowire.encode("KvHandoffHeader", header)
    for c in chunks:
        yield "KvChunk", protowire.encode("KvChunk", {
            "handoff_id": hid,
            "index": c.index,
            "total": c.total,
            "page_start": c.page_start,
            "page_count": c.page_count,
            "crc32": c.crc32,
            "payload": c.payload,
        })


def stream_to_frames(exp: SequenceExport, trace: Optional[tuple] = None):
    """Frame a chunked SequenceExport: header, its chunks, then the
    terminal KvHandoff frame carrying the host state (kv bytes empty —
    the pages moved in the chunks)."""
    yield from chunks_to_frames(exp.request_id, exp.wire_quant,
                                exp.kv_chunks or [], trace=trace)
    yield "KvHandoff", export_to_wire(exp)


def frames_to_parts(frames):
    """Decode a frame sequence into ``(header, chunks, state)`` — state
    is None for a prefix-only (phase 1) batch. Chunk frames may arrive
    in any order. Raises HandoffError on a malformed stream."""
    header: Optional[Dict[str, Any]] = None
    chunks: List[KvChunk] = []
    state: Optional[SequenceExport] = None
    for kind, data in frames:
        if kind == "KvHandoffHeader":
            header = protowire.decode("KvHandoffHeader", data)
        elif kind == "KvChunk":
            d = protowire.decode("KvChunk", data)
            if header is None or d["handoff_id"] != header["handoff_id"]:
                raise HandoffError(
                    "KvChunk before header or with a foreign handoff_id"
                )
            chunks.append(KvChunk(
                index=d["index"], total=d["total"],
                page_start=d["page_start"], page_count=d["page_count"],
                payload=d["payload"], crc32=d["crc32"],
            ))
        elif kind == "KvHandoff":
            state = export_from_wire(data)
        else:
            raise HandoffError(f"unknown stream frame {kind!r}")
    if header is None:
        raise HandoffError("truncated handoff stream (header missing)")
    return header, sorted(chunks, key=lambda c: c.index), state


def stream_from_frames(frames) -> SequenceExport:
    """Reassemble a full SequenceExport (chunks + host state) from
    streamed frames — the one-shot receiver used by
    ProtowireChannel.transfer."""
    header, chunks, state = frames_to_parts(frames)
    if state is None:
        raise HandoffError("truncated handoff stream (state missing)")
    state.kv_chunks = chunks
    state.wire_quant = header["wire_quant"] or "none"
    return state


class ProtowireChannel(KVTransferChannel):
    """Cross-process framing exercised in-process: every handoff
    round-trips through the ``KvHandoff`` protobuf encoding — or, for
    streamed exports, the KvHandoffHeader/KvChunk/KvHandoff frame
    sequence — so the wire format the future gRPC transport will carry
    is differentially tested on every migration instead of rotting in a
    docstring."""

    name = "protowire"

    def transfer(self, exp: SequenceExport) -> SequenceExport:
        if exp.kv_chunks is not None:
            return stream_from_frames(stream_to_frames(exp))
        return export_from_wire(export_to_wire(exp))

    def transfer_chunks(self, request_id, wire_quant: str,
                        chunks: List[KvChunk],
                        trace: Optional[tuple] = None) -> List[KvChunk]:
        _header, wired, _state = frames_to_parts(
            chunks_to_frames(request_id, wire_quant, chunks, trace=trace)
        )
        return wired

    def transfer_commit(self, exp: SequenceExport,
                        tail: List[KvChunk]) -> SequenceExport:
        return stream_from_frames(stream_to_frames(
            dataclasses.replace(exp, kv_chunks=list(tail))
        ))

    def transfer_fetch_request(self, request_id, hashes: Sequence[int],
                               chunk_pages: int, wire_quant: str,
                               trace: Optional[tuple] = None) -> tuple:
        obj = {
            "request_id": str(request_id),
            "hashes": [int(h) for h in hashes],
            "chunk_pages": chunk_pages,
            "wire_quant": wire_quant,
        }
        if trace:
            obj["trace_id"], obj["parent_span_id"] = trace
        d = protowire.decode("KvPrefixFetch", protowire.encode(
            "KvPrefixFetch", obj))
        wire_trace = ((d.get("trace_id"), d.get("parent_span_id"))
                      if d.get("trace_id") else None)
        return (d["request_id"], d["hashes"], d["chunk_pages"],
                d["wire_quant"] or "none", wire_trace)


def make_channel(name: str) -> KVTransferChannel:
    if name == "inproc":
        return InProcessChannel()
    if name == "protowire":
        return ProtowireChannel()
    raise ConfigError(
        f"disagg.channel must be inproc/protowire, got {name!r}"
    )


# ---------------------------------------------------------------------------
# Migration controller
# ---------------------------------------------------------------------------


@dataclass
class _StreamJob:
    """Phase-1 state of a two-phase streamed migration: the immutable
    prefix is transferred and OPENED on a decode engine while the source
    sequence is still decoding in place. The source runner polls
    ``status`` between steps and switches over on "ready"; "failed" /
    "cancelled" cost nothing — the sequence simply keeps decoding where
    it is. Transitions happen under the controller's ``_cv``."""

    request_id: Any
    chunks: List[KvChunk]  # prefix chunks (source-side objects)
    n_prefix_pages: int
    wire_quant: str
    req: Any
    source: Any
    enqueued_at: float = field(default_factory=time.monotonic)
    deadline: float = 0.0
    target: Any = None  # decode EngineRunner, set when opened
    status: str = "opening"  # opening | ready | failed | cancelled
    error: str = ""
    # kv.handoff span (docs/OBSERVABILITY.md), parented on the request's
    # trace context — the same context the KvHandoffHeader carries
    span: Any = None


@dataclass
class _MigrationJob:
    exp: SequenceExport
    req: Any  # ServerRequest (typed loosely to avoid an import cycle)
    source: Any  # EngineRunner that prefilled (the in-place fallback)
    enqueued_at: float = field(default_factory=time.monotonic)
    deadline: float = 0.0
    attempts: int = 0
    # set on a phase-2 (switchover commit) job: the opened stream whose
    # target already holds the prefix
    stream: Optional[_StreamJob] = None
    # kv.handoff span for MONOLITHIC migrations (streamed jobs carry it
    # on their _StreamJob)
    span: Any = None


class DisaggController:
    """Owns the migration queue between prefill and decode engines.

    Prefill runners enqueue ``(export, request, source_runner)`` after
    the first token; the worker thread moves each payload through the
    channel, picks the least-loaded healthy decode engine
    (``scheduler.schedule_decode``), and resumes the request there. Any
    failure — channel error, no decode engine, import rejection — is
    retried up to ``handoff_retries`` times within ``handoff_timeout_s``,
    then falls back to resuming on the SOURCE engine, so the request
    completes (merely un-disaggregated) instead of dropping. Shutdown
    drains the queue the same way.
    """

    def __init__(
        self,
        scheduler,
        metrics: Optional[MetricsCollector] = None,
        channel: Optional[KVTransferChannel] = None,
        settings: Optional[DisaggSettings] = None,
        tracer=None,
        recorder=None,
    ):
        """``tracer``/``recorder`` (docs/OBSERVABILITY.md): migrations
        get a ``kv.handoff`` span parented on the request's trace
        context (the same context the KvHandoffHeader carries across
        the channel) and note handoff phases into the request's
        flight-recorder timeline — the stall windows feed the
        ``handoff_stall`` phase attribution."""
        self.scheduler = scheduler
        self.metrics = metrics
        self.tracer = tracer
        self.recorder = recorder
        self.channel = channel or InProcessChannel()
        self.settings = settings or DisaggSettings()
        # shared retry budget (serving/health.py RetryBudget), wired by
        # the server: retry attempts beyond the first draw from it, so
        # a sick decode fleet cannot turn every migration into retry
        # amplification (exhaustion = straight to the in-place
        # fallback). Single-writer at boot  # distlint: ignore[DL008]
        self.retry_budget = None
        self._jobs: Deque[_MigrationJob] = deque()
        self._cv = threading.Condition()
        # requests between dequeue and resume-submit: counted by
        # pending_count() so the dispatcher's drain loop cannot miss a
        # request that is in neither a queue nor a runner's inflight map
        self._migrating: Dict[Any, _MigrationJob] = {}
        # client disconnects that raced an in-flight migration: checked
        # right before the resume submit so a dead request is dropped
        # instead of decoding to completion into a closed sink
        self._aborted: set = set()
        self._stop = threading.Event()
        self._accepting = False
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        with self._cv:
            self._accepting = True
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._worker, name="disagg-migrator", daemon=True
        )
        self._thread.start()

    def shutdown(self) -> None:
        """Stop accepting migrations and drain: every queued job resumes
        in place on its source engine (drain-on-shutdown semantics — a
        graceful shutdown may lose disaggregation, never requests)."""
        self._stop.set()
        with self._cv:
            # _accepting flips under _cv: enqueue re-checks it under the
            # same lock, so a job can land in _jobs concurrently with
            # shutdown only BEFORE this block — where the drain below
            # still sees it — never after (distlint DL002-adjacent race:
            # an orphaned job would hang its client forever)
            self._accepting = False
            leftovers = list(self._jobs)
            self._jobs.clear()
            for job in leftovers:
                if isinstance(job, _StreamJob):
                    # phase-1 streams: the sequence is still decoding on
                    # its source — flipping to cancelled makes the source
                    # keep it in place, which IS the drain semantics
                    job.status = "cancelled"
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None
        for job in leftovers:
            if isinstance(job, _StreamJob):
                if job.target is not None:
                    job.target.submit_import_abort(job.request_id)
                continue
            if job.stream is not None and job.stream.target is not None:
                job.stream.target.submit_import_abort(
                    job.stream.request_id)
            self._fallback(job, "controller shutdown")

    # -- observability helpers ---------------------------------------------

    def _trace_ctx(self, req) -> Optional[tuple]:
        span = getattr(req, "span", None)
        return span.context() if span is not None else None

    def _start_handoff_span(self, req, source, streamed: bool):
        """A ``kv.handoff`` span parented on the request's trace — the
        SAME context the KvHandoffHeader carries, so a cross-process
        receiver would stitch identically (docs/OBSERVABILITY.md)."""
        if self.tracer is None:
            return None
        ctx = self._trace_ctx(req)
        if ctx is None:
            return None
        return self.tracer.start(
            "kv.handoff", parent=ctx, request_id=str(req.request_id),
            source=source.engine_id, streamed=streamed,
        )

    @staticmethod
    def _span_holder(mjob: _MigrationJob):
        return mjob.stream if mjob.stream is not None else mjob

    def _finish_handoff_span(self, holder, outcome: str, **attrs) -> None:
        span, holder.span = getattr(holder, "span", None), None
        if span is not None and self.tracer is not None:
            span.set(outcome=outcome, **attrs)
            self.tracer.finish(span)

    def _note(self, req, name: str, **attrs) -> None:
        if self.recorder is not None:
            self.recorder.note(req.request_id, name, **attrs)

    # -- submission (runner threads) ---------------------------------------

    def enqueue(self, exp: SequenceExport, req, source) -> None:
        """Queue a migration. Called on the source runner's thread right
        after export; if the controller is not accepting (shutdown race),
        the request resumes in place immediately."""
        job = _MigrationJob(
            exp=exp, req=req, source=source,
            deadline=time.monotonic() + self.settings.handoff_timeout_s,
            span=self._start_handoff_span(req, source, streamed=False),
        )
        self._note(req, "handoff_export", source=source.engine_id)
        with self._cv:
            if self._accepting:
                self._jobs.append(job)
                self._cv.notify()
                return
        # checked under _cv: a shutdown racing this enqueue either sees
        # the job in _jobs (and drains it) or we see _accepting False
        # here and resume in place — the job can never be orphaned
        self._fallback(job, "controller not accepting")

    def abort(self, request_id) -> bool:
        """Client disconnect while the request sat in the migration
        queue (drop the job — pages already released by the export) or
        mid-migration (flag it so the worker drops it before the resume
        submit instead of decoding into a closed sink).

        Mid-migration returns False on purpose: the caller
        (Dispatcher.abort) then also sweeps every runner, covering the
        window where the resume was already submitted to a target; the
        flag covers the window where it was not. Phase-1 stream jobs
        also return False: the sequence is still DECODING on its source
        runner, so the runner sweep must reach it — here they are only
        flipped to cancelled (the source's pump then releases the
        target's reserved pages via cancel_stream)."""
        cleanup = None
        removed = False
        with self._cv:
            for job in self._jobs:
                if job.req.request_id != request_id:
                    continue
                if isinstance(job, _StreamJob):
                    job.status = "cancelled"
                    break
                self._jobs.remove(job)
                if job.stream is not None:
                    # commit job: the target session holds reserved pages
                    job.stream.status = "cancelled"
                    cleanup = job.stream.target
                removed = True
                break
            else:
                if request_id in self._migrating:
                    self._aborted.add(request_id)
        if cleanup is not None:
            cleanup.submit_import_abort(request_id)
        return removed

    def _consume_abort(self, job: _MigrationJob) -> bool:
        with self._cv:
            if job.req.request_id in self._aborted:
                self._aborted.discard(job.req.request_id)
                self._migrating.pop(job.req.request_id, None)
                return True
        return False

    def _consume_abort_flag(self, request_id) -> bool:
        """Pop just the abort flag (the _migrating entry is handled by
        the caller's own finish path)."""
        with self._cv:
            if request_id in self._aborted:
                self._aborted.discard(request_id)
                return True
        return False

    def pending_count(self) -> int:
        with self._cv:
            return len(self._jobs) + len(self._migrating)

    # -- worker ------------------------------------------------------------

    def _worker(self) -> None:
        while True:
            with self._cv:
                while not self._jobs and not self._stop.is_set():
                    self._cv.wait(0.1)
                if self._stop.is_set():
                    return
                job = self._jobs.popleft()
                if isinstance(job, _StreamJob):
                    # phase 1: the request is still LIVE (and decoding)
                    # on the source runner — visible to the drain loop
                    # via its active_count, so no _migrating entry
                    if job.status == "cancelled":
                        continue
                else:
                    self._migrating[job.req.request_id] = job
            if isinstance(job, _StreamJob):
                try:
                    self._open_stream(job)
                except Exception as e:  # noqa: BLE001 — worker survives
                    logger.exception("unexpected stream-open failure")
                    with self._cv:
                        if job.status == "opening":
                            job.error = str(e)
                            job.status = "failed"
                continue
            try:
                if job.stream is not None:
                    self._commit_stream_job(job)
                else:
                    self._migrate(job)
            except Exception as e:  # noqa: BLE001 — worker must survive
                logger.exception("unexpected migration failure")
                self._fallback(job, str(e))

    # -- streamed (two-phase) migration ------------------------------------

    def open_stream(self, request_id, chunks: List[KvChunk],
                    n_prefix_pages: int, wire_quant: str, req,
                    source) -> Optional[_StreamJob]:
        """Queue phase 1 of a streamed migration (called on the source
        runner's thread once the prefix is serialized). Returns None
        when the controller is not accepting — the sequence then simply
        keeps decoding in place."""
        job = _StreamJob(
            request_id=request_id, chunks=chunks,
            n_prefix_pages=n_prefix_pages, wire_quant=wire_quant,
            req=req, source=source,
            deadline=time.monotonic() + self.settings.handoff_timeout_s,
            span=self._start_handoff_span(req, source, streamed=True),
        )
        with self._cv:
            if self._accepting:
                self._note(req, "handoff_export",
                           source=source.engine_id, streamed=True)
                self._jobs.append(job)
                self._cv.notify()
                return job
        self._finish_handoff_span(job, "not_accepting")
        return None

    def _open_stream(self, job: _StreamJob) -> None:
        """Worker half of phase 1: move the prefix chunks through the
        channel, pick a decode target, and open an import session there.
        A REMOTE target (a fleet member's decode replica behind a KV
        data channel, serving/fleet_kv.py) skips the in-process channel
        — the data channel does the real framing on its own wire
        thread. Failure just flips the job to "failed" — the source
        sequence never stopped decoding, so there is nothing to fall
        back FROM."""
        try:
            # injection points (docs/RESILIENCE.md): disagg.chunk hits
            # once per chunk, so nth=N fails the transfer at its Nth
            # chunk — the channel API is batch-synchronous (the target
            # opens with the COMPLETE prefix or not at all), so this
            # models "the stream died partway" from the fleet's view;
            # target-side partial-import abort is kv.import_chunk's
            # domain. disagg.slow_peer stalls (delay_ms rule).
            faults.fire("disagg.slow_peer")
            for _ in job.chunks:
                faults.fire("disagg.chunk")
            target = self.scheduler.schedule_decode(
                exclude=job.source.engine_id,
                # the election charges each remote candidate's learned
                # wire rate for THESE pages (serving/fleet_mesh.py)
                pages=job.n_prefix_pages,
            )
            if target is None:
                raise HandoffError("no healthy decode engine")
            if getattr(target, "is_remote", False):
                wired = job.chunks  # the data channel frames for real
            else:
                wired = self.channel.transfer_chunks(
                    job.request_id, job.wire_quant, job.chunks,
                    trace=self._trace_ctx(job.req),
                )
        except Exception as e:  # noqa: BLE001 — channel/sched fault domain
            with self._cv:
                if job.status == "opening":
                    job.error = str(e)
                    job.status = "failed"
            if self.metrics:
                self.metrics.record_handoff("retry")
            return

        def _opened(ok: bool, err: Optional[str],
                    job=job, target=target) -> None:
            # runs on the target runner's thread (or the data channel's
            # reader thread for a remote target)
            cancelled = False
            with self._cv:
                if job.status == "cancelled":
                    cancelled = True  # raced an abort: undo the open
                elif ok:
                    job.target = target
                    job.status = "ready"
                else:
                    job.error = err or "import open failed"
                    job.status = "failed"
            if cancelled and ok:
                target.submit_import_abort(job.request_id)

        if getattr(target, "is_remote", False):
            target.submit_import_open(
                job.request_id, job.n_prefix_pages, wired, _opened,
                wire_quant=job.wire_quant, trace=self._trace_ctx(job.req),
            )
        else:
            target.submit_import_open(
                job.request_id, job.n_prefix_pages, wired, _opened
            )

    def commit_stream(self, job: _StreamJob, exp: SequenceExport) -> None:
        """Queue phase 2 (called on the source runner's thread right
        after the switchover export): move the tail delta + host state
        to the opened target and resume there. The request has left the
        source runner, so from here the job follows the migration
        bookkeeping (pending_count / fallback semantics)."""
        mjob = _MigrationJob(
            exp=exp, req=job.req, source=job.source, stream=job,
            enqueued_at=job.enqueued_at,
            deadline=time.monotonic() + self.settings.handoff_timeout_s,
        )
        with self._cv:
            if self._accepting:
                self._jobs.append(mjob)
                self._cv.notify()
                return
        # controller shutting down: the state is already lifted off the
        # engine — resume in place on the source, drop the target session
        if job.target is not None:
            job.target.submit_import_abort(job.request_id)
        self._fallback(mjob, "controller not accepting")

    def cancel_stream(self, job: _StreamJob, record: bool = True) -> None:
        """Drop phase 1 (source cancelled: session died, open failed, or
        deadline passed). The sequence keeps decoding in place on the
        source; the target's reserved pages (if the open landed) are
        released. ``record=False`` for cancels that are not fallbacks
        (request finished in place / client abort)."""
        with self._cv:
            try:
                self._jobs.remove(job)
            except ValueError:
                pass
            target = job.target
            job.status = "cancelled"
        self._finish_handoff_span(job,
                                  "fallback" if record else "cancelled")
        if target is not None:
            target.submit_import_abort(job.request_id)
        if record:
            if self.metrics:
                self.metrics.record_handoff("fallback")
            self._note(job.req, "handoff_fallback",
                       reason=job.error or "cancelled")

    def _commit_stream_job(self, mjob: _MigrationJob) -> None:
        """Phase 2 on the worker: tail + host state through the channel,
        commit on the already-opened target. Single attempt — the prefix
        lives in exactly one target session, so retrying elsewhere is
        meaningless; failure falls back to an in-place resume on the
        source (mjob.exp carries the FULL chunk set for that)."""
        job = mjob.stream
        if self._consume_abort(mjob):
            if job.target is not None:
                job.target.submit_import_abort(job.request_id)
            return
        n_prefix = len(job.chunks)
        remote_target = getattr(job.target, "is_remote", False)
        try:
            tail = (mjob.exp.kv_chunks or [])[n_prefix:]
            # commit dropped on the channel (docs/RESILIENCE.md): the
            # target holds the prefix but the switchover delta never
            # lands — the source must resume in place from the full
            # export it still carries
            faults.fire("disagg.commit")
            for _ in tail:
                faults.fire("disagg.chunk")
            if remote_target:
                # the data channel frames the tail itself; hand it the
                # export with ONLY the tail chunks (the member already
                # holds the prefix in its open session)
                wired = dataclasses.replace(mjob.exp, kv_chunks=list(tail))
            else:
                wired = self.channel.transfer_commit(mjob.exp, tail)
        except Exception as e:  # noqa: BLE001 — channel fault domain
            if job.target is not None:
                job.target.submit_import_abort(job.request_id)
            self._fallback(mjob, f"channel {self.channel.name}: {e}")
            return

        def _done(ok: bool, err: Optional[str],
                  mjob=mjob, target=job.target) -> None:
            # runs on the target runner's thread
            if ok:
                self._finish_migration(mjob)
                if self._consume_abort_flag(mjob.req.request_id):
                    target.abort(mjob.req.request_id)
                    return
                if err == "aborted":
                    return
                now = time.monotonic()
                stall = (now - mjob.exp.stalled_at
                         if mjob.exp.stalled_at else None)
                self._finish_handoff_span(
                    self._span_holder(mjob), "ok",
                    target=target.engine_id,
                    chunks=len(mjob.exp.kv_chunks or []),
                )
                self._note(mjob.req, "handoff_resume",
                           target=target.engine_id,
                           chunks=len(mjob.exp.kv_chunks or []),
                           **({"stall_s": stall}
                              if stall is not None else {}))
                if self.metrics:
                    self.metrics.record_handoff(
                        "ok",
                        latency_s=now - mjob.enqueued_at,
                        nbytes=mjob.exp.kv_bytes(),
                        stall_s=stall,
                        chunks=len(mjob.exp.kv_chunks or []),
                        scope=("remote"
                               if getattr(target, "is_remote", False)
                               else "local"),
                    )
            else:
                logger.warning(
                    "streamed KV commit rejected by %s (%s); decoding "
                    "in place on %s",
                    target.engine_id, err, mjob.source.engine_id,
                )
                self._fallback(mjob, err or "import commit failed")

        job.target.submit_import_commit(wired, mjob.req, _done)

    def _migrate(self, job: _MigrationJob) -> None:
        """One migration: channel transfer + decode-engine selection,
        retried within the deadline; the import itself resolves
        asynchronously on the target runner's thread and falls back on
        rejection."""
        last_err = "handoff timeout"
        max_attempts = 1 + max(0, self.settings.handoff_retries)
        while job.attempts < max_attempts and time.monotonic() < job.deadline:
            if job.attempts:
                if (self.retry_budget is not None
                        and not self.retry_budget.acquire("handoff_retry")):
                    # the shared retry budget is dry (serving/health.py):
                    # skip the retry and fall back to decoding in place
                    # now — exactly-once either way
                    last_err = "handoff retry budget exhausted"
                    break
                # exponential backoff between attempts, bounded by the
                # deadline: a decode replica mid-restart gets a real
                # chance to come back before the in-place fallback
                # (back-to-back retries would burn the whole budget in
                # microseconds); _stop short-circuits for shutdown
                delay = min(0.1 * (2 ** (job.attempts - 1)),
                            job.deadline - time.monotonic())
                if delay > 0 and self._stop.wait(delay):
                    break
            if self._consume_abort(job):
                return
            job.attempts += 1
            target = self.scheduler.schedule_decode(
                exclude=job.source.engine_id,
                # pages the move would put on the wire (0 for a
                # monolithic export: the election stays least-loaded)
                pages=sum(c.page_count
                          for c in job.exp.kv_chunks or ()),
            )
            if target is None:
                last_err = "no healthy decode engine"
                if self.metrics:
                    self.metrics.record_handoff("retry")
                continue
            try:
                faults.fire("disagg.slow_peer")
                faults.fire("disagg.transfer")
                for _ in job.exp.kv_chunks or ():
                    faults.fire("disagg.chunk")
                if getattr(target, "is_remote", False):
                    # cross-host target: the member's data channel does
                    # the real framing (serving/fleet_kv.py)
                    wired = job.exp
                else:
                    wired = self.channel.transfer(job.exp)
            except Exception as e:  # noqa: BLE001 — channel fault domain
                last_err = f"channel {self.channel.name}: {e}"
                if self.metrics:
                    self.metrics.record_handoff("retry")
                continue
            if self._consume_abort(job):
                return

            def _done(ok: bool, err: Optional[str],
                      job=job, target=target) -> None:
                # runs on the target runner's thread
                if ok:
                    # the request is (and stays) in the target's
                    # inflight map — safe to leave the migrating set
                    self._finish_migration(job)
                    if self._consume_abort_flag(job.req.request_id):
                        # client disconnected while the resume was in
                        # flight and the dispatcher's runner sweep ran
                        # before the target registered it — apply the
                        # abort now instead of decoding into a dead sink
                        target.abort(job.req.request_id)
                        return
                    if err == "aborted":
                        return  # resolved by an abort, not a transfer
                    now = time.monotonic()
                    # decode pause the migrated sequence actually
                    # observed: switchover (streamed) or export start
                    # (monolithic) until the resume landed
                    stall = (now - job.exp.stalled_at
                             if job.exp.stalled_at else None)
                    self._finish_handoff_span(
                        self._span_holder(job), "ok",
                        target=target.engine_id,
                        chunks=len(job.exp.kv_chunks or []),
                    )
                    self._note(job.req, "handoff_resume",
                               target=target.engine_id,
                               chunks=len(job.exp.kv_chunks or []),
                               **({"stall_s": stall}
                                  if stall is not None else {}))
                    if self.metrics:
                        self.metrics.record_handoff(
                            "ok",
                            latency_s=now - job.enqueued_at,
                            nbytes=job.exp.kv_bytes(),
                            stall_s=stall,
                            chunks=len(job.exp.kv_chunks or []),
                            scope=("remote"
                                   if getattr(target, "is_remote", False)
                                   else "local"),
                        )
                else:
                    logger.warning(
                        "KV handoff import rejected by %s (%s); decoding "
                        "in place on %s",
                        target.engine_id, err, job.source.engine_id,
                    )
                    self._fallback(job, err or "import failed")

            target.submit_resume(wired, job.req, _done)
            return
        self._fallback(job, last_err)

    def _finish_migration(self, job: _MigrationJob) -> None:
        with self._cv:
            self._migrating.pop(job.req.request_id, None)

    def _fallback(self, job: _MigrationJob, err: str) -> None:
        """Resume the request on its SOURCE engine (in-place decode). If
        even that fails, the request errors out — visibly, never
        silently dropped.

        Drain-coverage invariant: the job leaves the migrating set only
        AFTER submit_resume has registered the request with the source
        runner (registration is synchronous), so at every instant the
        request is visible to the dispatcher's drain loop through either
        ``pending_count()`` or some runner's ``active_count()``."""
        if self._consume_abort(job):
            return
        stall = (time.monotonic() - job.exp.stalled_at
                 if job.exp.stalled_at else None)
        self._finish_handoff_span(self._span_holder(job), "fallback",
                                  reason=err)
        self._note(job.req, "handoff_fallback", reason=err,
                   **({"stall_s": stall} if stall is not None else {}))
        if self.metrics:
            self.metrics.record_handoff("fallback", stall_s=stall)

        def _done(ok: bool, import_err: Optional[str]) -> None:
            if not ok:
                try:
                    job.req.sink.on_error(
                        f"KV handoff failed ({err}) and in-place resume "
                        f"failed ({import_err})",
                        "handoff_failed",
                    )
                except Exception as sink_exc:  # noqa: BLE001 — sink isolation
                    logger.debug("fallback sink.on_error for %s raised: %s",
                                 job.req.request_id, sink_exc)
                    if self.metrics:
                        self.metrics.record_error("disagg.sink_error")

        # the original (pre-channel) export resumes in place: the source
        # engine's own dtype/topology always matches itself
        job.source.submit_resume(job.exp, job.req, _done)
        self._finish_migration(job)

    # -- introspection -----------------------------------------------------

    def has_decode_targets(self) -> bool:
        """True while at least one decode-role replica is REGISTERED
        (health is deliberately ignored: a transiently unhealthy decode
        engine is worth the retry/fallback path, a topology with no
        decode replicas at all is not — prefill runners then admit
        unified and skip the per-request serialize/fallback churn).
        Remote fleet proxies count exactly when their member carries a
        KV data channel (``supports_kv_import``, serving/fleet_kv.py):
        the two-phase import stream then runs over the wire; a decode
        replica reachable only over the control wire is still not a
        handoff target."""
        return any(
            getattr(r, "role", "unified") == "decode"
            and (not getattr(r, "is_remote", False)
                 or getattr(r, "supports_kv_import", False))
            for r in self.scheduler.engines()
        )

    @staticmethod
    def role_counts(roles: Sequence[str]) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for r in roles:
            out[r] = out.get(r, 0) + 1
        return out


# ---------------------------------------------------------------------------
# Fleet-wide prefix sharing: the fetch_prefix RPC driver
# ---------------------------------------------------------------------------


class PrefixFetcher:
    """Drives one peer-to-peer prefix fetch per routed-``fetch`` request
    (docs/CACHING.md "Fleet-wide prefix sharing"): the scheduler's cost
    model (scheduler.plan_route) picked a cold replica and a warm peer;
    this moves the matched KV pages before the request is submitted —

    1. the request half crosses the channel (``KvPrefixFetch`` framing,
       differentially wire-tested per fetch under protowire);
    2. the peer's engine thread serializes the chain — HBM and host
       tier, consecutive from the head — as crc-guarded KvChunks
       (``EngineRunner.submit_prefix_export``);
    3. the chunks cross the channel (``KvHandoffHeader``/``KvChunk``
       framing; ``kv.peer_fetch`` fires per chunk, docs/RESILIENCE.md);
    4. the target's engine thread validate-and-scatters them into its
       prefix cache (``submit_prefix_import`` → engine.import_prefix);
    5. the request is submitted to the target — ALWAYS, on every
       outcome. The fetch is an accelerator, never a gate: a dead peer,
       a stale registry (chain evicted between score and fetch), a torn
       stream, or an import rejection all degrade the request to plain
       recompute on its chosen replica, exactly once.

    Thread-safe: fetches start on the dispatcher thread and settle on
    runner threads; the in-flight map is the drain/abort surface
    (``pending_count`` counts toward dispatcher shutdown, ``abort``
    drops a disconnected client's request instead of submitting it into
    a closed sink)."""

    def __init__(self, channel: Optional[KVTransferChannel] = None,
                 settings: Optional[DisaggSettings] = None,
                 metrics: Optional[MetricsCollector] = None,
                 tracer=None, recorder=None, mesh_route=None):
        """``tracer``/``recorder`` (docs/OBSERVABILITY.md): each fetch
        gets a ``kv.prefix_fetch`` span parented on the trace context
        that round-tripped through the KvPrefixFetch wire fields, and
        settles a ``prefix_fetch`` timeline event whose duration feeds
        the ``peer_fetch`` phase attribution.

        ``mesh_route`` (``(target_member, peer_member) -> bool``,
        docs/FLEET.md "KV mesh"): when both the fetch target and the
        warm peer are fleet members and the registry has introduced
        that wire, the fetch is DELEGATED — the request is submitted to
        the target with a fetch hint and the target's member pulls the
        chunks directly from the peer over its own data channel. The
        bulk bytes never touch the registry."""
        self.channel = channel or InProcessChannel()
        self.settings = settings or DisaggSettings()
        self.metrics = metrics
        self.tracer = tracer
        self.recorder = recorder
        self.mesh_route = mesh_route  # distlint: ignore[DL008] — set
        # once by server wiring before traffic; read-only afterwards
        self._lock = threading.Lock()
        # request_id -> aborted? for fetches in flight (score→submit)
        self._fetching: Dict[Any, bool] = {}
        # ONE bounded wire worker (lazily started): the protowire round
        # trip per fetch is GIL-bound byte work — a thread per routed-
        # fetch request would turn a burst of fetch decisions into a
        # burst of OS threads degrading the decode latency the fetch
        # exists to protect; serializing them through one worker bounds
        # that (jobs are ms-scale; a queued fetch just settles later)
        self._wire_q: "queue.Queue" = queue.Queue()
        self._wire_thread: Optional[threading.Thread] = None

    def pending_count(self) -> int:
        with self._lock:
            return len(self._fetching)

    def abort(self, request_id) -> bool:
        """Client disconnect while the prefix fetch is in flight: flag
        it — the settle path then drops the request instead of
        submitting it into a closed sink (same semantics as a queue
        cancel: an abandoned request gets no terminal event). Returns
        True when the flag landed on an in-flight fetch."""
        with self._lock:
            if request_id in self._fetching:
                self._fetching[request_id] = True
                return True
        return False

    def fetch_then_submit(self, target, peer, req, plan) -> None:
        """Run the fetch for ``req`` per ``plan`` (a PrefixRoutePlan
        with decision "fetch"), then submit the request to ``target``.
        Called on the dispatcher thread; returns immediately — the
        pipeline advances on the peer's and target's runner threads."""
        rid = req.request_id
        ps = max(1, plan.page_size)
        t0 = time.monotonic()
        fetch_span = [None]  # set after the request half round-trips
        # a remote peer (a fleet member behind a KV data channel,
        # serving/fleet_kv.py): the request/response halves cross the
        # REAL wire, so the in-process framing round-trip and the local
        # wire-thread stage are skipped — the channel's own worker and
        # reader threads own serialization
        remote_peer = getattr(peer, "is_remote", False)
        if (remote_peer and getattr(target, "is_remote", False)
                and self.mesh_route is not None):
            # member->member mesh delegation (docs/FLEET.md "KV mesh"):
            # both ends are fleet members and the registry brokered the
            # wire — ship the fetch PLAN to the target instead of the
            # bytes through this host. The target's member dials the
            # peer directly; any failure over there degrades to plain
            # recompute on the member, exactly once, so the request is
            # never gated on the mesh. Not registered in _fetching: the
            # submit happens NOW (the hint rides the FleetSubmit frame)
            # and the member settles its own fetch metrics.
            t_member = target.engine_id.rsplit(":", 1)[0]
            p_member = peer.engine_id.rsplit(":", 1)[0]
            if t_member != p_member and self.mesh_route(t_member,
                                                        p_member):
                if self.metrics:
                    self.metrics.record_prefix_fetch(
                        "delegated", scope="mesh")
                if self.recorder is not None:
                    self.recorder.note(rid, "prefix_fetch",
                                       outcome="delegated", seconds=0.0,
                                       bytes=0, peer=peer.engine_id,
                                       target=target.engine_id)
                target.submit([req], fetch_hint={
                    "fetch_member": p_member,
                    "fetch_source_engine": getattr(
                        peer, "local_engine_id", peer.engine_id),
                    "fetch_hashes": list(plan.prefix_hashes or ()),
                    "fetch_chunk_pages": self.settings.chunk_pages,
                    "fetch_wire_quant": self.settings.wire_quant,
                })
                return
        scope = "remote" if remote_peer else "local"
        with self._lock:
            self._fetching[rid] = False

        def _settle(outcome: str, nbytes: int = 0) -> None:
            # runs on whichever thread resolved the pipeline; exactly
            # once by construction (each stage's callback fires once and
            # every failure arm returns after calling _settle). The
            # in-flight entry is popped only AFTER the submit hand-off:
            # pop-first would open a drain window where pending_count()
            # reads 0 while the request is registered nowhere yet — a
            # graceful shutdown could declare the fleet drained and stop
            # the runners under a request it should have completed.
            with self._lock:
                aborted = self._fetching.get(rid, False)
            seconds = time.monotonic() - t0
            span, fetch_span[0] = fetch_span[0], None
            if span is not None and self.tracer is not None:
                span.set(outcome=outcome, bytes=nbytes)
                self.tracer.finish(span)
            if self.recorder is not None:
                # the seconds attr feeds the peer_fetch phase window
                self.recorder.note(rid, "prefix_fetch", outcome=outcome,
                                   seconds=seconds, bytes=nbytes,
                                   peer=peer.engine_id,
                                   target=target.engine_id)
            if self.metrics:
                self.metrics.record_prefix_fetch(
                    outcome, seconds=seconds, nbytes=nbytes, scope=scope
                )
            try:
                if not aborted:
                    target.submit([req])
            finally:
                with self._lock:
                    late_abort = self._fetching.pop(rid, False)
                if late_abort and not aborted:
                    # client disconnected between the flag read and the
                    # submit: the dispatcher saw the fetch in flight and
                    # skipped its runner sweep, so forward the abort
                    target.abort(rid)

        def _on_import(ok: bool, err: Optional[str],
                       nbytes: int = 0) -> None:
            if not ok:
                logger.debug("prefix fetch for %s: import rejected by "
                             "%s (%s); recomputing", rid,
                             target.engine_id, err)
            _settle("ok" if ok else "fallback", nbytes)

        def _wire(depth: int, chunks) -> None:
            # dedicated short-lived wire thread: the protowire round
            # trip (encode + decode + per-chunk crc over the whole
            # chain) must stall NEITHER engine thread — least of all
            # the warm peer's, which the cost model picked as the fetch
            # source precisely because it is busy decoding
            try:
                # peer death mid-fetch on the wire (docs/RESILIENCE.md):
                # one hit per chunk, so nth=N drops the Nth chunk
                for _ in chunks:
                    faults.fire("kv.peer_fetch")
                req_span = getattr(req, "span", None)
                wired = self.channel.transfer_chunks(
                    rid, self.settings.wire_quant, chunks,
                    trace=(req_span.context()
                           if req_span is not None else None),
                )
            except Exception as e:  # noqa: BLE001 — channel fault domain
                logger.debug("prefix fetch for %s: channel %s failed "
                             "(%s); recomputing", rid, self.channel.name, e)
                _settle("fallback")
                return
            nbytes = sum(len(c.payload) for c in wired)
            tokens = list(req.prompt_ids[: depth * ps])
            target.submit_prefix_import(
                rid, tokens, wired,
                lambda ok, ierr: _on_import(ok, ierr, nbytes),
            )

        def _on_export(result, err: Optional[str]) -> None:
            # peer runner's thread (or the caller's, peer already down;
            # the data channel's reader thread for a remote peer): only
            # hand the serialized chunks off — no wire work here
            if result is None:
                logger.debug("prefix fetch for %s: peer %s export failed "
                             "(%s); recomputing", rid, peer.engine_id, err)
                _settle("fallback")
                return
            depth, chunks = result
            if depth <= plan.depth or not chunks:
                # registry staleness: the peer evicted the chain (or
                # holds no more of it than the target already does)
                # between the routing score and the fetch
                _settle("fallback")
                return
            if remote_peer:
                # the chunks already crossed the real wire, crc-guarded
                # per chunk — import directly (submit_prefix_import only
                # posts to the target's inbox, cheap on this thread)
                nbytes = sum(len(c.payload) for c in chunks)
                tokens = list(req.prompt_ids[: depth * ps])
                target.submit_prefix_import(
                    rid, tokens, chunks,
                    lambda ok, ierr: _on_import(ok, ierr, nbytes),
                )
                return
            self._submit_wire(lambda: _wire(depth, chunks))

        try:
            # the request half crosses the channel too, so the
            # KvPrefixFetch wire format (trace context included) is
            # exercised on every fetch; a remote peer's request half is
            # framed by the data channel itself
            req_span = getattr(req, "span", None)
            req_trace = (req_span.context()
                         if req_span is not None else None)
            if remote_peer:
                rid_w, hashes_w = rid, list(plan.prefix_hashes or ())
                chunk_pages = self.settings.chunk_pages
                wire_quant = self.settings.wire_quant
                trace_w = req_trace
            else:
                rid_w, hashes_w, chunk_pages, wire_quant, trace_w = (
                    self.channel.transfer_fetch_request(
                        rid, plan.prefix_hashes or (),
                        self.settings.chunk_pages,
                        self.settings.wire_quant,
                        trace=req_trace,
                    )
                )
        except Exception as e:  # noqa: BLE001 — channel fault domain
            logger.debug("prefix fetch for %s: request framing failed "
                         "(%s); recomputing", rid, e)
            _settle("fallback")
            return
        if self.tracer is not None and trace_w:
            # parented on the WIRE's round-tripped context — exactly
            # what a cross-host peer would parent on
            fetch_span[0] = self.tracer.start(
                "kv.prefix_fetch", parent=tuple(trace_w),
                request_id=str(rid), peer=peer.engine_id,
                target=target.engine_id,
            )
        if remote_peer:
            peer.submit_prefix_export(rid_w, hashes_w, chunk_pages,
                                      wire_quant, _on_export,
                                      trace=trace_w)
        else:
            peer.submit_prefix_export(rid_w, hashes_w, chunk_pages,
                                      wire_quant, _on_export)

    def _submit_wire(self, fn: Callable[[], None]) -> None:
        with self._lock:
            if self._wire_thread is None:
                self._wire_thread = threading.Thread(
                    target=self._wire_worker, name="peerfetch-wire",
                    daemon=True,
                )
                self._wire_thread.start()
        self._wire_q.put(fn)

    def _wire_worker(self) -> None:
        while True:
            fn = self._wire_q.get()
            try:
                fn()
            except Exception as e:  # noqa: BLE001 — job isolation (the
                # job's own failure arms settle the request; this only
                # guards the worker loop itself from dying silently)
                logger.exception("peer-fetch wire job failed: %s", e)
                if self.metrics:
                    self.metrics.record_error("disagg.peer_fetch_wire")
