"""Fault injection: a seeded, config/env-gated registry of named
failure points threaded through the serving stack (docs/RESILIENCE.md).

The resilience machinery this repo promises — worker self-restart,
crash-safe redispatch, handoff fallback, import abort — only exists
where a fault can reach it. This module makes faults reachable on
purpose: code at a crash-relevant site calls ``fire("<point>")`` and,
when a rule for that point is armed, the call raises ``InjectedFault``
(or sleeps, or returns True for flag-style points). With nothing
installed — the production default — ``fire`` is one module-global load
and a ``None`` check; no rule matching, no RNG, no allocation.

Arming is explicit and double-gated:

- config: ``faults.spec`` / ``faults.seed`` (serving/config.py), which
  the standard ``DIS_TPU_FAULTS__SPEC`` env override reaches too;
- programmatic: ``install(parse_spec(...))`` from the chaos harness
  (tools/chaos_fleet.py) and the tier-1 chaos tests.

Spec grammar (semicolon-separated rules)::

    point:key=val[,key=val][;point2:...]

    runner.inbox:nth=1              crash on the 1st inbox command
    runner.step:prob=0.01           crash ~1% of engine steps (seeded)
    disagg.chunk:nth=3,times=2      chunk 3 and 4 error on the channel
    disagg.slow_peer:prob=0.5,delay_ms=20   slow-peer stall, no error

Keys: ``nth`` (fire on the Nth hit of the point, 1-based), ``prob``
(per-hit probability from the seeded RNG), ``times`` (max fires; default
1 for ``nth`` rules, unlimited for ``prob``), ``delay_ms`` (sleep
instead of raising — the slow-peer action).

Point catalog (the authoritative list lives in docs/RESILIENCE.md):

======================  ====================================================
``runner.step``         crash mid-step: after ``engine.step()`` computed
                        outputs, before any reached a sink
``runner.inbox``        crash between submit and inbox drain: requests
                        registered in ``_inflight``, engine never saw them
``disagg.transfer``     monolithic handoff channel error
``disagg.chunk``        streamed channel error (one hit per chunk — ``nth``
                        selects the Nth chunk)
``disagg.commit``       switchover commit dropped on the channel
``disagg.slow_peer``    channel stall (pair with ``delay_ms``)
``kv.host_copy``        host-tier demotion copy fails (page drops, never
                        corrupts)
``kv.import_chunk``     import-side chunk validation failure
``kv.peer_fetch``       peer-to-peer prefix fetch dies on the wire (one
                        hit per chunk — ``nth`` drops the Nth chunk);
                        the request falls back to recompute
``kv.latent_decode``    latent payload reconstruction fails on import
                        (kind-3 decode in ``kv_cache._decode_payload``)
                        — the session aborts like any validation
                        failure and the consumer degrades exactly once
                        (handoff to decode-in-place, fetch to
                        recompute), zero page leak
``sched.health_flap``   flag: the health loop sees a healthy engine as
                        down for one sweep (restart of a live replica)
``sched.fetch_decision``  flag: force the cache_aware cost model to pick
                        FETCH when a fetch option exists (drives the
                        peer-fetch path deterministically under chaos)
``fleet.heartbeat``     a member's heartbeat is dropped before the
                        registry applies it (the partition model: the
                        member ages alive -> suspect -> dead while its
                        process lives on)
``fleet.submit``        a forwarded FleetSubmit dies — on the registry
                        host's wire (hit 1 per request) or as a worker
                        crash on receipt (the member drops the
                        connection and serves nothing); either way the
                        request takes the crash-safe redispatch path
``sched.rerole``        flag: force the RoleBalancer's rebalance signal
                        high for one evaluation (drives role flips
                        deterministically; hysteresis still bounds the
                        actual flip rate)
``fleet.kv_connect``    the lazy dial of a member's KV data channel
                        fails (serving/fleet_kv.py) — the handoff
                        degrades to decode-in-place, the fetch to
                        recompute, exactly once
``fleet.kv_chunk``      per-chunk wire death on a KV data channel (one
                        hit per KvChunk frame either direction; ``nth``
                        tears the stream at its Nth chunk) — same
                        exactly-once degradation, zero page leak
``fleet.slow_member``   delay-style (pair with ``delay_ms``): a fleet
                        member serves SLOWLY while heartbeating
                        healthily — the gray-failure model. Fired on
                        the member's serve path after the request's
                        arrival clock starts, so the member's own TTFT
                        telemetry carries the slowness the host's
                        HealthScorer demotes it on
``fleet.wire_timeout``  a send on the fleet control wire
                        (RemoteRunner.submit) or the KV data wire
                        (KvDataChannel wire worker) wedges/times out —
                        repeated hits are the scorer's wire-failure
                        eject evidence and walk the data channel's
                        circuit breaker closed → open
``fleet.kv_intro``      a KvIntro introduction frame dies on the
                        registry's control wire (serving/fleet.py
                        ``_send_intro``) — the pair is never
                        introduced, mesh fetch hints for it degrade to
                        plain recompute on the member, and the intro
                        is re-brokered when the endpoint next changes
``fleet.kv_peer_dial``  the lazy dial of a MEMBER's peer data channel
                        fails (serving/fleet_mesh.py MeshClient wires;
                        the member->member analogue of
                        ``fleet.kv_connect``) — the hinted mesh fetch
                        degrades to recompute exactly once, zero page
                        leak, and the wire's breaker walks toward open
``fleet.lease_beat``    a primary registry's RegistryLease frame is
                        dropped before the send (serving/fleet_ha.py
                        ``_tick``; one hit per peer per tick — the
                        registry-partition model). Standbys age the
                        lease alive -> suspect -> expired while the
                        primary's process lives on, then promote at a
                        higher epoch and fence it
``fleet.takeover``      a standby crashes at the start of promotion
                        (serving/fleet_ha.py ``_promote``), BEFORE the
                        epoch bump or role flip published anything —
                        takeover must be atomic-or-absent: either the
                        fleet sees the full new-epoch primary or the
                        election simply re-runs on the next tick
======================  ====================================================
"""

from __future__ import annotations

import logging
import random
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)


class FaultSpecError(ValueError):
    """Malformed fault spec string (config surfaces it as ConfigError)."""


class InjectedFault(RuntimeError):
    """Raised by an armed injection point; carries the point name so
    chaos invariant checks can tell injected failures from organic
    ones."""

    def __init__(self, point: str):
        super().__init__(f"injected fault at {point}")
        self.point = point


@dataclass
class FaultRule:
    """One armed point. ``nth`` fires on the Nth hit (1-based); ``prob``
    fires per hit from the seeded RNG; ``times`` bounds total fires
    (``None`` = unlimited). ``delay_ms`` turns the action into a stall
    instead of a raise."""

    point: str
    nth: int = 0
    prob: float = 0.0
    times: Optional[int] = None
    delay_ms: float = 0.0

    def __post_init__(self) -> None:
        if self.nth < 0:
            raise FaultSpecError(f"{self.point}: nth must be >= 1")
        if not (0.0 <= self.prob <= 1.0):
            raise FaultSpecError(f"{self.point}: prob must be in [0, 1]")
        if self.nth == 0 and self.prob == 0.0:
            raise FaultSpecError(
                f"{self.point}: rule needs nth=N or prob=p to ever fire"
            )
        if self.times is None:
            # an nth rule is a one-shot by default; a prob rule recurs
            self.times = 1 if self.nth else None


class FaultSet:
    """Armed rules + seeded RNG + fire log. Thread-safe: injection
    points fire from the runner threads, the dispatcher, the disagg
    worker, and the health loop concurrently; hit counting and RNG draws
    happen under one lock (the armed path is diagnostic machinery — a
    lock there costs nothing real, and unseeded racing draws would make
    "same seed, same faults" a lie)."""

    def __init__(self, rules: List[FaultRule], seed: int = 0):
        self.seed = seed
        self._rules: Dict[str, FaultRule] = {}
        for r in rules:
            if r.point in self._rules:
                raise FaultSpecError(f"duplicate rule for point {r.point}")
            self._rules[r.point] = r
        self._rng = random.Random(seed)
        self._hits: Dict[str, int] = {}
        self._fired: Dict[str, int] = {}
        self._lock = threading.Lock()
        #: (point, hit_number) of every fire, for harness introspection
        self.log: List[Tuple[str, int]] = []

    def _trigger(self, point: str) -> Optional[FaultRule]:
        """One hit of ``point``; returns the rule when it triggers."""
        rule = self._rules.get(point)
        if rule is None:
            return None
        with self._lock:
            hits = self._hits.get(point, 0) + 1
            self._hits[point] = hits
            fired = self._fired.get(point, 0)
            if rule.times is not None and fired >= rule.times:
                return None
            trigger = (rule.nth and hits >= rule.nth) or (
                rule.prob and self._rng.random() < rule.prob
            )
            if not trigger:
                return None
            self._fired[point] = fired + 1
            self.log.append((point, hits))
        logger.debug("fault injected at %s (hit %d)", point, hits)
        return rule

    def fire(self, point: str) -> bool:
        """One hit of a raise-style point. Raises InjectedFault when an
        armed rule triggers; sleeps instead for a delay-rule (returning
        True); returns False when unarmed or not triggered."""
        rule = self._trigger(point)
        if rule is None:
            return False
        if rule.delay_ms > 0:
            # Event.wait, not time.sleep: the injected stall stays
            # interruptible-shaped like every other serving-spine wait
            # (distlint DL001)
            threading.Event().wait(rule.delay_ms / 1000.0)
            return True
        raise InjectedFault(point)

    def flag(self, point: str) -> bool:
        """One hit of a FLAG-style point (e.g. ``sched.health_flap``):
        never raises — the caller interprets True as "the condition
        fired" (a delay-rule still sleeps first)."""
        rule = self._trigger(point)
        if rule is None:
            return False
        if rule.delay_ms > 0:
            threading.Event().wait(rule.delay_ms / 1000.0)
        return True

    def fired_count(self, point: Optional[str] = None) -> int:
        with self._lock:
            if point is not None:
                return self._fired.get(point, 0)
            return sum(self._fired.values())


def parse_spec(spec: str, seed: int = 0) -> FaultSet:
    """Parse the spec grammar (module docstring) into a FaultSet.
    Raises FaultSpecError on malformed input."""
    rules: List[FaultRule] = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        if ":" not in part:
            raise FaultSpecError(
                f"rule {part!r} missing ':' (want point:key=val,...)"
            )
        point, _, kvs = part.partition(":")
        point = point.strip()
        if not point:
            raise FaultSpecError(f"rule {part!r} has an empty point name")
        kwargs: Dict[str, float] = {}
        for kv in kvs.split(","):
            kv = kv.strip()
            if not kv:
                continue
            if "=" not in kv:
                raise FaultSpecError(f"{point}: {kv!r} is not key=val")
            key, _, val = kv.partition("=")
            key = key.strip()
            if key not in ("nth", "prob", "times", "delay_ms"):
                raise FaultSpecError(
                    f"{point}: unknown key {key!r} "
                    "(known: nth, prob, times, delay_ms)"
                )
            try:
                kwargs[key] = float(val)
            except ValueError:
                raise FaultSpecError(
                    f"{point}: {key}={val!r} is not a number"
                ) from None
        rules.append(FaultRule(
            point=point,
            nth=int(kwargs.get("nth", 0)),
            prob=kwargs.get("prob", 0.0),
            times=int(kwargs["times"]) if "times" in kwargs else None,
            delay_ms=kwargs.get("delay_ms", 0.0),
        ))
    if not rules:
        raise FaultSpecError(f"fault spec {spec!r} contains no rules")
    return FaultSet(rules, seed=seed)


# -- module-level registry (the injection points' view) ---------------------

_active: Optional[FaultSet] = None
# arm/disarm observers (serving/flightrec.py): flight recorders note
# fault-injection hops into their fleet-event windows so a postmortem
# timeline shows WHEN the chaos lever moved. A LIST, not a slot: the
# chaos fleet topology runs two InferenceServers (registry host +
# member) in one interpreter, and the host's recorder must not lose the
# events to the member's. Never on the fire() hot path — only
# install/clear transitions report.
_observers: List = []


def add_observer(cb) -> None:
    """Register ``cb(event, **attrs)``; called on install/clear only.
    Pair with ``remove_observer`` (server shutdown) or the registry
    grows across server lifetimes."""
    _observers.append(cb)


def remove_observer(cb) -> None:
    try:
        _observers.remove(cb)
    except ValueError:
        pass


def install(faults: Optional[FaultSet]) -> None:
    """Arm a FaultSet process-wide (None = disarm). The chaos harness
    installs a fresh seeded set per scenario iteration."""
    global _active
    _active = faults
    if faults is not None:
        logger.warning(
            "fault injection ARMED (seed=%d, points: %s) — never in "
            "production", faults.seed, ", ".join(sorted(faults._rules)),
        )
    for cb in list(_observers):
        try:
            if faults is not None:
                cb("faults_armed", seed=faults.seed,
                   points=sorted(faults._rules))
            else:
                cb("faults_cleared")
        except Exception:  # noqa: BLE001 — observability must not gate
            # the chaos lever
            logger.debug("fault observer failed", exc_info=True)


def clear() -> None:
    install(None)


def active() -> Optional[FaultSet]:
    return _active


def fire(point: str) -> bool:
    """A raise-style injection point: no-op (one global load + None
    check) unless a FaultSet is installed AND has a rule for ``point``.
    May raise InjectedFault, or sleep and return True for delay rules."""
    faults = _active
    if faults is None:
        return False
    return faults.fire(point)


def flag(point: str) -> bool:
    """A flag-style injection point (never raises): True when an armed
    rule triggered — the call site applies the condition itself (e.g.
    the health loop treating a live replica as down)."""
    faults = _active
    if faults is None:
        return False
    return faults.flag(point)
