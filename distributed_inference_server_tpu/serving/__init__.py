"""Serving layer: HTTP transport, admission batching, scheduling, metrics.

The TPU-native counterpart of the reference's ``crates/server`` (stub;
spec'd ``design.md:139-155,227-307,449-491``) — see SURVEY.md §2.2 S1-S9.
"""

from distributed_inference_server_tpu.serving.batcher import (
    AdmissionBatch,
    AdmissionBatcher,
    BatcherConfig,
)
from distributed_inference_server_tpu.serving.disagg import (
    DisaggController,
    DisaggSettings,
    InProcessChannel,
    KVTransferChannel,
    ProtowireChannel,
    parse_roles,
)
from distributed_inference_server_tpu.serving.dispatcher import Dispatcher
from distributed_inference_server_tpu.serving.handler import InferenceHandler
from distributed_inference_server_tpu.serving.metrics import (
    EngineStatus,
    MetricsCollector,
    MetricsSnapshot,
)
from distributed_inference_server_tpu.serving.runner import (
    EngineRunner,
    ResultSink,
    ServerRequest,
)
from distributed_inference_server_tpu.serving.scheduler import (
    AdaptiveScheduler,
    SchedulingStrategy,
    choose_engine,
)
from distributed_inference_server_tpu.serving.server import InferenceServer
from distributed_inference_server_tpu.serving.streamer import (
    CollectingSink,
    StreamingSink,
    sse_encode,
)

__all__ = [
    "AdmissionBatch",
    "AdmissionBatcher",
    "BatcherConfig",
    "DisaggController",
    "DisaggSettings",
    "InProcessChannel",
    "KVTransferChannel",
    "ProtowireChannel",
    "parse_roles",
    "Dispatcher",
    "InferenceHandler",
    "EngineStatus",
    "MetricsCollector",
    "MetricsSnapshot",
    "EngineRunner",
    "ResultSink",
    "ServerRequest",
    "AdaptiveScheduler",
    "SchedulingStrategy",
    "choose_engine",
    "InferenceServer",
    "CollectingSink",
    "StreamingSink",
    "sse_encode",
]
