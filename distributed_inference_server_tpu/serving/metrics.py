"""Metrics collection: Prometheus registry + `/server/stats` snapshot.

TPU-native realization of the reference's spec'd ``MetricsCollector`` trait
and ``MetricsSnapshot`` (``design.md:466-491`` [spec]; behavior
``requirements.md:118-122``): request latency by endpoint/status, batch size
and padding ratio, inference token/duration, time-to-first-token, cache hit
rate, queue depth, and per-engine status, exported both as Prometheus text
(GET /metrics) and as a JSON snapshot (GET /server/stats).

Thread-safe: the engine-runner thread, dispatcher thread, and asyncio
handlers all record into the same collector.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Deque, Dict, List, Optional, Tuple

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)

from distributed_inference_server_tpu.serving.teledigest import (
    PerfTelemetry,
    window_stats,
)

# rolling windows for the snapshot's derived rates
_TOKEN_WINDOW_S = 10.0
_TTFT_WINDOW = 1024
#: distinct SLO tenant label values before new tenants fold into
#: "other" — tenant is a client-chosen string and counter series are
#: forever, so the label set must be bounded (unlike the tenant GAUGE,
#: which removes drained series)
_SLO_TENANT_CAP = 32


@dataclass(frozen=True)
class EngineStatus:
    """Health/load of one engine replica (reference ``WorkerStatus``,
    design.md:283-296 [spec])."""

    engine_id: str
    healthy: bool
    active_requests: int
    waiting_requests: int
    total_processed: int
    # raw page occupancy (pages not on the free list, CACHED prefix pages
    # included); pages_cached below says how much of it is reclaimable-
    # on-demand prefix cache, so consumers can score live pressure as
    # used - cached (scheduler memory_aware, degradation ladder)
    memory_used_pages: int = 0
    memory_total_pages: int = 0
    pages_cached: int = 0
    # disaggregated prefill/decode serving (serving/disagg.py): which
    # part of the pipeline this replica serves
    role: str = "unified"
    # speculative-decoding stats (Req 12.4): acceptance_rate,
    # estimated_speedup, enabled, num_draft_tokens — None when no draft
    # model is configured
    speculation: Any = None
    # cache-aware routing (ISSUE 5): rolling digest of cached prefix
    # chains (first-K page content hashes, kv_cache.chain_hashes key
    # space) and the page size the hashes were computed with. Not
    # serialized — in-process routing state only.
    prefix_digest: Any = None
    page_size: int = 0
    # chain depth the digest covers (cache.digest_depth): the scheduler
    # hashes prompts to the fleet's published depth, so a deeper digest
    # widens the window the three-way cost model can score (and peer-
    # fetch) instead of flattening matches past page 8. In-process only,
    # like the digest itself.
    digest_depth: int = 0
    # host-tier prefix cache occupancy (engine.host_tier_stats()); None
    # when the tier is off
    host_tier: Any = None
    # latent page codec (engine.latent_stats(); docs/CACHING.md "Latent
    # KV pages"): rank / encoded_bytes / saved_bytes — None when no
    # codec is calibrated
    latent: Any = None
    # ragged mixed-batch stepping (engine.mixed_stats(); docs/PERF.md):
    # steps / prefill_tokens / decode_tokens / batch_density /
    # prefill_frac — None when engine.mixed_step_tokens is 0
    mixed: Any = None
    # run-to-completion looped decode blocks (engine.loop_stats();
    # docs/PERF.md "Kernel Looping"): blocks / steps / decode_tokens /
    # exits / cap / cap_frac — None when engine.loop_to_completion is
    # off
    loop: Any = None
    # fleet control plane (serving/fleet.py): True for a RemoteRunner
    # proxy's status reconstructed from a member heartbeat. Remote
    # replicas take routed admissions; without a data plane they are
    # excluded from paths that need to move KV bytes (handoff targets,
    # peer-fetch sources) and always from health-loop restarts.
    remote: bool = False
    # fleet KV data plane (serving/fleet_kv.py): True when the member
    # behind a remote proxy carries a dialed-on-demand KV data channel,
    # making it a legal handoff target and fetch source. In-process
    # routing state only (never serialized — the member cannot know).
    data_plane: bool = False
    # gray-failure verdict (serving/health.py HealthScorer): "healthy" |
    # "degraded" | "ejected", stamped by AdaptiveScheduler.statuses().
    # Routing prefers healthy replicas, falls back to degraded, and
    # admits ejected ones only when nothing else exists (Property 20).
    # In-process routing state only — each process scores its own view.
    health: str = "healthy"

    def to_dict(self) -> Dict[str, Any]:
        d = {
            "engine_id": self.engine_id,
            "healthy": self.healthy,
            "active_requests": self.active_requests,
            "waiting_requests": self.waiting_requests,
            "total_processed": self.total_processed,
            "memory_used_pages": self.memory_used_pages,
            "memory_total_pages": self.memory_total_pages,
            "pages_cached": self.pages_cached,
            "role": self.role,
        }
        if self.speculation is not None:
            d["speculation"] = self.speculation
        if self.host_tier is not None:
            d["host_tier"] = self.host_tier
        if self.latent is not None:
            d["latent"] = self.latent
        if self.mixed is not None:
            d["mixed"] = self.mixed
        if self.loop is not None:
            d["loop"] = self.loop
        if self.remote:
            d["remote"] = True
            if self.data_plane:
                d["data_plane"] = True
        if self.health != "healthy":
            d["health"] = self.health
        return d


@dataclass(frozen=True)
class MetricsSnapshot:
    """JSON stats snapshot (reference ``MetricsSnapshot``,
    design.md:479-491 [spec])."""

    total_requests: int
    active_requests: int
    tokens_per_second: float
    average_ttft_ms: float
    average_latency_ms: float
    p99_latency_ms: float
    average_batch_size: float
    cache_hit_rate: float
    queue_depth: int
    worker_statuses: Tuple[EngineStatus, ...] = ()
    uptime_seconds: float = 0.0
    # disaggregated-serving block (None when no handoff has happened and
    # every engine is unified): handoff outcome counts + bytes moved
    disagg: Optional[Dict[str, Any]] = None
    # prefix-cache block (ISSUE 5 + the allocator counters that never
    # reached /server/stats before): hit/miss/eviction totals, per-tier
    # prefix hits, and host-tier reload cost
    cache: Optional[Dict[str, Any]] = None
    # resilience block (docs/RESILIENCE.md; None until any restart,
    # redispatch, or queue expiry happened): per-engine restart attempts,
    # redispatch outcomes, and queue-timeout expiries
    resilience: Optional[Dict[str, Any]] = None
    # observability block (docs/OBSERVABILITY.md; None until any span
    # was dropped or any request's phases were attributed): span drops
    # by reason + cumulative phase-attribution sums
    tracing: Optional[Dict[str, Any]] = None

    def to_dict(self) -> Dict[str, Any]:
        out = {
            "total_requests": self.total_requests,
            "active_requests": self.active_requests,
            "tokens_per_second": round(self.tokens_per_second, 3),
            "average_ttft_ms": round(self.average_ttft_ms, 3),
            "average_latency_ms": round(self.average_latency_ms, 3),
            "p99_latency_ms": round(self.p99_latency_ms, 3),
            "average_batch_size": round(self.average_batch_size, 3),
            "cache_hit_rate": round(self.cache_hit_rate, 4),
            "queue_depth": self.queue_depth,
            "worker_statuses": [w.to_dict() for w in self.worker_statuses],
            "uptime_seconds": round(self.uptime_seconds, 1),
        }
        if self.disagg is not None:
            out["disagg"] = self.disagg
        if self.cache is not None:
            out["cache"] = self.cache
        if self.resilience is not None:
            out["resilience"] = self.resilience
        if self.tracing is not None:
            out["tracing"] = self.tracing
        return out


class MetricsCollector:
    """Records serving metrics; renders Prometheus text and JSON snapshots."""

    def __init__(self, registry: Optional[CollectorRegistry] = None):
        self.registry = registry or CollectorRegistry()
        self._lock = threading.Lock()
        self._started_at = time.monotonic()

        r = self.registry
        self.request_latency = Histogram(
            "request_latency_seconds",
            "End-to-end request latency",
            ["endpoint", "status"],
            registry=r,
            buckets=(0.005, 0.02, 0.05, 0.1, 0.2, 0.5, 1, 2, 5, 10, 30),
        )
        self.batch_size = Histogram(
            "batch_size",
            "Requests per dispatched admission batch",
            registry=r,
            buckets=(1, 2, 4, 8, 16, 32, 64),
        )
        self.batch_padding_ratio = Histogram(
            "batch_padding_ratio",
            "Padding overhead per batch (padded/real - 1)",
            registry=r,
            buckets=(0.0, 0.05, 0.1, 0.25, 0.5, 1.0, 2.0),
        )
        self.tokens_generated = Counter(
            "tokens_generated_total", "Output tokens generated", registry=r
        )
        self.inference_seconds = Counter(
            "inference_seconds_total",
            "Wall-clock seconds spent in engine steps",
            registry=r,
        )
        self.ttft = Histogram(
            "time_to_first_token_seconds",
            "Admission to first streamed token",
            registry=r,
            buckets=(0.01, 0.05, 0.1, 0.2, 0.5, 1, 2, 5),
        )
        self.cache_hits = Counter(
            "kv_cache_hits_total", "Prefix-cache page hits", registry=r
        )
        self.cache_misses = Counter(
            "kv_cache_misses_total", "Prefix-cache misses", registry=r
        )
        self.cache_evictions = Counter(
            "kv_cache_evictions_total", "LRU page evictions", registry=r
        )
        # tiered prefix cache (ISSUE 5; engine/kv_cache.py HostTier):
        # page-granular prefix hits by tier — "hbm" pages were shared in
        # place, "host" pages were re-seated from the host-RAM tier
        # instead of recomputing their prefill
        self.prefix_hits = Counter(
            "kv_prefix_hits_total",
            "Prefix-cache page hits by tier (hbm = shared in place, "
            "host = re-seated from the host-RAM tier)", ["tier"],
            registry=r,
        )
        self.prefix_reload = Histogram(
            "kv_prefix_reload_seconds",
            "Host-side time to re-seat a host-tier prefix match into "
            "HBM (decode + batched scatter dispatch, per prefill)",
            registry=r,
            buckets=(0.0005, 0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                     0.5, 1),
        )
        # fleet-wide prefix sharing (docs/CACHING.md): peer-to-peer
        # prefix fetch traffic and the cache_aware three-way route
        # decisions that drive it
        self.prefix_fetches = Counter(
            "kv_prefix_fetch_total",
            "Peer-to-peer prefix fetches by outcome (ok = fetched pages "
            "seated on the cold replica, fallback = peer death / stale "
            "registry / torn stream degraded the request to recompute) "
            "and scope (local = in-process peer, remote = a fleet "
            "member over its KV data channel)",
            ["outcome", "scope"], registry=r,
        )
        self.prefix_fetch_bytes = Counter(
            "kv_prefix_fetch_bytes_total",
            "Serialized KV bytes moved by peer prefix fetches "
            "(post wire-quantization), by peer scope (local | remote)",
            ["scope"], registry=r,
        )
        self.prefix_fetch_latency = Histogram(
            "kv_prefix_fetch_seconds",
            "Peer prefix fetch latency (route decision to request "
            "submission on the target replica)",
            registry=r,
            buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                     1, 2),
        )
        self.prefix_routes = Counter(
            "kv_prefix_route_total",
            "cache_aware route decisions (warm = routed to a matched "
            "replica, fetch = peer-fetch onto a cold replica, recompute "
            "= no usable match)",
            ["decision"], registry=r,
        )
        self.host_tier_bytes_g = Gauge(
            "kv_host_tier_bytes",
            "Bytes resident in the host-RAM prefix-cache tier",
            ["engine_id"], registry=r,
        )
        self.host_tier_pages_g = Gauge(
            "kv_host_tier_pages",
            "Pages resident in the host-RAM prefix-cache tier",
            ["engine_id"], registry=r,
        )
        # latent page codec (docs/CACHING.md "Latent KV pages"):
        # serialized KV payload bytes by encoding kind across all four
        # KV paths (disagg handoff, host-tier offload, peer prefix
        # fetch, fleet KV data plane)
        self.kv_payload_bytes = Counter(
            "kv_payload_bytes_total",
            "Serialized KV payload bytes moved, by encoding kind (raw | "
            "int8 | qpool | latent | latent_int8), across handoff, "
            "host-tier offload, prefix fetch, and the fleet KV data "
            "plane",
            ["kind"], registry=r,
        )
        # ragged mixed-batch stepping (engine/engine.py _mixed_step;
        # docs/PERF.md): tokens consumed by mixed dispatches per kind,
        # and how full the packed MXU tiles actually ran
        self.mixed_step_tokens = Counter(
            "engine_mixed_step_tokens",
            "Tokens consumed by ragged mixed-step dispatches (prefill = "
            "packed prefill-chunk tokens, decode = advanced decode rows)",
            ["kind"], registry=r,
        )
        self.mixed_density = Gauge(
            "engine_mixed_batch_density",
            "Rolling mean of real packed tokens / mixed_step_tokens per "
            "mixed dispatch (1.0 = every MXU tile slot carried a real "
            "token)", ["engine_id"],
            registry=r,
        )
        # run-to-completion looped decode blocks (engine/engine.py
        # _loop_step; docs/PERF.md "Kernel Looping"): device iterations
        # executed inside looped blocks, and why each block stopped
        self.loop_steps_total = Counter(
            "engine_loop_steps_total",
            "Device iterations executed inside run-to-completion looped "
            "decode blocks (each iteration advances every active row "
            "one token, or one speculative round, with no host sync)",
            registry=r,
        )
        self.loop_exit_total = Counter(
            "engine_loop_exit_total",
            "Looped decode-block row exits by stop condition (eos | "
            "budget | pages = device free-list exhausted | cap = "
            "loop_max_steps iteration cap)",
            ["reason"], registry=r,
        )
        self.queue_depth_g = Gauge(
            "queue_depth", "Queued requests by priority", ["priority"], registry=r
        )
        self.active_requests_g = Gauge(
            "active_requests", "Requests admitted and not yet finished", registry=r
        )
        self.spec_acceptance = Gauge(
            "speculation_acceptance_rate",
            "Rolling draft-token acceptance rate (Req 12.3)", ["engine_id"],
            registry=r,
        )
        self.spec_speedup = Gauge(
            "speculation_estimated_speedup",
            "Tokens emitted per target forward (>= 1)", ["engine_id"],
            registry=r,
        )
        self.spec_enabled = Gauge(
            "speculation_enabled",
            "1 while speculation is active (auto-disables below threshold, "
            "Req 12.5)", ["engine_id"],
            registry=r,
        )
        self.engine_up = Gauge(
            "engine_up", "1 if the engine replica is healthy", ["engine_id"],
            registry=r,
        )
        # disaggregated prefill/decode serving (serving/disagg.py)
        self.handoff_latency = Histogram(
            "kv_handoff_latency_seconds",
            "Prefill->decode KV handoff latency (export to resume)",
            registry=r,
            buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1,
                     2, 5),
        )
        self.handoff_bytes = Counter(
            "kv_handoff_bytes_total",
            "Serialized KV bytes moved over the handoff channel "
            "(post wire-quantization)",
            registry=r,
        )
        # the decode pause the MIGRATED sequence observes (switchover to
        # resume) — distinct from kv_handoff_latency_seconds, which is
        # end-to-end and, under the streamed export, mostly overlapped
        # with the sequence's own decoding
        self.handoff_stall = Histogram(
            "kv_handoff_stall_seconds",
            "Decode pause observed by the migrated sequence",
            registry=r,
            buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1,
                     2, 5),
        )
        self.handoff_chunks = Counter(
            "kv_handoff_chunks_total",
            "KvChunk frames moved over the handoff channel, by target "
            "scope (local = in-process decode replica, remote = a fleet "
            "member over its KV data channel)",
            ["scope"], registry=r,
        )
        self.handoffs = Counter(
            "kv_handoff_total",
            "KV handoffs by outcome (ok | fallback | retry)", ["outcome"],
            registry=r,
        )
        self.engines_by_role = Gauge(
            "engines_by_role",
            "Engine replicas per disaggregation role", ["role"],
            registry=r,
        )
        # swallowed-failure visibility (distlint DL004, docs/LINTS.md):
        # isolation boundaries that deliberately eat exceptions count them
        # here so "quietly degrading" is a queryable condition, not a
        # soak-test discovery
        self.errors_total = Counter(
            "errors_total",
            "Errors absorbed at isolation boundaries, by site", ["site"],
            registry=r,
        )
        # resilience surfaces (docs/RESILIENCE.md): restart churn and the
        # crash-safe redispatch path must be queryable — a crash-looping
        # engine or an exhausted redispatch budget is an operator page,
        # not a log line
        self.engine_restarts = Counter(
            "engine_restarts_total",
            "Engine replica restart attempts by the health loop",
            ["engine_id"], registry=r,
        )
        self.redispatches = Counter(
            "requests_redispatched_total",
            "Zero-token in-flight requests moved off a dead engine "
            "(ok = resubmitted to a healthy replica, exhausted = attempt "
            "budget or healthy capacity ran out)", ["outcome"],
            registry=r,
        )
        self.requests_expired = Counter(
            "requests_expired_total",
            "Queued requests expired by the dispatcher sweep before "
            "dispatch (queue_timeout)",
            registry=r,
        )
        # gray-failure defense (serving/health.py; docs/RESILIENCE.md
        # "Gray failures and overload"): deadline-aware admission
        # shedding, latency-scored health transitions, circuit-breaker
        # flips, and retry-budget exhaustion
        self.requests_shed = Counter(
            "requests_shed_total",
            "Requests shed at admission by deadline-aware control "
            "(deadline = the windowed queue-wait estimate blows the "
            "tenant's SLO-derived deadline, brownout = a low-weight "
            "tenant shed early as the backlog grows); tenants beyond "
            "a bounded label set fold into \"other\"",
            ["tenant", "reason"], registry=r,
        )
        self.engine_health = Gauge(
            "engine_health_state",
            "Latency-scored health verdict per engine "
            "(0 healthy, 1 degraded, 2 ejected)",
            ["engine_id"], registry=r,
        )
        self.health_transitions = Counter(
            "health_transitions_total",
            "Health-state transitions applied by the scorer, by the "
            "state entered (healthy | degraded | ejected)",
            ["state"], registry=r,
        )
        self.breaker_transitions = Counter(
            "fleet_breaker_transitions_total",
            "KV data-channel circuit-breaker transitions, by the state "
            "entered (closed | open | half_open)",
            ["state"], registry=r,
        )
        self.retry_denied = Counter(
            "retry_budget_exhausted_total",
            "Retries declined by the shared windowed retry budget, by "
            "consumer site (redispatch | handoff_retry | kv_reconnect) "
            "— each denial degraded to its exactly-once fallback",
            ["site"], registry=r,
        )
        # fleet control plane (serving/fleet.py; docs/FLEET.md): member
        # liveness, heartbeat ingest outcomes, role rebalancing, and
        # per-tenant queue occupancy
        self.fleet_members = Gauge(
            "fleet_members",
            "Fleet members by registry state (alive = beating, suspect "
            "= missed beats past fleet.suspect_after_s, dead = aged out "
            "or connection lost)", ["state"],
            registry=r,
        )
        self.fleet_heartbeats = Counter(
            "fleet_heartbeats_total",
            "Heartbeat ingest outcomes (ok = applied, rejoin = revived "
            "a suspect/dead member, dropped = lost before the registry "
            "— the fleet.heartbeat partition fault)", ["outcome"],
            registry=r,
        )
        self.fleet_reroles = Counter(
            "fleet_reroles_total",
            "Dynamic role flips by the RoleBalancer (to_prefill = "
            "prompt-queue pressure crossed fleet.rerole_high_ratio, "
            "to_unified = it drained below fleet.rerole_low_ratio)",
            ["direction"], registry=r,
        )
        self.queue_tenant_depth = Gauge(
            "queue_tenant_depth",
            "Queued requests per tenant (per-tenant fair admission, "
            "queue.tenant_fairness)", ["tenant"],
            registry=r,
        )
        # observability spine (docs/OBSERVABILITY.md): spans lost before
        # an operator could see them — ring eviction, exporter failure,
        # or fleet-wire buffer overflow — and the flight recorder's
        # derived per-request phase attribution
        self.trace_drops = Counter(
            "trace_spans_dropped_total",
            "Finished spans dropped before reaching an operator (ring = "
            "evicted from the bounded in-memory ring, exporter = an "
            "exporter failed or overflowed, wire = the fleet span buffer "
            "overflowed before shipping)", ["reason"],
            registry=r,
        )
        self.request_phases = Histogram(
            "request_phase_seconds",
            "Per-request wall-clock attributed to lifecycle phases by "
            "the flight recorder (serving/flightrec.py): queue_wait | "
            "prefill | peer_fetch | handoff_stall | decode | detok",
            ["phase"], registry=r,
            buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1,
                     2, 5, 10, 30),
        )
        # engine step clock (docs/OBSERVABILITY.md "Performance
        # telemetry"): host-side wall time, dispatch counts, and tokens
        # per dispatch kind, delta-reported by the runner from the
        # engine's cumulative counters (like the `mixed` block)
        self.step_seconds = Counter(
            "engine_step_seconds_total",
            "Host wall-clock seconds attributed to engine dispatches by "
            "kind (prefill = chunk quantum, decode_block = K-step block "
            "launch + reconcile, mixed = ragged mixed dispatch)",
            ["engine_id", "kind"], registry=r,
        )
        self.step_dispatches = Counter(
            "engine_step_dispatches_total",
            "Engine dispatches by kind (the step clock's denominator)",
            ["engine_id", "kind"], registry=r,
        )
        self.step_tokens = Counter(
            "engine_step_tokens_total",
            "Tokens moved per dispatch kind (prefill = prompt tokens "
            "computed, decode_block/mixed = sampled tokens reconciled)",
            ["engine_id", "kind"], registry=r,
        )
        self.step_events = Counter(
            "engine_step_events_total",
            "Step-loop pressure events (cache_full = allocation failed "
            "and the step degraded, preempt = youngest sequence evicted, "
            "reclaim = sliding-window pages released, retrace = a new "
            "program geometry compiled mid-serving)",
            ["engine_id", "event"], registry=r,
        )
        # SLO / goodput accounting (serving/teledigest.py SloSettings;
        # fed by flightrec.finish() from the exact phase partition)
        self.slo_requests = Counter(
            "slo_requests_total",
            "Finished requests with an applicable SLO, by tenant and "
            "verdict (ok | violated); tenants beyond a bounded label "
            "set fold into \"other\"",
            ["tenant", "verdict"], registry=r,
        )
        self.slo_goodput = Counter(
            "slo_goodput_tokens_total",
            "Output tokens of requests that MET their SLO (goodput; "
            "compare against tokens_generated_total for the waste share)",
            ["tenant"], registry=r,
        )
        # fleet telemetry federation (serving/fleet.py ingest +
        # serving/remote_runner.py ship): frame traffic accounting
        self.fleet_telemetry_frames = Counter(
            "fleet_telemetry_frames_total",
            "FleetTelemetry frames by outcome (sent/failed on a worker, "
            "ingested on the registry host)",
            ["outcome"], registry=r,
        )
        # per-member series merged from ingested member digests: the
        # registry host's /metrics answers \"which member is burning "
        # "the fleet p99\" without touching any member
        self.fleet_member_step_tokens = Gauge(
            "fleet_member_step_tokens",
            "A member's cumulative step-clock tokens by dispatch kind "
            "(from its last FleetTelemetry frame)",
            ["member", "kind"], registry=r,
        )
        self.fleet_member_ttft_p99 = Gauge(
            "fleet_member_ttft_p99_ms",
            "A member's windowed TTFT p99 (ms) from its last shipped "
            "digest (0 until it has a windowed sample)",
            ["member"], registry=r,
        )
        # KV mesh (serving/fleet_mesh.py; docs/FLEET.md "KV mesh"):
        # learned per-wire transfer rates and intro-broker traffic.
        # src/dst are member ids ("registry" = this host); dead
        # members' series are removed (tenant-gauge policy)
        self.kv_wire_rate = Gauge(
            "fleet_kv_wire_rate_bytes_per_s",
            "Learned KV wire transfer rate over the configured window "
            "(fleet.kv_rate_window_s); absent while the wire is cold "
            "(it then prices at the fleet.kv_rate_prior constant)",
            ["src", "dst"], registry=r,
        )
        self.kv_intros = Counter(
            "fleet_kv_intro_total",
            "KvIntro broker sends by outcome (sent | gone = retraction "
            "| dropped = injected fleet.kv_intro fault | failed = "
            "member session wire error)",
            ["outcome"], registry=r,
        )
        # registry HA (serving/fleet_ha.py; docs/FLEET.md "Registry
        # HA"): lease-fenced warm-standby control plane. Role is a 0/1
        # gauge per role label (both series always published so an
        # alert on absent(fleet_registry_role{role="primary"}) works);
        # epoch is the fencing token members compare control frames
        # against.
        self.registry_role = Gauge(
            "fleet_registry_role",
            "This registry's HA role as a 0/1 gauge per role label "
            "(primary | standby); exactly one series is 1 at a time",
            ["role"], registry=r,
        )
        self.registry_takeovers = Counter(
            "fleet_registry_takeovers_total",
            "Registry HA role transitions by reason (lease_expired = "
            "standby promoted after the primary lease aged out | "
            "fenced = a primary demoted on seeing a higher-epoch or "
            "lower-index peer lease)",
            ["reason"], registry=r,
        )
        self.registry_epoch = Gauge(
            "fleet_registry_epoch",
            "This registry's current control epoch (the fencing token "
            "stamped on FleetSubmit/KvIntro frames; members reject "
            "control from lower epochs)",
            registry=r,
        )

        # windowed performance digests (serving/teledigest.py): the
        # sliding-epoch store behind GET /server/perf, the snapshot's
        # windowed p99, and the member half of FleetTelemetry frames
        self.perf = PerfTelemetry()

        # snapshot internals
        self._total_requests = 0
        self._active_requests = 0
        self._token_events: Deque[Tuple[float, int]] = deque()
        self._ttfts_ms: Deque[float] = deque(maxlen=_TTFT_WINDOW)
        self._batch_sizes: Deque[int] = deque(maxlen=_TTFT_WINDOW)
        self._cache_hits = 0
        self._cache_misses = 0
        self._cache_evictions = 0
        self._prefix_hits_hbm = 0
        self._prefix_hits_host = 0
        self._reload_sum = 0.0
        self._reload_count = 0
        self._prefix_fetches: Dict[str, int] = {}
        self._prefix_fetch_bytes = 0
        self._fetch_sum = 0.0
        self._fetch_count = 0
        self._prefix_routes: Dict[str, int] = {}
        self._payload_bytes: Dict[str, int] = {}
        self._handoffs: Dict[str, int] = {}
        self._handoff_bytes = 0
        self._handoff_chunks = 0
        self._stall_sum = 0.0
        self._stall_count = 0
        self._engine_restarts: Dict[str, int] = {}
        self._redispatches: Dict[str, int] = {}
        self._requests_expired = 0
        # gray-failure surfaces (serving/health.py): shed counts keyed
        # (tenant, reason) with the tenant label bounded like the SLO
        # counters; health/breaker transition and retry-denial tallies
        self._requests_shed: Dict[Tuple[str, str], int] = {}
        self._shed_tenants: set = set()
        self._health_transitions: Dict[str, int] = {}
        self._breaker_transitions: Dict[str, int] = {}
        self._retry_denied: Dict[str, int] = {}
        self._fleet_heartbeats: Dict[str, int] = {}
        self._fleet_reroles: Dict[str, int] = {}
        self._kv_intros: Dict[str, int] = {}
        self._registry_takeovers: Dict[str, int] = {}
        self._tenants_seen: set = set()
        self._trace_drops: Dict[str, int] = {}
        self._phase_sums: Dict[str, float] = {}
        self._phase_requests = 0
        # SLO accounting (teledigest.slo_verdict via flightrec.finish)
        self._slo_counts: Dict[str, Dict[str, int]] = {}
        self._slo_goodput: Dict[str, int] = {}
        # member -> step-token kinds published, so a pruned member's
        # gauge series can be REMOVED (dead members must not keep
        # reporting their last p99 as live, and per-restart member ids
        # must not grow the label set forever — tenant-gauge policy)
        self._member_kinds: Dict[str, set] = {}

    # -- recording ---------------------------------------------------------

    def record_request(self, endpoint: str, status: int, latency_s: float) -> None:
        self.request_latency.labels(endpoint=endpoint, status=str(status)).observe(
            latency_s
        )
        # the windowed digest replaces the process-lifetime raw-latency
        # buffer: /server/stats p99 is now a SLIDING-window percentile
        self.perf.observe("latency_ms", latency_s * 1000.0)
        with self._lock:
            self._total_requests += 1

    def record_batch(self, size: int, padding_ratio: float = 0.0) -> None:
        self.batch_size.observe(size)
        self.batch_padding_ratio.observe(padding_ratio)
        with self._lock:
            self._batch_sizes.append(size)

    def record_tokens(self, n: int) -> None:
        if n <= 0:
            return
        self.tokens_generated.inc(n)
        now = time.monotonic()
        with self._lock:
            self._token_events.append((now, n))
            cutoff = now - _TOKEN_WINDOW_S
            while self._token_events and self._token_events[0][0] < cutoff:
                self._token_events.popleft()

    def record_inference(self, duration_s: float) -> None:
        self.inference_seconds.inc(duration_s)

    def record_ttft(self, seconds: float, local: bool = True) -> None:
        """``local=False`` (RemoteRunner proxies): the host-observed
        histogram and snapshot average still record, but the windowed
        ``ttft_ms`` digest does NOT — that digest carries locally-SERVED
        requests only. Each member ships its own digest in its
        telemetry frames, so counting a remote-served request here too
        would double-weight it in every fleet-merged view AND poison
        the HealthScorer's local-vs-member latency comparison (a slow
        member would drag the host's own series up with it, hiding
        exactly the gray failure the comparison exists to catch)."""
        self.ttft.observe(seconds)
        if local:
            self.perf.observe("ttft_ms", seconds * 1000.0)
        with self._lock:
            self._ttfts_ms.append(seconds * 1000.0)

    def record_cache(self, hits: int = 0, misses: int = 0, evictions: int = 0) -> None:
        """Record *deltas* of allocator counters since the last call."""
        if hits:
            self.cache_hits.inc(hits)
        if misses:
            self.cache_misses.inc(misses)
        if evictions:
            self.cache_evictions.inc(evictions)
        with self._lock:
            self._cache_hits += hits
            self._cache_misses += misses
            self._cache_evictions += evictions

    def record_prefix_hits(self, hbm: int = 0, host: int = 0) -> None:
        """Page-granular prefix-cache hit deltas by tier (ISSUE 5):
        ``hbm`` pages were shared in place, ``host`` pages were re-seated
        from the host-RAM tier."""
        if hbm:
            self.prefix_hits.labels(tier="hbm").inc(hbm)
        if host:
            self.prefix_hits.labels(tier="host").inc(host)
        with self._lock:
            self._prefix_hits_hbm += hbm
            self._prefix_hits_host += host

    def record_prefix_reload(self, seconds: float) -> None:
        """One host-tier reload (host→HBM re-seat) observed by a
        prefill."""
        self.prefix_reload.observe(seconds)
        with self._lock:
            self._reload_sum += seconds
            self._reload_count += 1

    def record_prefix_fetch(self, outcome: str,
                            seconds: Optional[float] = None,
                            nbytes: int = 0,
                            scope: str = "local") -> None:
        """One peer-to-peer prefix fetch (disagg.PrefixFetcher):
        ``outcome`` is "ok" (pages seated on the cold replica),
        "fallback" (any failure — the request recomputed instead), or
        "delegated" (handed to a fleet member as a mesh fetch hint);
        ``scope`` is "local" (in-process peer), "remote" (a fleet
        member over its KV data channel, serving/fleet_kv.py), or
        "mesh" (member pulls directly from member, fleet_mesh.py)."""
        self.prefix_fetches.labels(outcome=outcome, scope=scope).inc()
        if seconds is not None:
            self.prefix_fetch_latency.observe(seconds)
        if nbytes:
            self.prefix_fetch_bytes.labels(scope=scope).inc(nbytes)
        with self._lock:
            self._prefix_fetches[outcome] = (
                self._prefix_fetches.get(outcome, 0) + 1
            )
            self._prefix_fetch_bytes += nbytes
            if seconds is not None:
                self._fetch_sum += seconds
                self._fetch_count += 1

    def record_prefix_route(self, decision: str) -> None:
        """One cache_aware route decision (dispatcher):
        warm | fetch | recompute."""
        self.prefix_routes.labels(decision=decision).inc()
        with self._lock:
            self._prefix_routes[decision] = (
                self._prefix_routes.get(decision, 0) + 1
            )

    def set_host_tier(self, engine_id: str, nbytes: int, pages: int) -> None:
        """Host-tier occupancy gauges for one engine replica."""
        self.host_tier_bytes_g.labels(engine_id=engine_id).set(nbytes)
        self.host_tier_pages_g.labels(engine_id=engine_id).set(pages)

    def record_kv_payload(self, deltas: Dict[str, int]) -> None:
        """Serialized KV payload byte deltas by encoding kind since the
        last report (runner, engine.payload_byte_counters())."""
        with self._lock:
            for kind, n in deltas.items():
                if n > 0:
                    self.kv_payload_bytes.labels(kind=kind).inc(n)
                    self._payload_bytes[kind] = (
                        self._payload_bytes.get(kind, 0) + n
                    )

    def record_mixed_step(self, prefill_tokens: int = 0,
                          decode_tokens: int = 0) -> None:
        """Mixed-step token deltas since the last report (runner)."""
        if prefill_tokens:
            self.mixed_step_tokens.labels(kind="prefill").inc(
                prefill_tokens
            )
        if decode_tokens:
            self.mixed_step_tokens.labels(kind="decode").inc(decode_tokens)

    def set_mixed_density(self, engine_id: str, density: float) -> None:
        """Rolling mixed-batch density gauge for one engine replica."""
        self.mixed_density.labels(engine_id=engine_id).set(density)

    def record_loop_block(self, steps: int = 0,
                          exits: Optional[Dict[str, int]] = None) -> None:
        """Looped-block deltas since the last report (runner): device
        iterations plus per-reason row exits."""
        if steps:
            self.loop_steps_total.inc(steps)
        for reason, n in (exits or {}).items():
            if n:
                self.loop_exit_total.labels(reason=reason).inc(n)

    def set_queue_depth(self, high: int, normal: int, low: int) -> None:
        self.queue_depth_g.labels(priority="high").set(high)
        self.queue_depth_g.labels(priority="normal").set(normal)
        self.queue_depth_g.labels(priority="low").set(low)
        with self._lock:
            self._queue_depth = high + normal + low

    def request_started(self) -> None:
        with self._lock:
            self._active_requests += 1
        self.active_requests_g.inc()

    def request_finished(self) -> None:
        with self._lock:
            self._active_requests = max(0, self._active_requests - 1)
        self.active_requests_g.dec()

    def set_engine_up(self, engine_id: str, up: bool) -> None:
        self.engine_up.labels(engine_id=engine_id).set(1 if up else 0)

    def record_handoff(self, outcome: str, latency_s: Optional[float] = None,
                       nbytes: int = 0, stall_s: Optional[float] = None,
                       chunks: int = 0, scope: str = "local") -> None:
        """One KV-handoff event (serving/disagg.py): ``outcome`` is
        "ok" (resumed on a decode engine), "fallback" (decoded in place
        on the source), or "retry" (a failed attempt that was retried).
        ``stall_s`` is the decode pause the migrated sequence observed;
        ``chunks`` counts streamed KvChunk frames (0 = monolithic);
        ``scope`` is "local" or "remote" (a cross-host target over the
        fleet KV data channel, serving/fleet_kv.py)."""
        self.handoffs.labels(outcome=outcome).inc()
        if latency_s is not None:
            self.handoff_latency.observe(latency_s)
        if stall_s is not None:
            self.handoff_stall.observe(stall_s)
        if nbytes:
            self.handoff_bytes.inc(nbytes)
        if chunks:
            self.handoff_chunks.labels(scope=scope).inc(chunks)
        with self._lock:
            self._handoffs[outcome] = self._handoffs.get(outcome, 0) + 1
            self._handoff_bytes += nbytes
            self._handoff_chunks += chunks
            if stall_s is not None:
                self._stall_sum += stall_s
                self._stall_count += 1

    def record_engine_restart(self, engine_id: str) -> None:
        """One health-loop restart attempt of ``engine_id`` (counted at
        attempt start — a crash loop shows up even while it never
        succeeds)."""
        self.engine_restarts.labels(engine_id=engine_id).inc()
        with self._lock:
            self._engine_restarts[engine_id] = (
                self._engine_restarts.get(engine_id, 0) + 1
            )

    def record_redispatch(self, outcome: str) -> None:
        """One crash-safe redispatch decision (serving/dispatcher.py):
        ``outcome`` is "ok" (request resubmitted to a healthy replica)
        or "exhausted" (attempt budget or healthy capacity ran out and
        the request failed to its sink)."""
        self.redispatches.labels(outcome=outcome).inc()
        with self._lock:
            self._redispatches[outcome] = (
                self._redispatches.get(outcome, 0) + 1
            )

    def record_expired(self, n: int = 1) -> None:
        """``n`` queued requests expired by the dispatcher sweep
        (resolved to their sinks with the ``queue_timeout`` code)."""
        if n <= 0:
            return
        self.requests_expired.inc(n)
        with self._lock:
            self._requests_expired += n

    def record_shed(self, tenant: str, reason: str) -> None:
        """One request shed at admission (serving/health.py
        AdmissionControl): ``reason`` is "deadline" (the tenant's own
        deadline was blown by the queue-wait estimate) or "brownout"
        (a low-weight tenant shed early). Tenant label bounded like
        the SLO counters (client-chosen strings, counter series are
        forever)."""
        with self._lock:
            if (tenant not in self._shed_tenants
                    and len(self._shed_tenants) >= _SLO_TENANT_CAP):
                tenant = "other"
            self._shed_tenants.add(tenant)
            key = (tenant, reason)
            self._requests_shed[key] = self._requests_shed.get(key, 0) + 1
        self.requests_shed.labels(tenant=tenant, reason=reason).inc()

    def record_health_transition(self, engine_id: str, state: str) -> None:
        """One health-state transition (serving/health.py HealthScorer):
        the per-engine gauge follows the state entered (0/1/2) and the
        transition counts by destination state."""
        rank = {"healthy": 0, "degraded": 1, "ejected": 2}.get(state, 0)
        self.engine_health.labels(engine_id=engine_id).set(rank)
        self.health_transitions.labels(state=state).inc()
        with self._lock:
            self._health_transitions[state] = (
                self._health_transitions.get(state, 0) + 1
            )

    def remove_engine_health(self, engine_id: str) -> None:
        """Drop an unregistered engine's health gauge series (restarted
        fleet members mint fresh proxy ids — the member-gauge policy)."""
        with self._lock:
            try:
                self.engine_health.remove(engine_id)
            except KeyError:
                pass

    def record_breaker_transition(self, state: str) -> None:
        """One KV data-channel circuit-breaker transition
        (serving/health.py CircuitBreaker), by state entered."""
        self.breaker_transitions.labels(state=state).inc()
        with self._lock:
            self._breaker_transitions[state] = (
                self._breaker_transitions.get(state, 0) + 1
            )

    def record_retry_denied(self, site: str) -> None:
        """One retry declined by the shared retry budget
        (serving/health.py RetryBudget)."""
        self.retry_denied.labels(site=site).inc()
        with self._lock:
            self._retry_denied[site] = self._retry_denied.get(site, 0) + 1

    def record_error(self, site: str) -> None:
        """Count an error absorbed at an isolation boundary (``site`` is a
        stable dotted label, e.g. "runner.sink_error")."""
        self.errors_total.labels(site=site).inc()

    def record_trace_drops(self, reason: str, n: int = 1) -> None:
        """``n`` finished spans were lost for ``reason`` (ring |
        exporter | wire) — wired as ``Tracer.on_drop`` by the server so
        the tracer's internal accounting surfaces in /metrics and
        ``/server/stats`` (docs/OBSERVABILITY.md)."""
        if n <= 0:
            return
        self.trace_drops.labels(reason=reason).inc(n)
        with self._lock:
            self._trace_drops[reason] = self._trace_drops.get(reason, 0) + n

    def record_request_phases(self, phases: Dict[str, float],
                              tbt_s: Optional[float] = None) -> None:
        """One finished request's derived phase attribution
        (serving/flightrec.py): seconds per lifecycle phase. The
        queue-wait phase and the request's mean TBT (when it streamed
        more than one token) also feed the windowed digests behind
        ``GET /server/perf``."""
        for phase, seconds in phases.items():
            self.request_phases.labels(phase=phase).observe(seconds)
        self.perf.observe("queue_wait_ms",
                          phases.get("queue_wait", 0.0) * 1000.0)
        if tbt_s is not None:
            self.perf.observe("tbt_ms", tbt_s * 1000.0)
        with self._lock:
            self._phase_requests += 1
            for phase, seconds in phases.items():
                self._phase_sums[phase] = (
                    self._phase_sums.get(phase, 0.0) + seconds
                )

    def record_step_clock(self, engine_id: str, kind: str,
                          dispatches: int = 0, wall_s: float = 0.0,
                          tokens: int = 0, rows: int = 0) -> None:
        """Step-clock deltas for one dispatch kind since the runner's
        last report (docs/OBSERVABILITY.md \"Performance telemetry\").
        Feeds both the Prometheus counters and the /server/perf
        cumulative store (which also rides FleetTelemetry frames)."""
        if dispatches:
            self.step_dispatches.labels(engine_id=engine_id,
                                        kind=kind).inc(dispatches)
        if wall_s:
            self.step_seconds.labels(engine_id=engine_id,
                                     kind=kind).inc(wall_s)
        if tokens:
            self.step_tokens.labels(engine_id=engine_id,
                                    kind=kind).inc(tokens)
        base = f"step.{engine_id}.{kind}"
        if dispatches:
            self.perf.add_counter(f"{base}.dispatches", dispatches)
        if wall_s:
            self.perf.add_counter(f"{base}.wall_s", wall_s)
        if tokens:
            self.perf.add_counter(f"{base}.tokens", tokens)
        if rows:
            self.perf.add_counter(f"{base}.rows", rows)

    def record_step_events(self, engine_id: str,
                           deltas: Dict[str, int]) -> None:
        """Step-loop pressure-event deltas (cache_full / preempt /
        reclaim / retrace) since the runner's last report."""
        for event, n in deltas.items():
            if n <= 0:
                continue
            self.step_events.labels(engine_id=engine_id,
                                    event=event).inc(n)
            self.perf.add_counter(f"events.{engine_id}.{event}", n)

    def observe_step(self, kind: str, seconds: float) -> None:
        """One dispatch's host wall time into the per-kind windowed
        digest (p50/p90/p99 dispatch time at GET /server/perf)."""
        self.perf.observe(f"step_ms.{kind}", seconds * 1000.0)

    def _slo_tenant_label_locked(self, tenant: str) -> str:
        # bounded label set: counter series never go away, so a
        # client-chosen tenant string must not grow /metrics unboundedly
        if tenant in self._slo_counts or len(self._slo_counts) < _SLO_TENANT_CAP:
            return tenant
        return "other"

    def record_slo(self, tenant: str, verdict: str, tokens: int = 0) -> None:
        """One finished request's SLO verdict (flightrec.finish →
        teledigest.slo_verdict): counts + goodput tokens + the windowed
        burn-rate digests."""
        with self._lock:
            tenant = self._slo_tenant_label_locked(tenant)
            per = self._slo_counts.setdefault(tenant, {})
            per[verdict] = per.get(verdict, 0) + 1
            if verdict == "ok" and tokens:
                self._slo_goodput[tenant] = (
                    self._slo_goodput.get(tenant, 0) + tokens
                )
        self.slo_requests.labels(tenant=tenant, verdict=verdict).inc()
        if verdict == "ok" and tokens:
            self.slo_goodput.labels(tenant=tenant).inc(tokens)
        self.perf.count("slo.violated" if verdict == "violated"
                        else "slo.ok")

    def slo_counts(self) -> Tuple[Dict[str, Dict[str, int]],
                                  Dict[str, int]]:
        """(per-tenant verdict counts, per-tenant goodput tokens) for
        the /server/perf slo block."""
        with self._lock:
            return ({t: dict(v) for t, v in self._slo_counts.items()},
                    dict(self._slo_goodput))

    def configure_perf(self, epoch_s: float, window_s: float) -> None:
        """Boot-time digest-ring geometry (config slo.epoch_s /
        slo.window_s); see PerfTelemetry.configure."""
        self.perf.configure(epoch_s, window_s)

    def perf_store(self) -> PerfTelemetry:
        """The windowed-digest store (GET /server/perf assembly)."""
        return self.perf

    def perf_wire(self) -> Dict[str, Any]:
        """The FleetTelemetry frame body (worker heartbeat shipping)."""
        return self.perf.wire()

    def perf_window_s(self) -> float:
        """The configured percentile window (fleet telemetry ingest)."""
        return self.perf.window_s

    def perf_epoch_s(self) -> float:
        """The configured epoch resolution — the fleet ingest drops
        member digests whose epoch_s disagrees (a foreign time unit
        would corrupt the merged windows)."""
        return self.perf.epoch_s

    def record_telemetry_frame(self, outcome: str) -> None:
        """One FleetTelemetry frame: sent | failed (worker side),
        ingested | epoch_mismatch (registry host)."""
        self.fleet_telemetry_frames.labels(outcome=outcome).inc()

    def set_member_telemetry(self, member: str,
                             step_tokens: Dict[str, float],
                             ttft_p99_ms: Optional[float]) -> None:
        """Per-member gauges from an ingested FleetTelemetry frame
        (serving/fleet.py): the fleet_*{member} series."""
        with self._lock:
            # series add/remove under the collector lock (the tenant-
            # gauge discipline): an ingest racing a prune for the same
            # member must not interleave a remove with this set
            self._member_kinds.setdefault(member,
                                          set()).update(step_tokens)
            for kind, tokens in step_tokens.items():
                self.fleet_member_step_tokens.labels(
                    member=member, kind=kind).set(tokens)
            self.fleet_member_ttft_p99.labels(member=member).set(
                ttft_p99_ms or 0.0)

    def remove_member_telemetry(self, member: str) -> None:
        """Drop a pruned member's fleet_member_* series (its last
        values must stop reading as live, serving/fleet.py)."""
        with self._lock:
            for kind in self._member_kinds.pop(member, set()):
                try:
                    self.fleet_member_step_tokens.remove(member, kind)
                except KeyError:
                    pass
            try:
                self.fleet_member_ttft_p99.remove(member)
            except KeyError:
                pass

    def set_fleet_members(self, counts: Dict[str, int]) -> None:
        """Fleet members per registry state (serving/fleet.py): all
        three states are always published so a dead member reads as
        ``fleet_members{state="dead"} 1``, not a missing series."""
        for state in ("alive", "suspect", "dead"):
            self.fleet_members.labels(state=state).set(counts.get(state, 0))

    def record_fleet_heartbeat(self, outcome: str) -> None:
        """One heartbeat ingest: ok | rejoin | dropped."""
        self.fleet_heartbeats.labels(outcome=outcome).inc()
        with self._lock:
            self._fleet_heartbeats[outcome] = (
                self._fleet_heartbeats.get(outcome, 0) + 1
            )

    def record_rerole(self, direction: str) -> None:
        """One dynamic role flip: to_prefill | to_unified."""
        self.fleet_reroles.labels(direction=direction).inc()
        with self._lock:
            self._fleet_reroles[direction] = (
                self._fleet_reroles.get(direction, 0) + 1
            )

    def record_kv_intro(self, outcome: str) -> None:
        """One KvIntro broker send (serving/fleet.py): sent | gone |
        dropped | failed."""
        self.kv_intros.labels(outcome=outcome).inc()
        with self._lock:
            self._kv_intros[outcome] = self._kv_intros.get(outcome, 0) + 1

    def set_registry_role(self, role: str) -> None:
        """Publish this registry's HA role (serving/fleet_ha.py). Both
        role series are written every time (winner 1, loser 0) so a
        flip never leaves two series reading 1 and an absent() alert
        on the primary series stays meaningful."""
        self.registry_role.labels(role="primary").set(
            1 if role == "primary" else 0
        )
        self.registry_role.labels(role="standby").set(
            1 if role == "standby" else 0
        )

    def record_registry_takeover(self, reason: str) -> None:
        """One HA role transition (serving/fleet_ha.py): lease_expired
        = standby promoted | fenced = old primary demoted."""
        self.registry_takeovers.labels(reason=reason).inc()
        with self._lock:
            self._registry_takeovers[reason] = (
                self._registry_takeovers.get(reason, 0) + 1
            )

    def set_registry_epoch(self, epoch: int) -> None:
        """Publish this registry's control epoch (the fencing token)."""
        self.registry_epoch.set(epoch)

    def set_kv_wire_rate(self, src: str, dst: str, rate: float) -> None:
        """Refresh one wire's learned-rate gauge (serving/fleet_mesh.py
        MeshWireRates — the sole writer, which also drives removal, so
        the label set stays bounded by live wires)."""
        with self._lock:
            # series add/remove under the collector lock (tenant-gauge
            # discipline): an observation racing a member prune must
            # not interleave a remove with this set
            self.kv_wire_rate.labels(src=src, dst=dst).set(rate)

    def remove_kv_wire_rate(self, src: str, dst: str) -> None:
        """Drop a dead member's wire series (serving/fleet_mesh.py
        drop_member): its last rate must stop reading as live."""
        with self._lock:
            try:
                self.kv_wire_rate.remove(src, dst)
            except KeyError:
                pass

    def set_tenant_depths(self, depths: Dict[str, int]) -> None:
        """Per-tenant queue occupancy. A tenant that drained since the
        last publish has its series REMOVED (after this call a scrape
        simply doesn't see it) rather than kept at 0 forever — tenant is
        a client-chosen string, so ever-seen bookkeeping would grow the
        gauge write set and the /metrics payload without bound."""
        with self._lock:
            stale = self._tenants_seen - set(depths)
            self._tenants_seen = set(depths)
            # series add/remove under the collector lock: two
            # concurrent publishes must not interleave a remove with
            # the other's set for the same tenant
            for tenant in stale:
                try:
                    self.queue_tenant_depth.remove(tenant)
                except KeyError:
                    pass
            for tenant, depth in depths.items():
                self.queue_tenant_depth.labels(tenant=tenant).set(depth)

    def set_engines_by_role(self, counts: Dict[str, int]) -> None:
        """Per-role replica counts (prefill / decode / unified gauges)."""
        for role in ("prefill", "decode", "unified"):
            self.engines_by_role.labels(role=role).set(counts.get(role, 0))

    def set_speculation(self, engine_id: str, stats: Dict[str, Any]) -> None:
        """Export speculative-decoding gauges (Req 12.4)."""
        self.spec_acceptance.labels(engine_id=engine_id).set(
            stats.get("acceptance_rate", 0.0)
        )
        self.spec_speedup.labels(engine_id=engine_id).set(
            stats.get("estimated_speedup", 1.0)
        )
        self.spec_enabled.labels(engine_id=engine_id).set(
            1 if stats.get("enabled") else 0
        )

    def fleet_counters(self) -> Dict[str, Any]:
        """Heartbeat/rerole counter snapshot for the ``/server/stats``
        fleet block (serving/server.py)."""
        with self._lock:
            return {
                "heartbeats": dict(self._fleet_heartbeats),
                "reroles": dict(self._fleet_reroles),
                "kv_intros": dict(self._kv_intros),
            }

    # -- rendering ---------------------------------------------------------

    def prometheus_text(self) -> bytes:
        return generate_latest(self.registry)

    def snapshot(
        self, engine_statuses: Tuple[EngineStatus, ...] = ()
    ) -> MetricsSnapshot:
        now = time.monotonic()
        with self._lock:
            cutoff = now - _TOKEN_WINDOW_S
            while self._token_events and self._token_events[0][0] < cutoff:
                self._token_events.popleft()
            window_tokens = sum(n for _, n in self._token_events)
            if self._token_events:
                span = max(now - self._token_events[0][0], 1e-3)
            else:
                span = _TOKEN_WINDOW_S
            # sliding-window latency stats from the teledigest store:
            # p99 answers "now", not "since boot" (a quiet hour no
            # longer hides behind a morning burst's tail)
            lat_stats = window_stats(
                self.perf.wire_digest("latency_ms"),
                self.perf.window_s,
            )
            p99 = lat_stats.get("p99", 0.0)
            avg_latency = lat_stats.get("mean", 0.0)
            total_cache = self._cache_hits + self._cache_misses
            # prefix-cache block: allocator counters (incl. evictions,
            # which never reached the snapshot before) + tiered hits +
            # host-tier occupancy summed over replicas
            host_bytes = sum(
                (s.host_tier or {}).get("bytes", 0) for s in engine_statuses
            )
            host_pages = sum(
                (s.host_tier or {}).get("pages", 0) for s in engine_statuses
            )
            cache = {
                "hits": self._cache_hits,
                "misses": self._cache_misses,
                "evictions": self._cache_evictions,
                "prefix_hits": {"hbm": self._prefix_hits_hbm,
                                "host": self._prefix_hits_host},
                "reload_count": self._reload_count,
                "reload_avg_ms": round(
                    self._reload_sum / max(1, self._reload_count) * 1000.0,
                    3,
                ),
                "host_tier_bytes": host_bytes,
                "host_tier_pages": host_pages,
                # fleet prefix sharing (docs/CACHING.md): peer-fetch
                # traffic and the three-way route-decision mix
                "peer_fetch": {
                    **dict(self._prefix_fetches),
                    "bytes": self._prefix_fetch_bytes,
                    "avg_ms": round(
                        self._fetch_sum / max(1, self._fetch_count)
                        * 1000.0, 3,
                    ),
                },
                "route_decisions": dict(self._prefix_routes),
            }
            if self._payload_bytes:
                cache["payload_bytes"] = dict(self._payload_bytes)
            # latent page codec (docs/CACHING.md "Latent KV pages"):
            # rank + bytes saved vs raw, summed over replicas that
            # carry a calibrated codec
            latents = [s.latent for s in engine_statuses if s.latent]
            if latents:
                cache["latent"] = {
                    "rank": latents[0]["rank"],
                    "encoded_bytes": sum(
                        b["encoded_bytes"] for b in latents
                    ),
                    "saved_bytes": sum(b["saved_bytes"] for b in latents),
                }
            resilience = None
            if (self._engine_restarts or self._redispatches
                    or self._requests_expired or self._requests_shed
                    or self._retry_denied or self._breaker_transitions):
                resilience = {
                    "engine_restarts": dict(self._engine_restarts),
                    "redispatched": dict(self._redispatches),
                    "requests_expired": self._requests_expired,
                }
                if self._requests_shed:
                    shed: Dict[str, Dict[str, int]] = {}
                    for (tenant, reason), n in self._requests_shed.items():
                        shed.setdefault(tenant, {})[reason] = n
                    resilience["requests_shed"] = shed
                if self._retry_denied:
                    resilience["retry_denied"] = dict(self._retry_denied)
                if self._breaker_transitions:
                    resilience["breaker_transitions"] = dict(
                        self._breaker_transitions)
            tracing = None
            if self._trace_drops or self._phase_requests:
                tracing = {
                    "spans_dropped": dict(self._trace_drops),
                    "phase_requests": self._phase_requests,
                    "phase_seconds": {
                        k: round(v, 6)
                        for k, v in sorted(self._phase_sums.items())
                    },
                }
            disagg = None
            if self._handoffs or any(
                s.role != "unified" for s in engine_statuses
            ):
                disagg = {
                    "handoffs": dict(self._handoffs),
                    "handoff_bytes": self._handoff_bytes,
                    "handoff_chunks": self._handoff_chunks,
                    "handoff_stall_count": self._stall_count,
                    "handoff_stall_avg_ms": round(
                        self._stall_sum
                        / max(1, self._stall_count) * 1000.0, 3,
                    ),
                }
            return MetricsSnapshot(
                total_requests=self._total_requests,
                active_requests=self._active_requests,
                tokens_per_second=window_tokens / span,
                average_ttft_ms=(
                    sum(self._ttfts_ms) / len(self._ttfts_ms) if self._ttfts_ms else 0.0
                ),
                average_latency_ms=avg_latency,
                p99_latency_ms=p99,
                average_batch_size=(
                    sum(self._batch_sizes) / len(self._batch_sizes)
                    if self._batch_sizes
                    else 0.0
                ),
                cache_hit_rate=self._cache_hits / total_cache if total_cache else 0.0,
                queue_depth=getattr(self, "_queue_depth", 0),
                worker_statuses=engine_statuses,
                uptime_seconds=now - self._started_at,
                disagg=disagg,
                cache=cache,
                resilience=resilience,
                tracing=tracing,
            )
