"""Graceful degradation ladder driven by KV-page memory pressure.

Realizes the reference's spec'd degradation strategy (``design.md:925-943``
[spec]; behavior ``requirements.md:130-134``): as memory pressure rises the
server sheds load in stages instead of falling over —

    < 0.70  NORMAL                     full service
    < 0.80  REDUCED_BATCH_SIZE         admission batches halved
    < 0.90  AGGRESSIVE_CACHE_EVICTION  + cached (refcount-0) prefix pages
                                         evicted down to the low threshold
    < 0.95  REJECT_LOW_PRIORITY        + Priority.LOW requests get 503
    >=0.95  EMERGENCY                  + all new requests get 503

Pressure = max over engines of used_pages/total_pages (each engine owns its
page pool; the most-pressured replica gates the ladder). Transitions are
logged and reversible: when pressure drops, restrictions lift in reverse
order. Pure-logic core (``level_for_pressure``) is separately testable.
"""

from __future__ import annotations

import enum
import logging
import threading
from typing import Optional

from distributed_inference_server_tpu.serving.dispatcher import Dispatcher
from distributed_inference_server_tpu.serving.scheduler import AdaptiveScheduler

logger = logging.getLogger(__name__)


class DegradationLevel(enum.IntEnum):
    NORMAL = 0
    REDUCED_BATCH_SIZE = 1
    AGGRESSIVE_CACHE_EVICTION = 2
    REJECT_LOW_PRIORITY = 3
    EMERGENCY = 4


#: (upper pressure bound, level) — design.md:934-941 [spec]
THRESHOLDS = (0.70, 0.80, 0.90, 0.95)


def level_for_pressure(pressure: float) -> DegradationLevel:
    for i, bound in enumerate(THRESHOLDS):
        if pressure < bound:
            return DegradationLevel(i)
    return DegradationLevel.EMERGENCY


class DegradationController:
    """Evaluates pressure and applies/lifts ladder actions."""

    def __init__(
        self,
        dispatcher: Dispatcher,
        scheduler: AdaptiveScheduler,
        check_interval_s: float = 0.5,
        evict_target_frac: float = 0.70,
        metrics=None,
        burn_high: float = 0.5,
        burn_min_requests: int = 20,
    ):
        """``metrics``/``burn_high``/``burn_min_requests`` arm the SLO
        burn rate as a second escalation input alongside memory
        pressure (serving/health.py settings ``health.slo_burn_high`` /
        ``health.slo_burn_min_requests``; docs/RESILIENCE.md "Gray
        failures and overload"): once the trailing window holds
        ``burn_min_requests`` SLO verdicts, a burn rate at or above
        ``burn_high`` escalates the ladder to at least
        REJECT_LOW_PRIORITY (at or above half of it, to at least
        REDUCED_BATCH_SIZE) — a fleet burning its latency objective
        sheds load even while memory looks fine. The rung lifts as the
        windowed verdicts decay. None = memory-only (the pre-gray
        behavior exactly)."""
        self.dispatcher = dispatcher
        self.scheduler = scheduler
        self.metrics = metrics
        self.burn_high = burn_high
        self.burn_min_requests = burn_min_requests
        self.level = DegradationLevel.NORMAL
        self._interval = check_interval_s
        self._evict_target = evict_target_frac
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- pressure ----------------------------------------------------------

    def memory_pressure(self) -> float:
        worst = 0.0
        for status in self.scheduler.statuses():
            if status.memory_total_pages:
                # live pressure: cached (refcount-0 prefix) pages are
                # reclaimable on demand, so a pool merely full of cache
                # must not climb the ladder (EngineStatus reports raw
                # occupancy with the cached share broken out)
                live = status.memory_used_pages - getattr(
                    status, "pages_cached", 0
                )
                worst = max(worst, live / status.memory_total_pages)
        return worst

    def slo_burn_rate(self) -> Optional[float]:
        """Windowed SLO burn rate (violated / total) from the slo.ok /
        slo.violated count digests (serving/teledigest.py), or None
        while the window holds fewer than ``burn_min_requests``
        verdicts — a handful of early violations must not slam the
        ladder."""
        if self.metrics is None:
            return None
        from distributed_inference_server_tpu.serving.teledigest import (
            windowed_count,
        )

        perf = self.metrics.perf_store()
        ok = windowed_count(perf.wire_digest("slo.ok"), perf.window_s)
        bad = windowed_count(perf.wire_digest("slo.violated"),
                             perf.window_s)
        total = ok + bad
        if total < self.burn_min_requests:
            return None
        return bad / total

    def level_for_burn(self, burn: Optional[float]) -> DegradationLevel:
        """SLO-burn escalation floor: >= burn_high ->
        REJECT_LOW_PRIORITY, >= burn_high/2 -> REDUCED_BATCH_SIZE.
        Burn alone never reaches EMERGENCY — a latency fire sheds load,
        only a memory fire turns everyone away."""
        if burn is None:
            return DegradationLevel.NORMAL
        if burn >= self.burn_high:
            return DegradationLevel.REJECT_LOW_PRIORITY
        if burn >= self.burn_high / 2.0:
            return DegradationLevel.REDUCED_BATCH_SIZE
        return DegradationLevel.NORMAL

    # -- evaluation --------------------------------------------------------

    def evaluate(self, pressure: Optional[float] = None) -> DegradationLevel:
        """One ladder evaluation; applies side effects on level change.
        The level is the MAX of the memory rung and the SLO-burn rung
        (each lifts independently as its signal decays)."""
        pressure = self.memory_pressure() if pressure is None else pressure
        burn = self.slo_burn_rate()
        new = max(level_for_pressure(pressure), self.level_for_burn(burn))
        if new != self.level:
            logger.warning(
                "degradation level %s -> %s (memory pressure %.2f, "
                "slo burn %s)",
                self.level.name, new.name, pressure,
                f"{burn:.2f}" if burn is not None else "n/a",
            )
            self._apply(self.level, new)
            self.level = new
        elif new >= DegradationLevel.AGGRESSIVE_CACHE_EVICTION:
            self._evict(new)  # keep evicting while pressure stays high
        return self.level

    #: mixed-step prefill share per ladder level (engine/engine.py
    #: set_mixed_prefill_frac): under pressure, prompt loading slows
    #: instead of decode slots stalling — decode rows keep their one
    #: token per mixed dispatch at every rung
    MIXED_PREFILL_FRAC = {
        DegradationLevel.NORMAL: 1.0,
        DegradationLevel.REDUCED_BATCH_SIZE: 0.5,
        DegradationLevel.AGGRESSIVE_CACHE_EVICTION: 0.5,
        DegradationLevel.REJECT_LOW_PRIORITY: 0.25,
        DegradationLevel.EMERGENCY: 0.25,
    }

    #: looped-block iteration-cap share per ladder level (engine/engine.py
    #: set_loop_cap_frac): under pressure, run-to-completion blocks give
    #: the host back control sooner so admission and preemption can run —
    #: the same lever MIXED_PREFILL_FRAC pulls on prompt loading
    LOOP_CAP_FRAC = {
        DegradationLevel.NORMAL: 1.0,
        DegradationLevel.REDUCED_BATCH_SIZE: 0.5,
        DegradationLevel.AGGRESSIVE_CACHE_EVICTION: 0.5,
        DegradationLevel.REJECT_LOW_PRIORITY: 0.25,
        DegradationLevel.EMERGENCY: 0.25,
    }

    def _apply(self, old: DegradationLevel, new: DegradationLevel) -> None:
        # batch-size reduction: owns only the divisor — the config itself
        # stays owned by hot-reload, so the two compose
        self.dispatcher.batcher.size_divisor = (
            2 if new >= DegradationLevel.REDUCED_BATCH_SIZE else 1
        )
        # mixed-step prefill share (no-op on engines without the mixed
        # step); restored on the way back down the ladder
        frac = self.MIXED_PREFILL_FRAC[new]
        loop_frac = self.LOOP_CAP_FRAC[new]
        for runner in self.scheduler.engines():
            setter = getattr(runner, "set_mixed_prefill_frac", None)
            if setter is not None:
                setter(frac)
            # looped-block cap (no-op on engines without
            # loop_to_completion); restored on the way back down
            loop_setter = getattr(runner, "set_loop_cap_frac", None)
            if loop_setter is not None:
                loop_setter(loop_frac)
        # cache eviction
        if new >= DegradationLevel.AGGRESSIVE_CACHE_EVICTION > old or (
            new >= DegradationLevel.EMERGENCY > old
        ):
            self._evict(new)
        # admission gates
        self.dispatcher.reject_low_priority = (
            new >= DegradationLevel.REJECT_LOW_PRIORITY
        )
        self.dispatcher.reject_all = new >= DegradationLevel.EMERGENCY

    def _evict(self, level: DegradationLevel) -> None:
        """AGGRESSIVE_CACHE_EVICTION demotes HBM prefix pages to the
        host tier (the tier is exactly the pressure valve for this
        rung); only EMERGENCY — host RAM is the next thing to run out —
        drops the host tier as well."""
        drop_host = level >= DegradationLevel.EMERGENCY
        for runner in self.scheduler.engines():
            runner.evict_cache(self._evict_target,
                               drop_host_tier=drop_host)

    # -- background loop ---------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="degradation", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                self.evaluate()
            except Exception:  # noqa: BLE001 — monitoring must not die
                logger.exception("degradation evaluation failed")
