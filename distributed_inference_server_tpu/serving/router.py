"""Cross-host serving router: the control plane of the multi-host backend.

SURVEY.md §5's two-plane design keeps request traffic off the data plane:
XLA/DCN collectives move tensors between hosts (parallel/distributed.py);
requests move between hosts HERE, at the HTTP boundary — preserving the
reference's scheduler/front-end shape (``design.md:274-307`` [spec]) while
replacing its single-process assumption (``types.rs:10``: WorkerId "local
to a single server instance").

One router process fronts N worker hosts (each running the normal
``python -m distributed_inference_server_tpu`` server on its own
chips/slice). The router:

- routes /generate /chat /embeddings to a backend — round-robin or
  least-loaded (in-flight count through this router), the reference's
  scheduler strategies (``requirements.md:92-98``) applied cross-host;
- passes SSE streams through unbuffered (token latency stays intact);
- health-checks every backend on an interval, evicts unhealthy ones,
  reinstates on recovery, and retries a failed dispatch on the next
  healthy backend (failure detection <5s, Req 7.1-7.3 cross-host);
- aggregates /health and /server/stats across the fleet.

Run: ``python -m distributed_inference_server_tpu.serving.router
--backends http://host-a:8000,http://host-b:8000 --port 8080``.
"""

from __future__ import annotations

import argparse
import asyncio
import itertools
import json
import sys
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import aiohttp
from aiohttp import web


@dataclass
class BackendState:
    base_url: str
    healthy: bool = True
    active: int = 0  # in-flight requests routed through this router
    total: int = 0
    last_error: Optional[str] = None
    last_check: float = 0.0

    def to_dict(self) -> Dict:
        return {
            "backend": self.base_url,
            "healthy": self.healthy,
            "active_requests": self.active,
            "total_routed": self.total,
            "last_error": self.last_error,
        }


@dataclass
class RouterConfig:
    backends: List[str] = field(default_factory=list)
    strategy: str = "least_loaded"  # or "round_robin"
    health_check_interval_s: float = 1.0
    request_timeout_s: float = 300.0
    connect_timeout_s: float = 5.0


class Router:
    """Owns backend state, the health loop, and backend selection."""

    def __init__(self, cfg: RouterConfig):
        if not cfg.backends:
            raise ValueError("router needs at least one backend")
        if cfg.strategy not in ("least_loaded", "round_robin"):
            raise ValueError(
                f"strategy must be least_loaded/round_robin, "
                f"got {cfg.strategy!r}"
            )
        self.cfg = cfg
        self.backends = [
            BackendState(b.rstrip("/")) for b in cfg.backends
        ]
        self._rr = itertools.count()
        self._session: Optional[aiohttp.ClientSession] = None
        self._health_task: Optional[asyncio.Task] = None

    # -- lifecycle ------------------------------------------------------

    async def start(self) -> None:
        self._session = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(
                total=self.cfg.request_timeout_s,
                connect=self.cfg.connect_timeout_s,
            )
        )
        self._health_task = asyncio.create_task(self._health_loop())

    async def close(self) -> None:
        if self._health_task:
            self._health_task.cancel()
            try:
                await self._health_task
            except asyncio.CancelledError:
                pass
        if self._session:
            await self._session.close()

    # -- health ---------------------------------------------------------

    async def _health_loop(self) -> None:
        while True:
            await asyncio.gather(*(self._check(b) for b in self.backends))
            await asyncio.sleep(self.cfg.health_check_interval_s)

    async def _check(self, b: BackendState) -> None:
        try:
            async with self._session.get(
                b.base_url + "/health",
                timeout=aiohttp.ClientTimeout(total=self.cfg.connect_timeout_s),
            ) as resp:
                body = await resp.json()
                b.healthy = resp.status == 200 and body.get("status") == "ok"
                b.last_error = None if b.healthy else f"status {resp.status}"
        except Exception as e:  # noqa: BLE001 — network failure = unhealthy
            b.healthy = False
            b.last_error = str(e)
        b.last_check = time.monotonic()

    # -- selection ------------------------------------------------------

    def pick(self, exclude: Optional[set] = None) -> Optional[BackendState]:
        pool = [
            b for b in self.backends
            if b.healthy and (not exclude or b.base_url not in exclude)
        ]
        if not pool:
            return None
        if self.cfg.strategy == "round_robin":
            return pool[next(self._rr) % len(pool)]
        return min(pool, key=lambda b: b.active)

    @property
    def session(self) -> aiohttp.ClientSession:
        assert self._session is not None, "router not started"
        return self._session


def build_router_app(router: Router) -> web.Application:
    app = web.Application()

    async def _on_startup(app):
        await router.start()

    async def _on_cleanup(app):
        await router.close()

    app.on_startup.append(_on_startup)
    app.on_cleanup.append(_on_cleanup)

    def _unavailable() -> web.Response:
        return web.json_response(
            {"error": {"message": "no healthy backend available",
                       "error_type": "service_unavailable_error",
                       "code": "no_backend"}},
            status=503,
        )

    async def _proxy(request: web.Request, path: str) -> web.StreamResponse:
        try:
            raw = await request.read()
        except Exception:  # noqa: BLE001 — client went away early
            raise web.HTTPBadRequest() from None
        streaming = False
        try:
            streaming = json.loads(raw or b"{}").get("stream") is True
        # peek only decides proxy buffering; the backend parses the body
        # authoritatively and 400s malformed JSON to the client
        except Exception:  # noqa: BLE001  # distlint: ignore[DL004]
            pass
        tried: set = set()
        while True:
            backend = router.pick(exclude=tried)
            if backend is None:
                return _unavailable()
            tried.add(backend.base_url)
            backend.active += 1
            backend.total += 1
            try:
                resp = await router.session.post(
                    backend.base_url + path,
                    data=raw,
                    headers={"Content-Type": "application/json"},
                )
            except Exception as e:  # noqa: BLE001 — connect/dispatch error
                backend.active -= 1
                backend.healthy = False
                backend.last_error = str(e)
                continue  # retry on the next healthy backend
            try:
                if streaming and resp.status == 200:
                    out = web.StreamResponse(
                        status=200,
                        headers={
                            "Content-Type": "text/event-stream",
                            "Cache-Control": "no-cache",
                        },
                    )
                    await out.prepare(request)
                    async for chunk in resp.content.iter_any():
                        await out.write(chunk)
                    await out.write_eof()
                    return out
                body = await resp.read()
                return web.Response(
                    body=body, status=resp.status,
                    content_type=resp.content_type,
                )
            finally:
                backend.active -= 1
                resp.release()

    async def generate(request):
        return await _proxy(request, "/generate")

    async def chat(request):
        return await _proxy(request, "/chat")

    async def embeddings(request):
        return await _proxy(request, "/embeddings")

    # OpenAI-compatible aliases proxy 1:1 — the backend applies the
    # field/wire translation (serving/app.py), the router stays dumb
    async def generate_v1(request):
        return await _proxy(request, "/v1/completions")

    async def chat_v1(request):
        return await _proxy(request, "/v1/chat/completions")

    async def embeddings_v1(request):
        return await _proxy(request, "/v1/embeddings")

    async def health(request: web.Request) -> web.Response:
        healthy = any(b.healthy for b in router.backends)
        return web.json_response(
            {
                "status": "ok" if healthy else "unhealthy",
                "backends": [b.to_dict() for b in router.backends],
            },
            status=200 if healthy else 503,
        )

    async def stats(request: web.Request) -> web.Response:
        async def one(b: BackendState):
            try:
                async with router.session.get(
                    b.base_url + "/server/stats",
                    timeout=aiohttp.ClientTimeout(total=5.0),
                ) as resp:
                    return b.base_url, await resp.json()
            except Exception as e:  # noqa: BLE001 — partial aggregation
                return b.base_url, {"error": str(e)}

        results = dict(await asyncio.gather(
            *(one(b) for b in router.backends)
        ))
        return web.json_response({
            "router": [b.to_dict() for b in router.backends],
            "backends": results,
        })

    app.router.add_post("/generate", generate)
    app.router.add_post("/chat", chat)
    app.router.add_post("/embeddings", embeddings)
    app.router.add_post("/v1/completions", generate_v1)
    app.router.add_post("/v1/chat/completions", chat_v1)
    app.router.add_post("/v1/embeddings", embeddings_v1)
    app.router.add_get("/health", health)
    app.router.add_get("/server/stats", stats)
    return app


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="distributed-inference-server-tpu-router",
        description="Cross-host request router for the TPU serving fleet",
    )
    parser.add_argument(
        "--backends", required=True,
        help="comma-separated backend base URLs "
             "(http://host-a:8000,http://host-b:8000)",
    )
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=8080)
    parser.add_argument(
        "--strategy", default="least_loaded",
        choices=("least_loaded", "round_robin"),
    )
    parser.add_argument("--health-interval", type=float, default=1.0)
    args = parser.parse_args(argv)

    try:
        router = Router(RouterConfig(
            backends=[b for b in args.backends.split(",") if b],
            strategy=args.strategy,
            health_check_interval_s=args.health_interval,
        ))
    except ValueError as e:
        print(f"config error: {e}", file=sys.stderr)
        return 2
    app = build_router_app(router)
    web.run_app(app, host=args.host, port=args.port)
    return 0


if __name__ == "__main__":
    sys.exit(main())
