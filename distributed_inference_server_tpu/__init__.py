"""distributed_inference_server_tpu — a TPU-native distributed LLM inference framework.

A ground-up rebuild of the capability surface of the reference Rust serving stack
(`its-me-ojas/distributed-inference-server`), designed TPU-first:

- Model execution: JAX/XLA via jit + shard_map over explicit device meshes, with
  Pallas/Mosaic kernels for the hot ops (paged attention, RMSNorm, RoPE, dequant-matmul).
- Serving layer: priority queueing with backpressure hysteresis, windowed admission
  batching feeding a continuous-batching engine, adaptive scheduling over engine
  replicas, SSE token streaming, Prometheus metrics, config precedence.
- KV cache: paged, block-allocated in HBM with prefix reuse and LRU page reclamation.
- Parallelism: TP over ICI, expert parallelism, pipeline stages, and context-parallel
  (ring attention) prefill — absent from the reference, first-class here.

Layer map mirrors SURVEY.md §1 (reference layers L1–L5):

- ``core``     — L4 request processing: types, errors, API models, validator, queue.
- ``models``   — JAX model zoo (Llama, Mixtral-style MoE) + weight loading.
- ``ops``      — Pallas TPU kernels and jnp reference ops (attention, norms, sampling).
- ``engine``   — L2/L3: paged KV cache, continuous batching engine, batcher, scheduler.
- ``parallel`` — device meshes, sharding rules, ring attention, collectives.
- ``serving``  — L5/L1: HTTP/SSE front-end, streamer, metrics, config, orchestration.
- ``native``   — C++ runtime components (queue, page allocator) behind ctypes.
- ``utils``    — tracing, logging, misc.
"""

__version__ = "0.1.0"

from distributed_inference_server_tpu.core import (  # noqa: F401
    Priority,
    new_request_id,
)
